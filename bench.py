"""Headline benchmark: Llama2-7B INT4, bs=1 decode latency on one TPU chip.

Mirrors the reference's BenchmarkWrapper metric (BASELINE.md: first-token
latency + mean next-token latency, 1024-128-style run). Weights are random
(quantized on device) — latency does not depend on weight values. Decode is
timed as a jitted K-step lax.scan so tunnel/host overhead never pollutes the
per-token number.

On TPU the run A/Bs the kernel dispatch configurations (Pallas decode
GEMV / generic Pallas tiles / XLA matmul x Pallas / XLA attention — the
on-chip A/B VERDICT r1 asked for) and reports the BEST as the headline,
with every configuration's numbers in the JSON extras.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
`vs_baseline` is speedup vs 30 ms/token, our documented stand-in for the
reference's Intel Max 1550 Llama2-7B INT4 decode latency (the reference
publishes no absolute tables; see BASELINE.md).
"""

from __future__ import annotations

import functools
import json
import os
import subprocess
import sys
import time


def _probe_backend(timeout_s: int = 150) -> bool:
    """Check in a SUBPROCESS that the default JAX backend answers — a
    wedged TPU tunnel otherwise hangs this process forever before any
    timeout can fire. Returns True if the ambient backend is usable."""
    code = ("import jax, jax.numpy as jnp;"
            "print(jax.default_backend());"
            "jnp.ones((2,2)).block_until_ready()")
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=timeout_s,
                           capture_output=True)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


BASELINE_NEXT_TOKEN_MS = 30.0
PROMPT_LEN = 1024
DECODE_STEPS = 64
MAX_SEQ = 2048

# (label, flag overrides) — the dispatch configurations to A/B on TPU
AB_CONFIGS = [
    ("pallas+gemv", dict(matmul_backend="auto", attention_backend="auto",
                         matmul_gemv="auto")),
    ("pallas", dict(matmul_backend="auto", attention_backend="auto",
                    matmul_gemv="off")),
    ("xla-matmul", dict(matmul_backend="xla", attention_backend="auto",
                        matmul_gemv="off")),
    ("xla-attn", dict(matmul_backend="auto", attention_backend="xla",
                      matmul_gemv="auto")),
    ("xla", dict(matmul_backend="xla", attention_backend="xla",
                 matmul_gemv="off")),
]


def main() -> None:
    # probe BEFORE importing jax here: a wedged TPU tunnel would hang this
    # process with no recourse (import-time probing would tax every
    # `import bench` too, so it lives in main())
    if not _probe_backend():
        print("bench: default backend unresponsive; falling back to CPU",
              file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax import lax

    from bigdl_tpu.config import set_flags
    from bigdl_tpu.models import llama as llama_mod
    from bigdl_tpu.utils.testing import (LLAMA2_7B, TINY_LLAMA,
                                         random_llama_params)

    on_tpu = jax.default_backend() == "tpu"
    cfg = LLAMA2_7B if on_tpu else TINY_LLAMA
    max_seq = MAX_SEQ if on_tpu else 256
    prompt_len = PROMPT_LEN if on_tpu else 32
    steps = DECODE_STEPS if on_tpu else 8

    params = random_llama_params(cfg, qtype="sym_int4")
    jax.block_until_ready(params)
    tokens = jnp.ones((1, prompt_len), jnp.int32)

    def bench_config() -> tuple:
        """(first_ms, next_ms) best-of-N under the CURRENT flags."""
        prefill = jax.jit(llama_mod.forward_last_token, static_argnums=1,
                          donate_argnums=3)

        @functools.partial(jax.jit, donate_argnums=(2,))
        def decode_steps(params, tok, cache):
            def step(carry, _):
                tok, cache = carry
                logits, cache = llama_mod.forward(params, cfg,
                                                  tok[:, None], cache)
                nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(
                    jnp.int32)
                return (nxt, cache), None
            (tok, cache), _ = lax.scan(step, (tok, cache), None,
                                       length=steps)
            return tok, cache

        def run():
            cache = llama_mod.new_cache(cfg, 1, max_seq)
            t0 = time.perf_counter()
            logits, cache = prefill(params, cfg, tokens, cache)
            tok = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
            jax.block_until_ready(tok)
            first_ms = (time.perf_counter() - t0) * 1e3
            t1 = time.perf_counter()
            tok, cache = decode_steps(params, tok, cache)
            jax.block_until_ready(tok)
            next_ms = (time.perf_counter() - t1) * 1e3 / steps
            return first_ms, next_ms

        run()  # warmup: compile prefill + decode
        firsts, nexts = [], []
        for _ in range(3):
            f, n = run()
            firsts.append(f)
            nexts.append(n)
        return min(firsts), min(nexts)

    ab_results = {}
    if on_tpu:
        import dataclasses

        from bigdl_tpu.config import flags

        ambient = dataclasses.asdict(flags())   # restore after the loop
        for label, overrides in AB_CONFIGS:
            try:
                set_flags(**overrides)
                jax.clear_caches()
                f_ms, n_ms = bench_config()
                ab_results[label] = {"first_token_ms": round(f_ms, 3),
                                     "next_token_ms": round(n_ms, 3)}
                print(f"bench[{label}]: first {f_ms:.1f}ms "
                      f"next {n_ms:.2f}ms", file=sys.stderr)
            except Exception as e:
                ab_results[label] = {"error": f"{type(e).__name__}: {e}"}
                print(f"bench[{label}]: FAILED {e}", file=sys.stderr)
        set_flags(**ambient)       # keep user env flags authoritative
        ok = {k: v for k, v in ab_results.items() if "next_token_ms" in v}
        if not ok:
            raise SystemExit("bench: every dispatch configuration failed")
        best = min(ok, key=lambda k: ok[k]["next_token_ms"])
        first_ms = ok[best]["first_token_ms"]
        next_ms = ok[best]["next_token_ms"]
    else:
        best = "cpu-fallback"
        first_ms, next_ms = bench_config()

    record = {
        # a CPU fallback must not carry the 7B-on-TPU metric name
        # (VERDICT r2: a reader skimming would see a sub-ms llama2-7B
        # number that does not exist)
        "metric": ("llama2_7b_int4_next_token_latency" if on_tpu
                   else "cpu_fallback_smoke_next_token_latency"),
        "value": round(next_ms, 3),
        "unit": "ms",
        # a tiny-model CPU fallback must not claim a speedup vs the
        # real-hardware baseline
        "vs_baseline": (round(BASELINE_NEXT_TOKEN_MS / next_ms, 3)
                        if on_tpu else 0.0),
        "valid": bool(on_tpu),
        "first_token_ms": round(first_ms, 3),
        "prompt_len": prompt_len,
        "decode_steps": steps,
        "backend": jax.default_backend(),
        "model": "llama2-7b" if on_tpu else "tiny-llama(cpu-fallback)",
        "qtype": "sym_int4",
        "best_config": best,
        "ab": ab_results,
    }
    if on_tpu:
        record.update(_efficiency(cfg, params, prompt_len, steps, max_seq,
                                  first_ms, next_ms))
    print(json.dumps(record))


def _efficiency(cfg, params, prompt_len: int, steps: int, max_seq: int,
                first_ms: float, next_ms: float) -> dict:
    """MFU + HBM-roofline utilization (VERDICT r2 #2).

    Decode on one chip is HBM-bandwidth-bound: every token reads the whole
    packed weight set plus the live KV slice, so the honest efficiency
    number is bytes-moved / (latency x peak-BW). Prefill is compute-bound,
    so its number is model FLOPs / (latency x peak-FLOPs) — classic MFU.
    Chip peaks are v5e datasheet values, overridable for other chips.
    """
    import jax

    peak_tflops = float(os.environ.get("BIGDL_TPU_PEAK_BF16_TFLOPS", "197"))
    peak_gbps = float(os.environ.get("BIGDL_TPU_PEAK_HBM_GBPS", "819"))

    d = cfg.hidden_size
    l_ = cfg.num_hidden_layers
    ff = cfg.intermediate_size
    v = cfg.vocab_size
    h, hkv, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.hd
    # matmul FLOPs per token (fwd): qkvo + gated mlp + lm_head
    proj = 2 * (d * h * hd + 2 * d * hkv * hd + h * hd * d)
    mlp = 2 * 3 * d * ff
    flops_tok = l_ * (proj + mlp) + 2 * d * v
    # attention FLOPs per token at cache length S: 2 matmuls over S keys
    s_mid = prompt_len + steps // 2
    attn_tok = l_ * 2 * 2 * h * hd * s_mid

    # bytes read per decode token: all packed weights + live KV slice
    weight_bytes = sum(a.nbytes for a in jax.tree_util.tree_leaves(params))
    kv_elt_bytes = 2  # bf16 cache
    kv_bytes = 2 * l_ * s_mid * hkv * hd * kv_elt_bytes
    ideal_decode_ms = (weight_bytes + kv_bytes) / (peak_gbps * 1e9) * 1e3

    # prefill MFU over the whole prompt
    prefill_flops = prompt_len * flops_tok + l_ * 2 * 2 * h * hd * (
        prompt_len * prompt_len // 2)
    prefill_mfu = prefill_flops / (first_ms / 1e3) / (peak_tflops * 1e12)

    decode_mfu = (flops_tok + attn_tok) / (next_ms / 1e3) / (
        peak_tflops * 1e12)
    return {
        "decode_hbm_roofline_util": round(ideal_decode_ms / next_ms, 4),
        "decode_ideal_ms": round(ideal_decode_ms, 6),
        "decode_mfu": round(decode_mfu, 5),
        "prefill_mfu": round(prefill_mfu, 4),
        "weight_bytes": int(weight_bytes),
        "peak_bf16_tflops": peak_tflops,
        "peak_hbm_gbps": peak_gbps,
    }


if __name__ == "__main__":
    main()
