"""Headline benchmark: Llama2-7B INT4, bs=1 decode latency on one TPU chip.

Mirrors the reference's BenchmarkWrapper metric (BASELINE.md: first-token
latency + mean next-token latency, 1024-128-style run). Weights are random
(quantized on device) — latency does not depend on weight values. Decode is
timed as a jitted K-step lax.scan so tunnel/host overhead never pollutes the
per-token number.

On TPU the run A/Bs the kernel dispatch configurations (Pallas decode
GEMV / generic Pallas tiles / XLA matmul x Pallas / XLA attention — the
on-chip A/B VERDICT r1 asked for) and reports the BEST as the headline,
with every configuration's numbers in the JSON extras.

Each configuration runs in its OWN subprocess: the first live-chip session
(round 3) showed a kernel runtime fault can poison the axon tunnel's whole
client — block_until_ready stops blocking and every later timing in the
process reads sub-millisecond. Isolation gives each config a fresh runtime
connection, and physics floors (HBM roofline for decode, MXU peak for
prefill) reject timings no hardware could produce, recording them as
`invalid` instead of as results.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
`vs_baseline` is speedup vs 30 ms/token, our documented stand-in for the
reference's Intel Max 1550 Llama2-7B INT4 decode latency (the reference
publishes no absolute tables; see BASELINE.md).
"""

from __future__ import annotations

import functools
import json
import os
import re
import subprocess
import sys
import time


def _last_tb_frame(stderr: str) -> str:
    """Last real traceback frame in a lane's stderr. Lanes run with
    JAX_TRACEBACK_FILTERING=off, so this names the actual crash site
    instead of jax's re-raise shim — the one line that makes an
    erroring A/B lane diagnosable from the bench JSON alone."""
    frames = re.findall(r'File "[^"]*", line \d+, in \S+', stderr or "")
    return frames[-1] if frames else ""


def _exception_head(stderr: str) -> str:
    """The terminal ``SomeError: message`` line in a lane's stderr.
    The r05 window lost three lanes to identical 300-char stderr TAILS
    (all runtime-shutdown noise); the exception head line is what
    actually differs between failure modes, so it goes into the lane's
    JSON error string alongside the crash frame and the tail."""
    heads = [ln for ln in (stderr or "").splitlines()
             if re.match(r"^[A-Za-z_.]+(Error|Exception|Fault|Exit)\b[:(]",
                         ln)]
    return heads[-1][:200] if heads else ""


def _persist_lane_log(run_dir: str, label: str, stdout, stderr):
    """Write a lane's FULL stdout+stderr next to the bench results and
    return the path (referenced from the lane's JSON entry) — the
    in-JSON error string only carries a tail."""
    path = os.path.join(
        run_dir, "lane_%s.log" % re.sub(r"[^\w.+-]", "_", str(label)))
    try:
        with open(path, "w") as f:
            f.write("=== stdout ===\n")
            f.write(stdout or "")
            f.write("\n=== stderr ===\n")
            f.write(stderr or "")
        return path
    except OSError:
        return None


def _probe_backend(timeout_s: int = 150):
    """Check in a SUBPROCESS that the default JAX backend answers — a
    wedged TPU tunnel otherwise hangs this process forever before any
    timeout can fire. Returns the backend name (e.g. "tpu", "cpu") if
    usable, else None. Probing out-of-process also keeps the PARENT from
    initializing the TPU runtime, which on exclusive-access hosts would
    starve the per-config subprocesses that do the real work."""
    code = ("import jax, jax.numpy as jnp;"
            "jnp.ones((2,2)).block_until_ready();"
            "print(jax.default_backend())")
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=timeout_s,
                           capture_output=True, text=True)
        if r.returncode != 0:
            return None
        out = r.stdout.strip().splitlines()
        return out[-1] if out else None
    except subprocess.TimeoutExpired:
        return None


BASELINE_NEXT_TOKEN_MS = 30.0
PROMPT_LEN = 1024
DECODE_STEPS = 64
MAX_SEQ = 2048
CONFIG_TIMEOUT_S = int(os.environ.get("BENCH_CONFIG_TIMEOUT_S", "900"))
RUN_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "tpu_runs")

# (label, flag overrides) — the dispatch configurations to A/B on TPU.
# "pallas+gemv" is the shipped default: Pallas kernels at decode-class M,
# XLA matmul above matmul_pallas_max_m (prefill). "pallas-all-m" forces
# the dequant kernel at every M to re-check that threshold on chip.
AB_CONFIGS = [
    # ordered most-informative-first: the tunnel can die mid-run, and
    # every completed config is persisted to tpu_runs/ immediately
    ("pallas+gemv", dict(matmul_backend="auto", attention_backend="auto",
                         matmul_gemv="auto")),
    ("gemv-mxuflat", dict(matmul_backend="auto", attention_backend="auto",
                          matmul_gemv="mxuflat")),
    ("gemv-mxu8", dict(matmul_backend="auto", attention_backend="auto",
                       matmul_gemv="mxu8")),
    ("no-mxu-layout", dict(matmul_backend="auto", attention_backend="auto",
                           matmul_gemv="auto", mxu_layout="off")),
    ("gemv-fold", dict(matmul_backend="auto", attention_backend="auto",
                       matmul_gemv="fold", mxu_layout="off")),
    ("xla-matmul", dict(matmul_backend="xla", attention_backend="auto",
                        matmul_gemv="off")),
    ("no-merge", dict(matmul_backend="auto", attention_backend="auto",
                      matmul_gemv="auto", _merged=False)),
    ("xla-attn", dict(matmul_backend="auto", attention_backend="xla",
                      matmul_gemv="auto")),
    ("pallas", dict(matmul_backend="auto", attention_backend="auto",
                    matmul_gemv="off")),
    ("pallas-all-m", dict(matmul_backend="auto", attention_backend="auto",
                          matmul_gemv="auto",
                          matmul_pallas_max_m=1 << 30)),
    ("xla", dict(matmul_backend="xla", attention_backend="xla",
                 matmul_gemv="off")),
    # experiments beyond the dispatch matrix (keys starting with "_" are
    # bench_config parameters, not flags). int8: the in-kernel int4
    # dequant is VPU-bound (see matmul_pallas_max_m docstring) — int8's
    # cheaper unpack may decode FASTER despite 2x the HBM bytes. fp8-kv:
    # same int4 model with the e5m2 KV cache (halves KV traffic and
    # exercises the fp8 decode-attention kernel on chip).
    ("int8-weights", dict(matmul_backend="auto", attention_backend="auto",
                          matmul_gemv="auto", _qtype="sym_int8")),
    ("fp8-kv", dict(matmul_backend="auto", attention_backend="auto",
                    matmul_gemv="auto", _kv_cache_dtype="fp8_e5m2")),
]

# `--kv-cache-dtype a,b,...` sweep rows (not part of the default A/B
# matrix): each dtype runs the shipped dispatch flags with only the KV
# storage dtype varied, so the per-dtype TPOT/kv_cache_bytes deltas are
# attributable to the cache alone
KV_SWEEP_FLAGS = dict(matmul_backend="auto", attention_backend="auto",
                      matmul_gemv="auto")


def bench_config(qtype: str = "sym_int4", kv_quantized: bool = False,
                 merged: bool = True,
                 kv_cache_dtype: "str | None" = None) -> dict:
    """Time prefill + decode under the AMBIENT flags; returns raw numbers.

    Runs on whatever jax.default_backend() answers. The final token is
    transferred to host and its value recorded — a poisoned device buffer
    (crashed runtime) either raises here or yields timings below the
    physics floors the parent checks."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from bigdl_tpu.config import enable_compilation_cache
    from bigdl_tpu.models import llama as llama_mod
    from bigdl_tpu.ops.kvcache import kv_cache_bytes, resolve_kv_cache_dtype
    from bigdl_tpu.utils.testing import (LLAMA2_7B, TINY_LLAMA,
                                         random_llama_params)

    kv_dtype = resolve_kv_cache_dtype(
        kv_cache_dtype if kv_cache_dtype is not None else kv_quantized)

    # compiled 7B programs persist across subprocesses AND tunnel windows
    enable_compilation_cache()

    def phase(msg: str) -> None:
        # progress breadcrumbs on stderr: a config timeout must say WHERE
        # it wedged (compile vs first execution vs steady-state timing)
        print(f"bench-phase[{time.strftime('%H:%M:%S')}]: {msg}",
              file=sys.stderr, flush=True)

    on_tpu = jax.default_backend() == "tpu"
    cfg = LLAMA2_7B if on_tpu else TINY_LLAMA
    max_seq = MAX_SEQ if on_tpu else 256
    prompt_len = PROMPT_LEN if on_tpu else 32
    steps = DECODE_STEPS if on_tpu else 8

    from bigdl_tpu.ops.quant import prepack_tree

    if on_tpu and os.environ.get("BENCH_CANARY", "1") != "0":
        # tiny-geometry run under the SAME dispatch flags: if the 7B run
        # wedges but this passes, the fault is geometry-dependent — the
        # single most useful bit for off-chip debugging (r4's runtime
        # death was only ever seen at 7B shapes)
        phase("canary: tiny-geometry forward under ambient flags")
        tp = random_llama_params(TINY_LLAMA, qtype=qtype)
        if merged:
            tp = llama_mod.merge_projections(tp, TINY_LLAMA)
        tp, _ = prepack_tree(tp)
        tcache = llama_mod.new_cache(TINY_LLAMA, 1, 64,
                                     quantized=kv_dtype)
        tlg, tcache = jax.jit(llama_mod.forward, static_argnums=1)(
            tp, TINY_LLAMA, jnp.ones((1, 8), jnp.int32), tcache)
        np.asarray(tlg)
        phase("canary ok")
        del tp, tcache, tlg

    phase(f"generating {qtype} params")
    params = random_llama_params(cfg, qtype=qtype)
    if merged:
        # merged QKV + gate/up — the shipped from_pretrained default
        params = llama_mod.merge_projections(params, cfg)
    # the shipped from_pretrained load-time prepack (int4-dtype MXU
    # weight re-layout) — ONE implementation so bench measures exactly
    # what the loader does; the report rides along in the bench JSON
    t_pack = time.perf_counter()
    params, prepack_report = prepack_tree(params)
    jax.block_until_ready(params)
    prepack_report["prepack_ms"] = round(
        (time.perf_counter() - t_pack) * 1e3, 1)
    phase("params ready on device")
    tokens = jnp.ones((1, prompt_len), jnp.int32)

    prefill = jax.jit(llama_mod.forward_last_token, static_argnums=1,
                      donate_argnums=3)

    def make_decode(n_steps: int):
        @functools.partial(jax.jit, donate_argnums=(2,))
        def decode_steps(params, tok, cache):
            def step(carry, _):
                tok, cache = carry
                logits, cache = llama_mod.forward(params, cfg,
                                                  tok[:, None], cache)
                nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(
                    jnp.int32)
                return (nxt, cache), None
            (tok, cache), _ = lax.scan(step, (tok, cache), None,
                                       length=n_steps)
            return tok, cache
        return decode_steps

    # Decode latency is the DIFFERENCE of two in-jit loop counts, each
    # ended with a forced host readback: on the tunneled TPU a dispatch
    # costs ~1-2ms RTT, a readback ~70ms fixed, and block_until_ready
    # alone under-reports (it can return before the computation ran).
    # Differencing cancels every fixed cost and leaves pure per-token
    # time; it also zeroes out when a crashed runtime returns poisoned
    # buffers instantly, which the parent's physics floor then rejects.
    short, long_ = max(steps // 4, 1), steps
    dec_short, dec_long = make_decode(short), make_decode(long_)

    def run(decode_fn, tag=None):
        cache = llama_mod.new_cache(cfg, 1, max_seq,
                                    quantized=kv_dtype)
        t0 = time.perf_counter()
        logits, cache = prefill(params, cfg, tokens, cache)
        tok = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
        np.asarray(tok)                          # forced readback
        first_ms = (time.perf_counter() - t0) * 1e3
        if tag:
            phase(f"{tag}: prefill done ({first_ms:.0f}ms)")
        t1 = time.perf_counter()
        tok, cache = decode_fn(params, tok, cache)
        final = int(np.asarray(tok)[0])          # forced readback
        dec_ms = (time.perf_counter() - t1) * 1e3
        if tag:
            phase(f"{tag}: decode done ({dec_ms:.0f}ms)")
        return first_ms, dec_ms, final

    run(dec_short, tag="warmup-short")   # warmup: compile prefill + short
    run(dec_long, tag="warmup-long")     # warmup: compile long
    firsts, shorts, longs, final = [], [], [], 0
    for it in range(3):
        f, dm, final = run(dec_short)
        firsts.append(f)
        shorts.append(dm)
        f, dm, final = run(dec_long)
        firsts.append(f)
        longs.append(dm)
        phase(f"timing iter {it + 1}/3 done")
    next_ms = (min(longs) - min(shorts)) / (long_ - short)
    if next_ms <= 0:
        # differencing lost to dispatch noise (tiny CPU-fallback model);
        # the undifferenced long run still bounds per-token time
        next_ms = min(longs) / long_
    # fixed per-call overhead (dispatch RTT + readback) estimated from
    # the short run; subtract it from first-token so the number reflects
    # the chip, not the tunnel (raw kept alongside)
    overhead_ms = max(min(shorts) - short * next_ms, 0.0)
    first_raw = min(firsts)
    from bigdl_tpu.ops.quant import QTensor

    # QTensor.nbytes owns the int4-packing byte accounting; plain
    # arrays (norms, rope tables) report their own nbytes
    weight_bytes = sum(
        leaf.nbytes for leaf in jax.tree_util.tree_leaves(
            params, is_leaf=lambda x: isinstance(x, QTensor)))
    # observability snapshot rides along in the JSON: TTFT/TPOT sample
    # distributions from this run's timing iters plus whatever the
    # default registry accumulated (kernel probe outcomes, speculative
    # acceptance when a spec bench ran in-process)
    from bigdl_tpu.observability.metrics import (MetricsRegistry,
                                                 default_registry)

    obs = MetricsRegistry()
    ttft_h = obs.histogram("bigdl_tpu_ttft_seconds",
                           "Prefill + first token wall time.")
    for f in firsts:
        ttft_h.observe(f / 1e3)
    obs.histogram("bigdl_tpu_tpot_seconds",
                  "Differenced per-token decode time.").observe(
        next_ms / 1e3)
    obs_summary = obs.summary()
    obs_summary.update(default_registry().summary())
    from bigdl_tpu.observability.compile_watch import compile_table
    from bigdl_tpu.observability.memory import default_ledger, memory_report

    kv_bytes = kv_cache_bytes(jax.eval_shape(
        lambda: llama_mod.new_cache(cfg, 1, max_seq,
                                    quantized=kv_dtype)))
    ledger = default_ledger()
    ledger.register("weights", "bench_model", int(weight_bytes),
                    qtype=qtype)
    ledger.register("kv_cache", "bench_cache", kv_bytes["total"],
                    dtype=kv_dtype)

    # quality block (quantization-error observability): the per-format
    # golden NLL budget from ACCURACY.md (shrink-only ratcheted by
    # tools/bench_diff.py as nll_delta_vs_bf16) plus one measured
    # weight-error sample — a fixed-seed matrix quantized at the bench
    # qtype and scored by the same weight_error_stats the load-time
    # attribution uses, so a kernel-level encode regression moves a
    # bench number even without a checkpoint to convert
    from bigdl_tpu.observability.quality import (golden_nll_allowance,
                                                 weight_error_stats)
    from bigdl_tpu.ops.quant import (FLOAT_QTYPES, dequantize_linear,
                                     quantize_linear)

    q_sample = None
    if qtype not in FLOAT_QTYPES:
        try:
            w_ref = np.random.default_rng(0).standard_normal(
                (256, 256)).astype(np.float32)
            qt = quantize_linear(jnp.asarray(w_ref), qtype)
            q_sample = weight_error_stats(
                w_ref, np.asarray(dequantize_linear(qt, jnp.float32)))
        except Exception:
            q_sample = None     # telemetry, never fails the bench
    quality_block = {
        "qtype": qtype,
        "nll_delta_vs_bf16": round(golden_nll_allowance(qtype), 6),
        "weight_error_sample": q_sample,
    }

    return {
        "quality": quality_block,
        "observability": obs_summary,
        # static ledger totals + live device stats (TPU runs) + peak
        # jit scratch — tools/bench_diff.py compares the headline
        # scalars under --max-hbm-regress-pct
        "memory": memory_report(ledger),
        # per-executable compile counts/times for this process — a bench
        # row whose compile table grew between runs recompiled something
        "jit_compile_table": compile_table(),
        # load-time weight prepack report (ISSUE 14c): mode, QTensor
        # counts, bytes re-laid-out, and the one-time transform cost —
        # tools/bench_diff.py treats the block as informational
        "prepack": prepack_report,
        "first_token_ms": round(max(first_raw - overhead_ms, 0.0), 3),
        "first_token_ms_raw": round(first_raw, 3),
        "next_token_ms": round(next_ms, 3),
        "tunnel_overhead_ms": round(overhead_ms, 3),
        "final_token": final,
        "weight_bytes": int(weight_bytes),
        "backend": jax.default_backend(),
        "on_tpu": on_tpu,
        "prompt_len": prompt_len,
        "decode_steps": steps,
        "qtype": qtype,
        "kv_cache_dtype": kv_dtype,
        "kv_quantized": kv_dtype != "bf16",
        # logical cache footprint (eval_shape: no second allocation);
        # int4 counted at two codes per byte
        "kv_cache_bytes": kv_bytes,
    }


# single-sourced roofline math (bigdl_tpu/observability/roofline.py):
# the same functions drive the physics floors below, the efficiency
# block, bench_qlora/bench_serving/bench_speculative AND the serving
# engine's live bigdl_tpu_roofline_util gauges —
# tests/test_perf_observability.py asserts bench output is
# value-identical to the model on the r05 fixture numbers, so the
# offline bench and the live gauges cannot silently drift
from bigdl_tpu.observability.roofline import (  # noqa: E402
    chip_peaks, model_flops_per_token)
from bigdl_tpu.observability import roofline as _roofline  # noqa: E402


def _floors(cfg, weight_bytes: int, prompt_len: int) -> tuple:
    """(decode_floor_ms, prefill_floor_ms): timings below these are
    physically impossible on one chip and mean the runtime did not
    actually execute (chip peaks: v5e datasheet, env-overridable)."""
    peak_tflops, peak_gbps = chip_peaks()
    # decode reads at least the packed weights once per token
    decode_floor = weight_bytes / (peak_gbps * 1e9) * 1e3 * 0.8
    prefill_floor = (prompt_len * model_flops_per_token(cfg)) / (
        peak_tflops * 1e12) * 1e3 * 0.5
    return decode_floor, prefill_floor


# known environment-limitation signatures -> skip class. A lane whose
# crash matches one of these is NOT a code fault: it cannot run in this
# environment (tunnel backends without pallas lowering, runtimes
# without fp8, HBM too small for a forced all-M kernel). Such lanes
# become structured {"skip": reason, "skip_class": cls} records instead
# of bare errors: they don't demote in _ordered_configs, don't flip the
# run's exit code, and keep enough detail to revive on a capable chip.
SKIP_SIGNATURES = (
    ("Evaluation rule for 'program_id' not implemented",
     "pallas-lowering-unsupported"),
    ("Mosaic", "pallas-lowering-unsupported"),
    ("MOSAIC", "pallas-lowering-unsupported"),
    ("float8", "fp8-unsupported-backend"),
    ("f8E5M2", "fp8-unsupported-backend"),
    ("f8E4M3", "fp8-unsupported-backend"),
    ("RESOURCE_EXHAUSTED", "hbm-oom"),
    ("Out of memory", "hbm-oom"),
)


def _classify_skip(text: str) -> "str | None":
    """Skip class for a crash message, or None when it is a real
    fault that must keep failing the run."""
    for needle, cls in SKIP_SIGNATURES:
        if needle in text:
            return cls
    return None


def _one_config(label: str) -> None:
    """Subprocess entry: run ONE dispatch configuration, print JSON.

    `kv-<dtype>` labels are the --kv-cache-dtype sweep rows: shipped
    dispatch flags, only the KV storage dtype varied.

    A crash matching SKIP_SIGNATURES exits 0 with a structured skip
    record: the parent must be able to tell "this lane cannot run
    here" from "this lane found a bug"."""
    cfgs = dict(AB_CONFIGS)
    if label in cfgs:
        overrides = dict(cfgs[label])
    elif label.startswith("kv-"):
        overrides = dict(KV_SWEEP_FLAGS, _kv_cache_dtype=label[3:])
    else:
        raise KeyError(label)
    qtype = overrides.pop("_qtype", "sym_int4")
    kv_quantized = overrides.pop("_kv_quantized", False)
    kv_cache_dtype = overrides.pop("_kv_cache_dtype", None)
    merged = overrides.pop("_merged", True)
    from bigdl_tpu.config import set_flags

    set_flags(**overrides)
    try:
        rec = bench_config(qtype=qtype, kv_quantized=kv_quantized,
                           merged=merged, kv_cache_dtype=kv_cache_dtype)
    except Exception as e:
        import traceback

        detail = f"{type(e).__name__}: {e}"
        cls = _classify_skip(detail) or _classify_skip(
            traceback.format_exc())
        if cls is None:
            raise
        traceback.print_exc()
        print(json.dumps({"skip": detail[:300], "skip_class": cls,
                          "config": label}))
        return
    print(json.dumps(rec))


def _latest_valid_onchip_record(run_dir: str | None = None) -> dict | None:
    """Newest tpu_runs/bench_*.json whose record says valid:true.

    VERDICT r3 #8: when the tunnel is down at round end, BENCH_r*.json
    used to show only a CPU smoke number while a same-day valid on-chip
    record sat in tpu_runs/ — embed that record (marked cached) so the
    benchmark output always carries the last real silicon evidence."""
    import glob

    if run_dir is None:
        run_dir = RUN_DIR
    best_name, best_rec = None, None
    for path in sorted(glob.glob(os.path.join(run_dir, "bench_*.json"))):
        try:
            with open(path) as f:
                rec = json.loads(f.read().strip().splitlines()[-1])
        except (OSError, json.JSONDecodeError, IndexError):
            continue
        # this benchmark's metric only — qlora/serving records share the
        # tpu_runs/ dir and must never become the latency headline; and
        # never a record that is itself a cached re-emission (the watcher
        # saves bench stdout back into tpu_runs/, so without this a
        # failed round's cached copy would become "the newest record" and
        # provenance would chain through copies of copies)
        if rec.get("valid") and not rec.get("cached") \
                and rec.get("backend") == "tpu" \
                and rec.get("unit") == "ms" \
                and rec.get("metric") == "llama2_7b_int4_next_token_latency":
            best_name, best_rec = os.path.basename(path), rec
    if best_rec is None:
        return None
    best_rec["cached"] = True
    best_rec["cached_from"] = best_name
    return best_rec


def _ordered_configs(run_dir: str) -> list:
    """AB_CONFIGS, with configs that timed out / errored in the most
    recent partial record demoted to the END of the order.

    The 08:03 window lesson: a wedge-prone config at the front of the
    order costs the whole window (the tunnel dies with it). Demotion
    self-heals the ordering across windows — a repeat offender still
    runs, but only after every healthy config has its number on disk."""
    import glob

    parts = sorted(glob.glob(os.path.join(run_dir, "bench_partial_*.jsonl")))
    bad: set = set()
    starved: set = set()
    # newest window with ATTRIBUTABLE evidence wins: a window where the
    # tunnel died (only no_fault records) says nothing about config
    # health and must not erase an earlier window's demotion memory
    for path in reversed(parts):
        faults, owed, attributable = set(), set(), False
        try:
            with open(path) as f:
                for ln in f:
                    rec = json.loads(ln)
                    if "error" in rec and not rec.get("no_fault"):
                        # fast fails (clean exception within seconds)
                        # are attributable but not wedge-capable — run
                        # them in normal order so a fix lands same-day
                        if not rec.get("fast_fail"):
                            faults.add(rec.get("config"))
                        attributable = True
                    elif rec.get("skip_class") == "budget-exhausted":
                        # the window ran out of budget before this
                        # config: it is OWED a slot at the front next
                        # window, else the tail of the matrix starves
                        # forever
                        owed.add(rec.get("config"))
                        attributable = True
                    elif "next_token_ms" in rec:
                        attributable = True
        except (OSError, json.JSONDecodeError):
            continue
        if attributable:
            bad = faults
            starved = owed - faults
            break
    if not bad and not starved:
        return list(AB_CONFIGS)
    first = [c for c in AB_CONFIGS if c[0] in starved]
    healthy = [c for c in AB_CONFIGS
               if c[0] not in bad and c[0] not in starved]
    demoted = [c for c in AB_CONFIGS if c[0] in bad]
    if first:
        print(f"bench: promoting {[c[0] for c in first]} (budget-"
              "starved last window) to the front", file=sys.stderr)
    if demoted:
        print(f"bench: demoting {[c[0] for c in demoted]} (failed last "
              f"window) behind {len(healthy)} healthy configs",
              file=sys.stderr)
    return first + healthy + demoted


def _acquire_single_instance(max_wait_s: int = 2700):
    """One full bench run at a time: the driver's round-end invocation
    must not fight the watcher's in-flight window run for the chip (and
    the libtpu lockfile). Blocks up to max_wait_s for the other run to
    finish — its compiles land in the shared cache, so waiting is
    cheaper than contending — then proceeds regardless. Returns the
    held file object (kept open for the process lifetime) or None."""
    import fcntl

    os.makedirs(RUN_DIR, exist_ok=True)
    f = open(os.path.join(RUN_DIR, "bench.lock"), "w")
    deadline = time.time() + max_wait_s
    while True:
        try:
            fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)
            return f
        except OSError:
            if time.time() > deadline:
                print("bench: another bench run still holds the lock "
                      f"after {max_wait_s}s — proceeding anyway",
                      file=sys.stderr)
                return None
            print("bench: waiting for an in-flight bench run to finish",
                  file=sys.stderr)
            time.sleep(min(30.0, max(1.0, deadline - time.time())))


def main(kv_sweep: "list[str] | None" = None) -> None:
    _lock = _acquire_single_instance()
    # probe BEFORE importing jax here: a wedged TPU tunnel would hang this
    # process with no recourse (import-time probing would tax every
    # `import bench` too, so it lives in main())
    backend = _probe_backend()
    if backend is None:
        print("bench: default backend unresponsive; falling back to CPU",
              file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"
        backend = "cpu"

    # the probed name, NOT jax.default_backend(): the parent must never
    # initialize the TPU runtime — on exclusive-access hosts that would
    # starve the per-config subprocesses that do the real work
    on_tpu = backend == "tpu"

    # one record schema for every path; each path overrides what differs
    record = {
        "metric": "llama2_7b_int4_next_token_latency",
        "value": None,
        "unit": "ms",
        "vs_baseline": 0.0,
        "valid": False,
        "prompt_len": PROMPT_LEN,
        "decode_steps": DECODE_STEPS,
        "backend": backend,
        "model": "llama2-7b",
        "qtype": "sym_int4",
        "best_config": None,
        "ab": {},
    }

    if not on_tpu:
        import jax

        if os.environ.get("JAX_PLATFORMS") == "cpu":
            jax.config.update("jax_platforms", "cpu")
        raw = bench_config()
        record.update(
            # a CPU fallback must not carry the 7B-on-TPU metric name
            # (VERDICT r2: a reader skimming would see a sub-ms llama2-7B
            # number that does not exist)
            metric="cpu_fallback_smoke_next_token_latency",
            value=raw["next_token_ms"],
            first_token_ms=raw["first_token_ms"],
            prompt_len=raw["prompt_len"],
            decode_steps=raw["decode_steps"],
            backend=raw["backend"],
            model="tiny-llama(cpu-fallback)",
            best_config="cpu-fallback",
        )
        if kv_sweep:
            # per-dtype rows even off-chip: the bytes column is exact
            # (shape math), the timing column is a smoke number
            record["kv_sweep"] = {
                d: {k: r[k] for k in ("next_token_ms", "first_token_ms",
                                      "kv_cache_bytes")}
                for d, r in ((d, bench_config(kv_cache_dtype=d))
                             for d in kv_sweep)}
        cached = _latest_valid_onchip_record()
        if cached is not None:
            # surface the newest real on-chip record alongside the smoke
            # number: the CACHED record becomes the headline (it is real
            # hardware evidence; `cached: true` + source timestamp keep it
            # honest), the fallback smoke run moves to an extra field
            cached["cpu_fallback_smoke"] = record
            print(json.dumps(cached))
        else:
            print(json.dumps(record))
        return

    from bigdl_tpu.utils.testing import LLAMA2_7B

    # persist every completed config immediately: a tunnel death mid-A/B
    # must not cost the results already measured
    run_dir = RUN_DIR
    partial_path = os.path.join(
        run_dir, time.strftime("bench_partial_%Y%m%d_%H%M%S.jsonl"))
    os.makedirs(run_dir, exist_ok=True)

    # total wall budget: the driver runs bench.py once at round end with
    # finite patience (unknown, plausibly ~1h) — when the budget runs
    # out, emit the record from what's measured rather than risk being
    # killed mid-config with no final line. 3000s leaves 10 min of
    # margin inside a 1-hour cap; the watcher overrides it upward for
    # its own unsupervised runs.
    budget_s = int(os.environ.get("BENCH_TOTAL_BUDGET_S", "3000"))
    t_start = time.time()

    ab_results = {}
    schedule = ([(f"kv-{d}", None) for d in kv_sweep] if kv_sweep
                else _ordered_configs(run_dir))
    for label, _ in schedule:
        # never overshoot the budget: a config only starts with a
        # meaningful slice left, and its subprocess timeout is capped at
        # the REMAINING budget (not the full CONFIG_TIMEOUT_S)
        remaining = budget_s - (time.time() - t_start)
        if remaining < 120:
            # a structured skip, not an error: the config never ran, so
            # it must not demote, must not fail the run's exit code,
            # and (skip_class "budget-exhausted") gets promoted to the
            # front of the next window's order instead of starving at
            # the tail forever
            ab_results[label] = {
                "skip": f"total budget {budget_s}s exhausted before "
                        "this config",
                "skip_class": "budget-exhausted"}
            continue
        cfg_timeout = min(CONFIG_TIMEOUT_S, int(remaining) - 30)
        t0 = time.time()
        lane_log = None
        proc = None
        try:
            # unfiltered tracebacks: the child's crash frames must name
            # the real site, not jax's traceback-filtering shim
            proc = subprocess.run(
                [sys.executable, "-u", os.path.abspath(__file__),
                 "--config", label],
                capture_output=True, text=True, timeout=cfg_timeout,
                env={**os.environ, "JAX_TRACEBACK_FILTERING": "off"})
            sys.stderr.write(proc.stderr[-2000:])
            lane_log = _persist_lane_log(run_dir, label,
                                         proc.stdout, proc.stderr)
            lines = [ln for ln in proc.stdout.strip().splitlines()
                     if ln.startswith("{")]
            if not lines:
                frame = _last_tb_frame(proc.stderr)
                head = _exception_head(proc.stderr)
                raise RuntimeError(
                    f"no output (rc={proc.returncode}); "
                    + (f"{head}; " if head else "")
                    + (f"crashed at: {frame}; " if frame else "")
                    + f"stderr tail: {proc.stderr[-300:]}")
            raw = json.loads(lines[-1])
            if "skip" in raw:
                # the child classified its own crash as an environment
                # limitation (SKIP_SIGNATURES) — record it structured
                ab_results[label] = {"skip": raw["skip"],
                                     "skip_class": raw.get(
                                         "skip_class", "unclassified")}
                print(f"bench[{label}]: SKIP "
                      f"({raw.get('skip_class')}: {raw['skip'][:120]})",
                      file=sys.stderr)
                if lane_log:
                    ab_results[label]["lane_log"] = lane_log
                try:
                    with open(partial_path, "a") as pf:
                        pf.write(json.dumps({"config": label,
                                             **ab_results[label]})
                                 + "\n")
                except OSError:
                    pass
                continue
            if not raw.get("on_tpu"):
                raise RuntimeError("config subprocess fell back off-TPU")
            dfloor, pfloor = _floors(LLAMA2_7B, raw["weight_bytes"],
                                     raw["prompt_len"])
            entry = {"first_token_ms": raw["first_token_ms"],
                     "first_token_ms_raw": raw["first_token_ms_raw"],
                     "next_token_ms": raw["next_token_ms"],
                     "tunnel_overhead_ms": raw["tunnel_overhead_ms"],
                     "final_token": raw["final_token"],
                     "weight_bytes": raw["weight_bytes"],
                     "qtype": raw["qtype"],
                     "kv_cache_dtype": raw.get("kv_cache_dtype", "bf16"),
                     "kv_cache_bytes": raw.get("kv_cache_bytes"),
                     "kv_quantized": raw["kv_quantized"],
                     "prepack": raw.get("prepack"),
                     "observability": raw.get("observability", {})}
            if raw["next_token_ms"] < dfloor or \
                    raw["first_token_ms"] < pfloor:
                entry["invalid"] = (
                    f"timings beat the physics floors "
                    f"(decode>{dfloor:.2f}ms, prefill>{pfloor:.1f}ms) — "
                    f"runtime did not execute (poisoned buffers)")
            ab_results[label] = entry
            print(f"bench[{label}]: first {raw['first_token_ms']:.1f}ms "
                  f"next {raw['next_token_ms']:.2f}ms "
                  f"({'INVALID' if 'invalid' in entry else 'ok'}, "
                  f"{time.time() - t0:.0f}s)", file=sys.stderr)
        except subprocess.TimeoutExpired as te:
            child_err = ""
            if te.stderr:
                child_err = (te.stderr.decode("utf-8", "replace")
                             if isinstance(te.stderr, bytes) else te.stderr)
                sys.stderr.write(child_err[-2000:])
            child_out = ""
            if te.stdout:
                child_out = (te.stdout.decode("utf-8", "replace")
                             if isinstance(te.stdout, bytes) else te.stdout)
            lane_log = _persist_lane_log(run_dir, label,
                                         child_out, child_err)
            if "bench-phase" in child_err:
                last = [ln for ln in child_err.splitlines()
                        if "bench-phase" in ln][-1]
                ab_results[label] = {
                    "error": f"timeout {cfg_timeout}s after: {last[-120:]}"}
            else:
                # no phase breadcrumb means the child never got past jax
                # backend init — the tunnel died, the CONFIG is not at
                # fault (the 08:03 window post-mortem); a structured
                # skip carries that verdict explicitly
                ab_results[label] = {
                    "skip": f"timeout {cfg_timeout}s before any phase "
                            "(tunnel death, not the config)",
                    "skip_class": "tunnel-death",
                    "no_fault": True}
            print(f"bench[{label}]: TIMEOUT", file=sys.stderr)
        except Exception as e:
            err = f"{type(e).__name__}: {e}"
            if "crashed at:" not in err and proc is not None:
                frame = _last_tb_frame(proc.stderr or "")
                if frame:
                    err += f" (lane crashed at: {frame})"
            cls = _classify_skip(err)
            if cls is None and proc is not None:
                cls = _classify_skip(proc.stderr or "")
            if cls is not None:
                # the crash matches a known environment limitation the
                # child could not classify itself (e.g. it died before
                # printing): structured skip, not a fault
                ab_results[label] = {"skip": err[:300],
                                     "skip_class": cls}
                print(f"bench[{label}]: SKIP ({cls})", file=sys.stderr)
            else:
                ab_results[label] = {"error": err}
                # a config that failed FAST (clean subprocess exit, no
                # timeout) cannot have wedged the window; demoting it
                # would delay a since-fixed retry behind the whole
                # matrix (2026-08-02: the 3 mxu-layout configs died in
                # seconds on a D2H bug fixed the same window)
                if time.time() - t0 < 120:
                    ab_results[label]["fast_fail"] = True
                print(f"bench[{label}]: FAILED {e}", file=sys.stderr)
        if lane_log and isinstance(ab_results.get(label), dict):
            # full stdout/stderr on disk, referenced from the JSON —
            # the error string above only carries a tail
            ab_results[label]["lane_log"] = lane_log
        tunnel_dead = False
        if "error" in ab_results[label]:
            # probe BEFORE persisting: if the tunnel itself is gone, the
            # config is not at fault even when it died mid-phase — a
            # fault record here would demote a healthy config next window
            tunnel_dead = _probe_backend(60) != "tpu"
            if tunnel_dead:
                entry = ab_results[label]
                entry["no_fault"] = True
                entry["skip"] = entry.pop("error")
                entry["skip_class"] = "tunnel-death"
        try:
            with open(partial_path, "a") as pf:
                pf.write(json.dumps({"config": label,
                                     **ab_results[label]}) + "\n")
        except OSError:
            pass
        if tunnel_dead:
            # a kernel fault can take the whole tunnel down server-side;
            # don't burn the window timing out every remaining config
            print("bench: backend no longer answers — aborting remaining "
                  "configs", file=sys.stderr)
            for rest, _ in schedule:
                if rest not in ab_results:
                    ab_results[rest] = {
                        "skip": "tunnel died earlier in the run",
                        "skip_class": "tunnel-death"}
            break

    # headline candidates: valid AND the shipped default model config —
    # int4 weights, bf16 KV (experiment configs like int8-weights and
    # fp8-kv stay in `ab` as evidence)
    ok = {k: v for k, v in ab_results.items()
          if "next_token_ms" in v and "invalid" not in v
          and v.get("qtype") == "sym_int4"
          and not v.get("kv_quantized")}
    record["ab"] = ab_results
    if kv_sweep:
        record["kv_sweep"] = {
            lbl[3:]: {k: v[k] for k in ("next_token_ms", "first_token_ms",
                                        "kv_cache_bytes") if k in v}
            for lbl, v in ab_results.items() if lbl.startswith("kv-")}
    if not ok:
        # keep the record honest: no valid on-chip numbers were produced
        # THIS run — but the newest prior valid record is still the best
        # hardware evidence available (marked cached, with this run's
        # failures attached)
        record["note"] = ("every dispatch configuration failed or was "
                          "rejected by the physics floors")
        cached = _latest_valid_onchip_record()
        if cached is not None:
            cached["failed_live_run"] = record
            print(json.dumps(cached))
            _failed_lane_exit(ab_results)
            raise SystemExit(0)
        print(json.dumps(record))
        raise SystemExit(1)
    # the HEADLINE is the SHIPPED DEFAULT config when it is valid
    # (VERDICT r4 #3: no per-phase/per-config best-of as the record);
    # a faster non-default config is surfaced separately as the signal
    # to change the default
    fastest = min(ok, key=lambda k: ok[k]["next_token_ms"])
    best = "pallas+gemv" if "pallas+gemv" in ok else fastest
    first_ms = ok[best]["first_token_ms"]
    next_ms = ok[best]["next_token_ms"]

    record.update(
        value=round(next_ms, 3),
        vs_baseline=round(BASELINE_NEXT_TOKEN_MS / next_ms, 3),
        valid=True,
        first_token_ms=round(first_ms, 3),
        best_config=best,
        prepack=ok[best].get("prepack"),
        observability=ok[best].get("observability", {}),
    )
    if fastest != best:
        record["fastest_config"] = fastest
        record["fastest_next_token_ms"] = round(
            ok[fastest]["next_token_ms"], 3)
    record.update(_roofline_block(
        LLAMA2_7B, ok[best]["weight_bytes"], PROMPT_LEN, DECODE_STEPS,
        first_ms, next_ms,
        kv_cache_dtype=ok[best].get("kv_cache_dtype", "bf16")))
    print(json.dumps(record))
    _failed_lane_exit(ab_results)


def _failed_lane_exit(ab_results: dict) -> None:
    """Lane-failure summary AFTER the record is printed: the sweep
    continues past an erroring lane (each records ``{"error": ...}``),
    but the run's exit code must still say some lanes have no numbers.
    Structured skips ({"skip": ..., "skip_class": ...} — environment
    limitations, budget exhaustion, tunnel death) are reported but do
    NOT fail the run: a lane that cannot run here is not a fault.
    Consumers read the stdout record either way; exit 2 distinguishes
    partial-lane failure from total failure (exit 1)."""
    skipped = sorted(k for k, v in ab_results.items() if "skip" in v)
    if skipped:
        classes = {k: ab_results[k].get("skip_class", "?")
                   for k in skipped}
        print(f"bench: {len(skipped)} lane(s) skipped: "
              + ", ".join(f"{k} ({v})" for k, v in classes.items()),
              file=sys.stderr)
    failed = sorted(k for k, v in ab_results.items() if "error" in v)
    if failed:
        print(f"bench: {len(failed)} lane(s) failed: "
              f"{', '.join(failed)}", file=sys.stderr)
        raise SystemExit(2)


def _efficiency(cfg, weight_bytes: int, prompt_len: int, steps: int,
                first_ms: float, next_ms: float) -> dict:
    """MFU + HBM-roofline utilization (VERDICT r2 #2).

    Decode on one chip is HBM-bandwidth-bound: every token reads the whole
    packed weight set plus the live KV slice, so the honest efficiency
    number is bytes-moved / (latency x peak-BW). Prefill is compute-bound,
    so its number is model FLOPs / (latency x peak-FLOPs) — classic MFU.
    The math lives in observability/roofline.py (value-identical to
    what it always printed here — the identity test pins the r05
    fixture numbers), shared with the engine's live gauges.
    `weight_bytes` is measured from the live param pytree in the config
    subprocess and passed through."""
    return _roofline.efficiency(cfg, weight_bytes, prompt_len, steps,
                                first_ms, next_ms)


def _roofline_block(cfg, weight_bytes: int, prompt_len: int, steps: int,
                    first_ms: float, next_ms: float,
                    kv_cache_dtype: str = "bf16") -> dict:
    """The headline record's efficiency numbers plus the per-phase
    roofline attribution block (analytical FLOPs / HBM bytes / ideal ms
    next to the measured ms) — both from observability/roofline.py."""
    out = _efficiency(cfg, weight_bytes, prompt_len, steps,
                      first_ms, next_ms)
    out["roofline"] = _roofline.attribution(
        cfg, weight_bytes, prompt_len, steps, first_ms, next_ms,
        kv_cache_dtype=kv_cache_dtype)
    return out


def _parse_kv_sweep(argv: "list[str]") -> "list[str] | None":
    """`--kv-cache-dtype a,b,c` (or `=`-joined) -> validated dtype list."""
    from bigdl_tpu.ops.kvcache import resolve_kv_cache_dtype

    spec = None
    for i, a in enumerate(argv):
        if a == "--kv-cache-dtype" and i + 1 < len(argv):
            spec = argv[i + 1]
        elif a.startswith("--kv-cache-dtype="):
            spec = a.split("=", 1)[1]
    if spec is None:
        return None
    return [resolve_kv_cache_dtype(d) for d in spec.split(",") if d]


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--config":
        _one_config(sys.argv[2])
    else:
        main(kv_sweep=_parse_kv_sweep(sys.argv[1:]))
