#!/usr/bin/env python3
"""Compare two BENCH_*.json files and flag regressions.

Usage:
    python tools/bench_diff.py OLD.json NEW.json [--threshold PCT]

Both the raw bench-record form (the dict bench.py / bigdl_tpu.bench
emit) and the driver wrapper form ({"n", "cmd", "rc", "tail",
"parsed"}) are accepted — the wrapper's "parsed" block is compared when
present. Nested sub-records (ab variants, cpu_fallback_smoke, ...) are
walked too, so per-config latencies get their own rows.

A metric regresses when it moves in its bad direction by more than
--threshold percent (default 5): latencies and byte footprints UP,
throughput DOWN. The memory report's headline scalars
(hbm_static_total_bytes, hbm_device_peak_bytes, jit_peak_temp_bytes)
get their own --max-hbm-regress-pct threshold (default: --threshold);
the decode roofline and the critical-path dispatch overhead
(dispatch_overhead_ms) ride tighter ratchets
(--max-roofline-regress-pct / --max-dispatch-regress-pct, default 2).
Records missing any block — memory, jit_compile_table, observability,
or individual metric keys — are fine: only keys present in BOTH files
are compared. Exit status: 0 no regressions, 1 regressions found,
2 usage / unreadable input.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, Optional, Tuple

# comparable scalar fields -> direction ("lower" / "higher" is better)
METRIC_DIRECTIONS = {
    "first_token_ms": "lower",
    "first_token_ms_raw": "lower",
    "next_token_ms": "lower",
    "rest_token_ms": "lower",
    "ttft_p50_ms": "lower",
    "tpot_p50_ms": "lower",
    "decode_ideal_ms": "lower",
    "kv_cache_bytes": "lower",
    "weight_bytes": "lower",
    "serving_tokens_per_s": "higher",
    "tokens_per_s": "higher",
    # overload lanes (bench_serving "overload" block): at <=1x offered
    # load shed_total/brownout_level_max must stay zero (any growth is
    # inf% and flags), at 3x goodput dropping is the regression
    "goodput_tokens_per_s": "higher",
    "shed_total": "lower",
    "brownout_level_max": "lower",
    # shared-prefix lane (bench_serving "prefix_share" block): the
    # fraction of looked-up prompt tokens served from radix-shared
    # pages must not erode, and allocation stalls against the page
    # pool must not grow at the same offered load
    "prefix_hit_tokens_frac": "higher",
    "page_pool_exhausted": "lower",
    # SLO lane (bench_serving overload block, <=1x lanes): burn rate
    # and active alerts must stay zero below capacity (slo_alerts is
    # additionally zero-gated), and the fraction of TTFT/TPOT
    # observations inside their QoS targets must not erode. The 3x
    # lane's slo_burn_rate_overload is deliberately NOT here — alerts
    # firing under deliberate overload is the feature working
    "slo_burn_rate_max": "lower",
    "slo_alerts": "lower",
    "slo_compliance_ttft": "higher",
    "slo_compliance_tpot": "higher",
    # golden-canary byte mismatches (router lane): also zero-gated — a
    # single mismatch between byte-identical seeded replicas means a
    # replica decoded garbage
    "canary_failures": "lower",
    # rolling-restart lane (bench_serving router_bench.restart block):
    # a planned restart must lose no requests (http_5xx), re-decode no
    # tokens the fleet already generated (recomputed_tokens_total —
    # live migration ships them instead), and land every attempted
    # sequence handoff (migrations_failed). All three sit at zero on a
    # healthy baseline, so any growth flags as inf%.
    "http_5xx": "lower",
    "recomputed_tokens_total": "lower",
    "migrations_failed": "lower",
    "decode_mfu": "higher",
    "prefill_mfu": "higher",
    "decode_hbm_roofline_util": "higher",
}

# memory-report headline scalars (bench "memory" block): compared
# under --max-hbm-regress-pct instead of --threshold
HBM_METRICS = {
    "hbm_static_total_bytes": "lower",
    "hbm_device_peak_bytes": "lower",
    "jit_peak_temp_bytes": "lower",
}

# robustness counters pulled out of the "observability" registry
# summary (its other series churn per run and stay skipped). Summary
# keys carry label suffixes (`...{reason="nan_logits"}`); matching is
# by family-name prefix. A run that starts quarantining requests or
# retrying steps where the baseline did not IS a regression even when
# every latency improved.
ROBUSTNESS_COUNTERS = (
    "bigdl_tpu_requests_quarantined_total",
    "bigdl_tpu_step_retries_total",
    "bigdl_tpu_requests_cancelled_total",
    "bigdl_tpu_requests_shed_total",
    "bigdl_tpu_router_failovers_total",
    "bigdl_tpu_router_replays_total",
    "bigdl_tpu_router_breaker_trips_total",
    # KV-handoff wire health: retries and local-decode fallbacks both
    # mean a decode target failed to take a transfer
    "bigdl_tpu_handoff_retries_total",
    "bigdl_tpu_handoff_fallbacks_total",
    # autoscaler guard activity: a refused or skipped decision means a
    # scale action ran into a hard guard (last-healthy, bounds, admin
    # lock) — more of those at the same load is a control regression.
    # Applied decisions ("up"/"down"/flips) are intentionally NOT
    # gated: the autoscale lane forces them by design. Label order is
    # declaration order (action first), so a family{action=" prefix
    # selects exactly these.
    'bigdl_tpu_autoscaler_decisions_total{action="refused',
    'bigdl_tpu_autoscaler_decisions_total{action="skipped',
    # perf-regression sentinel trips (observability/sentinel.py) —
    # additionally zero-gated below: a gated lane must never ship a
    # run whose own sentinel fired
    "bigdl_tpu_perf_regression_total",
    # quality-regression sentinel trips (observability/quality.py) —
    # also zero-gated: the run itself watched its decode quality drift
    "bigdl_tpu_quality_regression_total",
    # golden-canary byte mismatches (serving/canary.py) — also
    # zero-gated: byte-identical seeded replicas must agree
    "bigdl_tpu_router_canary_failures_total",
    # live-migration health: a failed sequence migration means a
    # planned drain fell back to journal replay (recompute), and a
    # rejected wire frame means a corrupt/skewed internal payload
    # reached a replica
    'bigdl_tpu_migrations_total{outcome="failed',
    "bigdl_tpu_handoff_rejects_total",
)

# counters that must be exactly 0 in the candidate run, baseline or
# not: a sentinel trip means the run itself detected a decode (or
# decode-quality) regression while it was happening; an SLO alert or
# a canary byte mismatch in a gated lane means the run violated its
# own objectives
ZERO_COUNTERS = ("bigdl_tpu_perf_regression_total",
                 "bigdl_tpu_quality_regression_total",
                 "slo_alerts", "canary_failures")

# the router's flat counters block (bench_serving --replicas embeds
# GET /v1/router/stats as `router_bench.router`): every one of these
# counts a recovery action, so MORE of them between two runs of the
# same load is a robustness regression even when throughput improved
ROUTER_COUNTERS = {
    "failovers": "lower",
    "replays": "lower",
    "breaker_trips": "lower",
    "quarantined": "lower",
    "rerouted_503": "lower",
    "shed_429": "lower",
    "stream_errors": "lower",
    # disaggregated-serving health: handoff retries/fallbacks count
    # failed KV transfers to decode replicas; autoscale_refused counts
    # scale decisions stopped by a hard guard. Spawn/retire/flip
    # counters are not gated — the autoscale lane drives them on
    # purpose.
    "handoff_retries": "lower",
    "handoff_fallbacks": "lower",
    "autoscale_refused": "lower",
    # golden-canary byte mismatches: zero-gated via ZERO_COUNTERS too
    "canary_failures": "lower",
    # live-migration recovery actions (flat router counters): failed
    # handoffs, continuation fallbacks to journal replay, recomputed
    # tokens, torn journal records — all zero on a clean fleet
    "migration_failed": "lower",
    "sequences_migrate_failed": "lower",
    "migration_fallback_replays": "lower",
    "recomputed_tokens_total": "lower",
    "journal_torn_records": "lower",
}

# host dispatch overhead of the decode step (bench_serving
# "critical_path" block, EWMA of dispatch-return time per step): the
# tunnel-overhead number the paper optimizes, so it gets its own
# (tighter) --max-dispatch-regress-pct ratchet, lower-is-better
DISPATCH_METRICS = {
    "dispatch_overhead_ms": "lower",
}

# the HBM-bandwidth roofline utilization of the decode step is the
# tentpole serving efficiency number: it gets a RATCHET — its own
# (tighter) --max-roofline-regress-pct threshold, higher-is-better,
# instead of riding the generic --threshold. decode_mfu rides the same
# ratchet: the fused decode path moves both together (one dispatch,
# same bytes), so a run that holds roofline but drops MFU is hiding a
# compute regression behind the bandwidth number
ROOFLINE_METRICS = {
    "decode_hbm_roofline_util": "higher",
    "decode_mfu": "higher",
}

# the per-format golden NLL budget (quality block, nats/token —
# observability/quality.golden_nll_allowance from the refreshed
# ACCURACY.md deltas): a SHRINK-ONLY ratchet with its own (tight)
# --max-nll-regress-pct, lower-is-better — quantization quality may
# improve freely but a budget that grows means the format got worse
# (or someone quietly loosened the table)
NLL_METRICS = {
    "nll_delta_vs_bf16": "lower",
}


def load_record(path: str) -> dict:
    """Read a BENCH json; unwrap the driver's {"parsed": ...} wrapper
    when that is what we got."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: expected a JSON object, got "
                         f"{type(doc).__name__}")
    if set(doc) >= {"cmd", "rc", "parsed"}:
        parsed = doc.get("parsed")
        if not isinstance(parsed, dict):
            raise ValueError(
                f"{path}: wrapper has no parsed bench record "
                f"(parsed={parsed!r}) — nothing to compare")
        return parsed
    return doc


def flatten_metrics(rec: dict, prefix: str = "",
                    out: Optional[Dict[str, Tuple[float, str]]] = None,
                    depth: int = 0) -> Dict[str, Tuple[float, str]]:
    """{dotted.name: (value, direction)} for every comparable scalar,
    recursing into sub-record dicts (ab variants etc.). Tolerant by
    construction: absent keys/blocks simply contribute nothing (a
    pre-memory or pre-compile-table record still compares on whatever
    it has)."""
    if out is None:
        out = {}
    if not isinstance(rec, dict):
        return out
    for key, val in rec.items():
        name = f"{prefix}{key}"
        if key in METRIC_DIRECTIONS and isinstance(val, (int, float)) \
                and not isinstance(val, bool):
            out[name] = (float(val), METRIC_DIRECTIONS[key])
        elif key in HBM_METRICS and isinstance(val, (int, float)) \
                and not isinstance(val, bool):
            out[name] = (float(val), HBM_METRICS[key])
        elif key in DISPATCH_METRICS and isinstance(val, (int, float)) \
                and not isinstance(val, bool):
            out[name] = (float(val), DISPATCH_METRICS[key])
        elif key in NLL_METRICS and isinstance(val, (int, float)) \
                and not isinstance(val, bool):
            out[name] = (float(val), NLL_METRICS[key])
        elif key == "value" and isinstance(val, (int, float)) \
                and not isinstance(val, bool) and rec.get("unit") == "ms":
            # the headline {"metric": ..., "value": ..., "unit": "ms"}
            # row: a latency, keyed by its metric name
            label = rec.get("metric", "value")
            out[f"{prefix}{label}"] = (float(val), "lower")
        elif key == "observability" and isinstance(val, dict):
            # only the robustness counters: the full summary (latency
            # histograms, per-phase gauges) churns per environment
            for mk, mv in val.items():
                if mk.startswith(ROBUSTNESS_COUNTERS) \
                        and isinstance(mv, (int, float)) \
                        and not isinstance(mv, bool):
                    out[f"{name}.{mk}"] = (float(mv), "lower")
        elif key == "router" and isinstance(val, dict) \
                and isinstance(val.get("counters"), dict):
            # embedded GET /v1/router/stats: gate the recovery-action
            # counters lower-is-better (replica rows and config churn
            # per run and stay skipped)
            for mk, direction in ROUTER_COUNTERS.items():
                mv = val["counters"].get(mk)
                if isinstance(mv, (int, float)) \
                        and not isinstance(mv, bool):
                    out[f"{name}.counters.{mk}"] = (float(mv), direction)
        elif key == "memory" and isinstance(val, dict):
            # only the headline scalars: the snapshot's nested static/
            # device/headroom dicts churn per environment
            for mk, direction in HBM_METRICS.items():
                mv = val.get(mk)
                if isinstance(mv, (int, float)) \
                        and not isinstance(mv, bool):
                    out[f"{name}.{mk}"] = (float(mv), direction)
        elif isinstance(val, dict) and depth < 3 \
                and key not in ("observability", "jit_compile_table",
                                "prepack"):
            # "prepack" is the load-time weight-prepack report (mode,
            # counts, one-time transform ms) — informational, never a
            # per-token metric, so it stays out of the comparison
            flatten_metrics(val, f"{name}.", out, depth + 1)
    return out


def diff(old: Dict[str, Tuple[float, str]],
         new: Dict[str, Tuple[float, str]],
         threshold_pct: float,
         hbm_threshold_pct: Optional[float] = None,
         roofline_threshold_pct: Optional[float] = None,
         dispatch_threshold_pct: Optional[float] = None,
         nll_threshold_pct: Optional[float] = None):
    """Returns (rows, regressions): rows are (name, old, new, pct,
    direction, regressed) for every metric present in both files.
    Memory-report scalars (HBM_METRICS keys) regress past
    ``hbm_threshold_pct`` (default: ``threshold_pct``); the decode
    roofline ratchet (ROOFLINE_METRICS) past ``roofline_threshold_pct``
    (default 2); the host dispatch-overhead ratchet (DISPATCH_METRICS)
    past ``dispatch_threshold_pct`` (default 2); the golden NLL budget
    (NLL_METRICS) past ``nll_threshold_pct`` (default 2,
    shrink-only)."""
    if hbm_threshold_pct is None:
        hbm_threshold_pct = threshold_pct
    if roofline_threshold_pct is None:
        roofline_threshold_pct = 2.0
    if dispatch_threshold_pct is None:
        dispatch_threshold_pct = 2.0
    if nll_threshold_pct is None:
        nll_threshold_pct = 2.0
    rows = []
    regressions = []
    for name in sorted(set(old) & set(new)):
        o, direction = old[name]
        n, _ = new[name]
        if o == 0:
            pct = 0.0 if n == 0 else float("inf") * (1 if n > 0 else -1)
        else:
            pct = (n - o) / abs(o) * 100.0
        leaf = name.rsplit(".", 1)[-1]
        if leaf in HBM_METRICS:
            limit = hbm_threshold_pct
        elif leaf in ROOFLINE_METRICS:
            limit = roofline_threshold_pct
        elif leaf in DISPATCH_METRICS:
            limit = dispatch_threshold_pct
        elif leaf in NLL_METRICS:
            limit = nll_threshold_pct
        else:
            limit = threshold_pct
        bad = pct > limit if direction == "lower" else pct < -limit
        if n > 0 and any(z in name for z in ZERO_COUNTERS):
            bad = True      # zero-gated: nonzero is a failure outright
        rows.append((name, o, n, pct, direction, bad))
        if bad:
            regressions.append(name)
    # zero-gated counters present only in the candidate still fail:
    # the baseline predates the sentinel, the trip is real either way
    for name in sorted(set(new) - set(old)):
        n, direction = new[name]
        if n > 0 and any(z in name for z in ZERO_COUNTERS):
            rows.append((name, 0.0, n, float("inf"), direction, True))
            regressions.append(name)
    return rows, regressions


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("old", help="baseline BENCH json")
    ap.add_argument("new", help="candidate BENCH json")
    ap.add_argument("--threshold", type=float, default=5.0,
                    help="regression threshold in percent (default 5)")
    ap.add_argument("--max-hbm-regress-pct", type=float, default=None,
                    help="separate threshold for the memory report's "
                         "HBM scalars (default: --threshold)")
    ap.add_argument("--max-roofline-regress-pct", type=float,
                    default=2.0,
                    help="ratchet threshold for "
                         "decode_hbm_roofline_util and decode_mfu "
                         "(default 2; higher-is-better)")
    ap.add_argument("--max-dispatch-regress-pct", type=float,
                    default=2.0,
                    help="ratchet threshold for dispatch_overhead_ms "
                         "(default 2; lower-is-better)")
    ap.add_argument("--max-nll-regress-pct", type=float, default=2.0,
                    help="shrink-only ratchet threshold for the "
                         "quality block's nll_delta_vs_bf16 golden "
                         "budget (default 2; lower-is-better)")
    args = ap.parse_args(argv)

    try:
        old = flatten_metrics(load_record(args.old))
        new = flatten_metrics(load_record(args.new))
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"bench_diff: {e}", file=sys.stderr)
        return 2

    rows, regressions = diff(old, new, args.threshold,
                             args.max_hbm_regress_pct,
                             args.max_roofline_regress_pct,
                             args.max_dispatch_regress_pct,
                             args.max_nll_regress_pct)
    if not rows:
        print("bench_diff: no comparable metrics between "
              f"{args.old} and {args.new}", file=sys.stderr)
        return 0

    width = max(len(r[0]) for r in rows)
    print(f"{'metric':<{width}}  {'old':>14}  {'new':>14}  {'delta':>9}")
    for name, o, n, pct, direction, bad in rows:
        arrow = "" if not bad else \
            "  REGRESSION" + (" (want lower)" if direction == "lower"
                              else " (want higher)")
        print(f"{name:<{width}}  {o:>14.4f}  {n:>14.4f}  {pct:>+8.2f}%"
              f"{arrow}")
    missing = sorted(set(old) ^ set(new))
    if missing:
        print(f"(not in both files, skipped: {', '.join(missing)})")
    if regressions:
        print(f"{len(regressions)} regression(s) past "
              f"{args.threshold:g}%: {', '.join(regressions)}")
        return 1
    print(f"no regressions past {args.threshold:g}%")
    return 0


if __name__ == "__main__":
    sys.exit(main())
