"""On-chip Pallas kernel validation + microbenchmarks (opportunistic).

The axon TPU tunnel flaps; when tools/tpu_watch.sh finds it alive it runs
this suite. Each step runs in its OWN subprocess with a timeout so a wedged
tunnel mid-suite keeps the earlier results; the parent appends one JSON
line per step to stdout and to tpu_runs/onchip_results.jsonl.

Steps cover the kernels VERDICT.md flagged as interpret-verified-only:
dequant_matmul (generic + decode-GEMV, every supported qtype),
decode_attention, prefill_attention (fwd + VJP), moe_dispatch ragged,
plus timing vs the XLA fallback at llama-7B-like geometry.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# running this file by path puts tools/ (not the repo root) on sys.path,
# so the package would be unimportable in the per-step subprocesses
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

STEP_TIMEOUT = int(os.environ.get("ONCHIP_STEP_TIMEOUT", "600"))


def _backend_alive(timeout_s: int = 60) -> bool:
    """Quick out-of-process probe: does a fresh process still get a TPU?
    Compares the printed backend name — a dead tunnel can make JAX fall
    back to CPU, which exits 0 but means the chip is gone."""
    if os.environ.get("ONCHIP_FORCE_CPU"):
        return True              # smoke-testing the harness without a chip
    code = ("import jax, jax.numpy as jnp;"
            "jnp.ones((2,2)).block_until_ready();"
            "print(jax.default_backend())")
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           timeout=timeout_s, capture_output=True,
                           text=True)
        out = r.stdout.strip().splitlines()
        return r.returncode == 0 and bool(out) and out[-1] == "tpu"
    except subprocess.TimeoutExpired:
        return False

if os.environ.get("ONCHIP_FORCE_CPU"):
    # smoke-testing the suite itself without a chip: the ambient axon
    # plugin prepends itself to jax_platforms regardless of JAX_PLATFORMS,
    # so only the config API reliably forces CPU
    import jax

    jax.config.update("jax_platforms", "cpu")

# ---------------------------------------------------------------- steps


def _bench(fn, *args, warmup=2, iters=10):
    import jax

    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def step_sanity():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import pallas as pl

    d = jax.devices()[0]
    assert d.platform == "tpu", d

    def k0(x_ref, o_ref):
        o_ref[:] = x_ref[:] * 2

    x = jnp.ones((128, 128), jnp.bfloat16)
    y = pl.pallas_call(
        k0, out_shape=jax.ShapeDtypeStruct((128, 128), jnp.bfloat16))(x)
    np.testing.assert_allclose(np.asarray(y, np.float32), 2.0)
    return {"device": str(d), "trivial_kernel": "ok"}


def _qmat_case(qtype: str, m: int, k: int, n: int):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bigdl_tpu.ops.pallas.dequant_matmul import q_matmul_pallas
    from bigdl_tpu.ops.quant import dequantize, quantize

    key = jax.random.PRNGKey(0)
    w = jax.random.normal(key, (k, n), jnp.float32)
    wq = quantize(w, qtype)
    x = jax.random.normal(jax.random.PRNGKey(1), (m, k), jnp.bfloat16)

    y = np.asarray(q_matmul_pallas(x, wq), np.float32)
    ref = np.asarray(
        x.astype(jnp.float32) @ dequantize(wq).astype(jnp.float32))
    denom = np.maximum(np.abs(ref), 1.0)
    rel = float(np.max(np.abs(y - ref) / denom))

    def xla(xx):
        return xx.astype(jnp.float32) @ dequantize(wq, dtype=jnp.bfloat16)

    t_pal = _bench(jax.jit(lambda xx: q_matmul_pallas(xx, wq)), x)
    t_xla = _bench(jax.jit(xla), x)
    return {"qtype": qtype, "m": m, "k": k, "n": n, "max_rel_err": rel,
            "pallas_ms": t_pal * 1e3, "xla_ms": t_xla * 1e3,
            "speedup": t_xla / t_pal}


def _persist_case(step: str, case: dict) -> None:
    """Append one completed case to tpu_runs/ immediately: a multi-case
    step killed by the step timeout (or a tunnel wedge) must not cost
    the cases already measured."""
    try:
        os.makedirs("tpu_runs", exist_ok=True)
        with open(f"tpu_runs/onchip_cases_{step}.jsonl", "a") as f:
            f.write(json.dumps(
                {"ts": time.strftime("%Y-%m-%dT%H:%M:%S"), **case}) + "\n")
    except OSError:
        pass


def step_qmatmul_decode():
    out = []
    for qt in ["sym_int4", "asym_int4", "nf4", "fp4", "sym_int8"]:
        out.append(_qmat_case(qt, 1, 4096, 4096))
        _persist_case("qmatmul_decode", out[-1])
    return {"cases": out}


def step_qmatmul_prefill():
    out = []
    for qt, m, k, n in [("sym_int4", 512, 4096, 4096),
                        ("sym_int4", 512, 4096, 11008),
                        ("nf4", 512, 4096, 4096)]:
        out.append(_qmat_case(qt, m, k, n))
        _persist_case("qmatmul_prefill", out[-1])
    return {"cases": out}


def step_gemv():
    # decode-GEMV variants, called directly (bypasses the probe) at
    # llama-7B decode geometries: split, MERGED (qkv N=12288 /
    # gate_up N=22016 — the shipped default), tp=4 shards; bodies:
    # "std" (unpack chain), "fold" (scale-folded), "mxu" (int4-dtype
    # native load — the r5 shipped default), "mxu8" (int8 MXU path).
    # Per-case GB/s lets the parent see roofline utilization directly.
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bigdl_tpu.ops.pallas.dequant_matmul import (_q_gemv_pallas,
                                                     gemv_kernel_compiles)
    from bigdl_tpu.ops.quant import (dequantize, get_qtype, quantize,
                                     to_mxu_layout)

    out = []
    for qt_name, k, n, variant in [
            ("sym_int4", 4096, 4096, "std"),
            ("sym_int4", 4096, 4096, "fold"),
            ("sym_int4", 4096, 4096, "mxu"),
            ("sym_int4", 4096, 4096, "mxuflat"),
            ("sym_int4", 4096, 4096, "mxu8"),
            ("sym_int4", 4096, 12288, "mxu"),    # merged qkv
            ("sym_int4", 4096, 12288, "mxuflat"),
            ("sym_int4", 4096, 12288, "mxu8"),
            ("sym_int4", 4096, 22016, "mxu"),    # merged gate_up
            ("sym_int4", 4096, 22016, "mxuflat"),
            ("sym_int4", 4096, 22016, "mxu8"),
            ("sym_int4", 11008, 4096, "mxu"),    # down proj
            ("sym_int4", 11008, 4096, "mxuflat"),
            ("sym_int4", 11008, 4096, "mxu8"),
            ("sym_int4", 4096, 12288, "std"),
            ("sym_int4", 4096, 22016, "fold"),
            ("sym_int4", 11008, 4096, "fold"),
            ("sym_int4", 2816, 4096, "mxu"),     # tp=4 down shard (padded)
            ("sym_int8", 4096, 4096, "std"),
            ("sym_int8", 4096, 4096, "mxu8"),
            ("nf4", 4096, 4096, "std"),
            ("nf4", 4096, 4096, "fold")]:
        qt = get_qtype(qt_name)
        interp = bool(os.environ.get("ONCHIP_FORCE_CPU"))
        w = jax.random.normal(jax.random.PRNGKey(0), (k, n), jnp.float32)
        wq = quantize(w, qt_name)
        if variant in ("mxu", "mxuflat", "mxu8"):
            wq = to_mxu_layout(wq)
        x = jax.random.normal(jax.random.PRNGKey(1), (1, k), jnp.bfloat16)
        y = np.asarray(
            _q_gemv_pallas(x, wq, qt, 1, k, n, interp, x.dtype,
                           variant=variant),
            np.float32)
        # two references: bf16-dequant (the XLA fallback's contract —
        # the STANDARD kernel matches it) and exact-f32 dequant (the
        # FOLD/MXU kernels apply scales in f32 and land much closer to
        # this one; their larger bf16-ref deviation is the reference's
        # own weight rounding, not kernel error)
        ref16 = np.asarray(
            x.astype(jnp.float32) @ dequantize(wq).astype(jnp.float32))
        ref32 = np.asarray(
            x.astype(jnp.float32) @ dequantize(wq, dtype=jnp.float32))

        def _rel(ref):
            return float(np.max(np.abs(y - ref)
                                / np.maximum(np.abs(ref), 1.0)))

        t = _bench(jax.jit(
            lambda xx: _q_gemv_pallas(xx, wq, qt, 1, k, n, interp, xx.dtype,
                                      variant=variant)),
            x)
        probe = gemv_kernel_compiles(qt_name, k, n, variant=variant)
        bytes_moved = wq.nbytes
        out.append({"qtype": qt_name, "k": k, "n": n, "variant": variant,
                    "max_rel_err_bf16ref": _rel(ref16),
                    "max_rel_err_f32ref": _rel(ref32),
                    "gemv_ms": t * 1e3,
                    "gbps": bytes_moved / max(t, 1e-9) / 1e9,
                    "probe_ok": probe})
        _persist_case("gemv", out[-1])
    return {"cases": out}


def step_decode_attention():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bigdl_tpu.ops.attention import sdp_attention
    from bigdl_tpu.ops.pallas.decode_attention import decode_attention_pallas

    out = []
    for b, s, h, hkv, hd, kvdt in [
            (1, 1024, 32, 32, 128, "bfloat16"),     # llama2-7B MHA
            (1, 2048, 32, 8, 128, "bfloat16"),      # GQA
            (1, 2048, 32, 8, 128, "float8_e5m2"),   # fp8 KV
            (8, 1024, 32, 8, 128, "bfloat16")]:     # batched serving
        kdt = jnp.dtype(kvdt)
        q = jax.random.normal(jax.random.PRNGKey(0), (b, 1, h, hd),
                              jnp.bfloat16)
        kv_f = jax.random.normal(jax.random.PRNGKey(1), (b, s, hkv, hd),
                                 jnp.bfloat16) * 0.3
        k = kv_f.astype(kdt)
        v = (jax.random.normal(jax.random.PRNGKey(2), (b, s, hkv, hd),
                               jnp.bfloat16) * 0.3).astype(kdt)
        pos = jnp.asarray(s - 1, jnp.int32)
        y = np.asarray(
            decode_attention_pallas(q, k, v, pos, hd ** -0.5), np.float32)
        ref = np.asarray(sdp_attention(q, k, v, pos, backend="xla"),
                         np.float32)
        err = float(np.max(np.abs(y - ref)))
        t_pal = _bench(
            jax.jit(lambda qq: decode_attention_pallas(
                qq, k, v, pos, hd ** -0.5)), q)
        t_xla = _bench(
            jax.jit(lambda qq: sdp_attention(qq, k, v, pos, backend="xla")),
            q)
        out.append({"b": b, "s": s, "h": h, "hkv": hkv, "hd": hd,
                    "kv_dtype": kvdt, "max_abs_err": err,
                    "pallas_ms": t_pal * 1e3, "xla_ms": t_xla * 1e3,
                    "speedup": t_xla / t_pal})
        _persist_case("decode_attention", out[-1])
    return {"cases": out}


def step_prefill_attention():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bigdl_tpu.ops.attention import sdp_attention
    from bigdl_tpu.ops.pallas.prefill_attention import (
        prefill_attention_pallas)

    out = []
    for b, sq, s, h, hkv, hd in [(1, 512, 1024, 32, 32, 128),
                                 (1, 1024, 2048, 32, 8, 128)]:
        q = jax.random.normal(jax.random.PRNGKey(0), (b, sq, h, hd),
                              jnp.bfloat16)
        k = jax.random.normal(jax.random.PRNGKey(1), (b, s, hkv, hd),
                              jnp.bfloat16) * 0.3
        v = jax.random.normal(jax.random.PRNGKey(2), (b, s, hkv, hd),
                              jnp.bfloat16) * 0.3
        pos = jnp.asarray(0, jnp.int32)
        y = np.asarray(
            prefill_attention_pallas(q, k, v, pos, hd ** -0.5), np.float32)
        ref = np.asarray(sdp_attention(q, k, v, pos, backend="xla"),
                         np.float32)
        err = float(np.max(np.abs(y - ref)))
        t_pal = _bench(jax.jit(lambda qq: prefill_attention_pallas(
            qq, k, v, pos, hd ** -0.5)), q)
        t_xla = _bench(jax.jit(
            lambda qq: sdp_attention(qq, k, v, pos, backend="xla")), q)

        # VJP (QLoRA training uses the custom backward)
        def loss(qq):
            return jnp.sum(prefill_attention_pallas(
                qq, k, v, pos, hd ** -0.5).astype(jnp.float32))

        g = np.asarray(jax.jit(jax.grad(loss))(q), np.float32)
        grad_finite = bool(np.isfinite(g).all())
        out.append({"b": b, "sq": sq, "s": s, "h": h, "hkv": hkv, "hd": hd,
                    "max_abs_err": err, "grad_finite": grad_finite,
                    "pallas_ms": t_pal * 1e3, "xla_ms": t_xla * 1e3,
                    "speedup": t_xla / t_pal})
        _persist_case("prefill_attention", out[-1])
    return {"cases": out}


def step_moe():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bigdl_tpu.ops.pallas.moe_dispatch import (moe_mlp_ragged,
                                                   ragged_kernel_compiles)

    n, d, f, e, k = 256, 1024, 2816, 8, 2
    keys = jax.random.split(jax.random.PRNGKey(0), 5)
    xf = jax.random.normal(keys[0], (n, d), jnp.bfloat16)
    logits = jax.random.normal(keys[1], (n, e), jnp.float32)
    topw, topi = jax.lax.top_k(jax.nn.softmax(logits), k)
    gate = jax.random.normal(keys[2], (e, d, f), jnp.bfloat16) * 0.02
    up = jax.random.normal(keys[3], (e, d, f), jnp.bfloat16) * 0.02
    down = jax.random.normal(keys[4], (e, f, d), jnp.bfloat16) * 0.02
    act = jax.nn.silu
    y = np.asarray(moe_mlp_ragged(
        xf, topi.astype(jnp.int32), topw, gate, up, down, act, e),
        np.float32)

    # dense reference
    def dense():
        out = jnp.zeros((n, d), jnp.float32)
        for ei in range(e):
            h = act(xf @ gate[ei]) * (xf @ up[ei])
            o = (h @ down[ei]).astype(jnp.float32)
            wsum = jnp.sum(jnp.where(topi == ei, topw, 0.0), axis=1)
            out = out + o * wsum[:, None]
        return out

    ref = np.asarray(dense())
    err = float(np.max(np.abs(y - ref)))
    return {"n": n, "d": d, "f": f, "e": e,
            "max_abs_err": err,
            "probe_ok": ragged_kernel_compiles(None, d, f)}


def step_model_forward():
    # tiny llama end-to-end on-chip: prefill + one decode step
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bigdl_tpu.models import llama as llama_mod
    from bigdl_tpu.utils.testing import TINY_LLAMA, random_llama_params

    cfg = TINY_LLAMA
    params = random_llama_params(cfg, qtype="sym_int4")
    ids = jnp.ones((1, 128), jnp.int32)
    cache = llama_mod.new_cache(cfg, 1, 256)
    fwd = jax.jit(lambda p, i, c: llama_mod.forward(p, cfg, i, c))
    logits, cache = fwd(params, ids, cache)
    pre_ok = bool(np.isfinite(np.asarray(logits, np.float32)).all())
    tok = jnp.argmax(logits[:, -1:, :], axis=-1).astype(jnp.int32)
    logits2, cache = fwd(params, tok, cache)
    dec_ok = bool(np.isfinite(np.asarray(logits2, np.float32)).all())
    return {"prefill_logits_finite": pre_ok, "decode_logits_finite": dec_ok}


def step_model_forward_7b():
    # THE runtime-death reproducer: full shipped-default llama2-7B program
    # (merged projections + int4-MXU layout + auto kernel dispatch), short
    # prefill + 4 decode steps, phase prints between stages so a wedge is
    # attributable. Gated behind ONCHIP_7B=1 — the watcher runs it AFTER
    # the benches (a wedge here must not cost the window's other numbers).
    import time as _t

    import jax
    import jax.numpy as jnp
    import numpy as np

    from bigdl_tpu.models import llama as llama_mod
    from bigdl_tpu.transformers.model import _maybe_mxu_layout
    from bigdl_tpu.utils.testing import LLAMA2_7B, random_llama_params

    def ph(m):
        print(f"7b-phase[{_t.strftime('%H:%M:%S')}]: {m}",
              file=sys.stderr, flush=True)

    cfg = LLAMA2_7B
    ph("generating params")
    params = random_llama_params(cfg, qtype="sym_int4")
    params = llama_mod.merge_projections(params, cfg)
    params = _maybe_mxu_layout(params)
    jax.block_until_ready(params)
    ph("params ready")
    ids = jnp.ones((1, 128), jnp.int32)
    cache = llama_mod.new_cache(cfg, 1, 256)
    fwd = jax.jit(llama_mod.forward, static_argnums=1)
    logits, cache = fwd(params, cfg, ids, cache)
    pre_ok = bool(np.isfinite(np.asarray(logits[:, -1], np.float32)).all())
    ph(f"prefill done (finite={pre_ok})")
    tok = jnp.argmax(logits[:, -1:, :], axis=-1).astype(jnp.int32)
    times = []
    for i in range(4):
        t0 = _t.perf_counter()
        logits, cache = fwd(params, cfg, tok, cache)
        tok = jnp.argmax(logits[:, -1:, :], axis=-1).astype(jnp.int32)
        final = int(np.asarray(tok)[0, 0])
        times.append((_t.perf_counter() - t0) * 1e3)
        ph(f"decode step {i} done ({times[-1]:.0f}ms, tok={final})")
    dec_ok = bool(np.isfinite(np.asarray(logits[:, -1], np.float32)).all())
    return {"prefill_logits_finite": pre_ok, "decode_logits_finite": dec_ok,
            "decode_step_ms": [round(t, 1) for t in times]}


STEPS = {
    "sanity": step_sanity,
    "qmatmul_decode": step_qmatmul_decode,
    "qmatmul_prefill": step_qmatmul_prefill,
    "gemv": step_gemv,
    "decode_attention": step_decode_attention,
    "prefill_attention": step_prefill_attention,
    "moe": step_moe,
    "model_forward": step_model_forward,
}
if os.environ.get("ONCHIP_7B", "").lower() not in ("", "0", "false", "off"):
    STEPS["model_forward_7b"] = step_model_forward_7b


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "--step":
        name = sys.argv[2]
        t0 = time.time()
        try:
            from bigdl_tpu.config import enable_compilation_cache

            enable_compilation_cache()   # reuse compiles across windows
            result = STEPS[name]()
            rec = {"step": name, "ok": True,
                   "elapsed_s": round(time.time() - t0, 2),
                   "result": result}
        except Exception as e:  # noqa: BLE001
            rec = {"step": name, "ok": False,
                   "elapsed_s": round(time.time() - t0, 2),
                   "error": f"{type(e).__name__}: {e}"}
        # real-HBM high-water mark AFTER the step's kernels ran ({} on
        # CPU smoke runs) — the parent turns this into a memory_stats
        # line per step in onchip_results.jsonl
        from bigdl_tpu.observability.memory import device_memory_stats

        rec["memory_stats"] = device_memory_stats()
        print(json.dumps(rec))
        return

    os.makedirs("tpu_runs", exist_ok=True)
    only = [s for s in os.environ.get("ONCHIP_ONLY", "").split(",") if s]
    unknown = [s for s in only if s not in STEPS]
    if unknown:
        print(json.dumps({"step": "_config", "ok": False,
                          "error": f"ONCHIP_ONLY names not registered: "
                                   f"{unknown} (known: {list(STEPS)})"}))
        sys.exit(2)
    results = []
    for name in STEPS:
        if only and name not in only:
            continue
        t0 = time.time()
        try:
            proc = subprocess.run(
                [sys.executable, "-u", __file__, "--step", name],
                capture_output=True, text=True, timeout=STEP_TIMEOUT)
            line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
            rec = json.loads(line) if line.startswith("{") else {
                "step": name, "ok": False,
                "error": f"no output (rc={proc.returncode}); "
                         f"stderr tail: {proc.stderr[-400:]}"}
        except subprocess.TimeoutExpired:
            rec = {"step": name, "ok": False,
                   "error": f"timeout after {STEP_TIMEOUT}s",
                   "elapsed_s": round(time.time() - t0, 2)}
        except Exception as e:  # noqa: BLE001
            rec = {"step": name, "ok": False,
                   "error": f"{type(e).__name__}: {e}"}
        rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%S")
        # split the child's device telemetry into its own jsonl line so
        # HBM peaks per kernel step grep out of the log directly
        mem = rec.pop("memory_stats", None)
        print(json.dumps(rec), flush=True)
        results.append(rec)
        with open("tpu_runs/onchip_results.jsonl", "a") as f:
            f.write(json.dumps(rec) + "\n")
            f.write(json.dumps({"step": name, "memory_stats": mem or {},
                                "ts": rec["ts"]}) + "\n")
        if not rec["ok"] and not _backend_alive():
            # a kernel fault can wedge the tunnel server-side; record it
            # and stop instead of timing out every remaining step
            rec2 = {"step": "_abort", "ok": False,
                    "error": "backend stopped answering after "
                             f"'{name}' failed; remaining steps skipped",
                    "ts": time.strftime("%Y-%m-%dT%H:%M:%S")}
            print(json.dumps(rec2), flush=True)
            results.append(rec2)
            with open("tpu_runs/onchip_results.jsonl", "a") as f:
                f.write(json.dumps(rec2) + "\n")
            break
    n_ok = sum(r["ok"] for r in results)
    print(json.dumps({"summary": f"{n_ok}/{len(results)} steps ok"}))


if __name__ == "__main__":
    main()
