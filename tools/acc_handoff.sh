#!/bin/bash
# One-shot: wait for the 2500-step training process (old in-memory quant
# code) to export its checkpoint, then kill it before its eval phase and
# run the eval with the CURRENT (fixed-imatrix-objective) code instead.
cd "$(dirname "$0")/.." || exit 1
PID=$1
while true; do
  if [ -f acc_ckpt_medium/train_meta.json ] \
      && grep -q '"steps": 2500' acc_ckpt_medium/train_meta.json 2>/dev/null \
      && [ -f acc_ckpt_medium/model.safetensors ]; then
    kill "$PID" 2>/dev/null
    sleep 3
    echo "$(date +%H:%M:%S) checkpoint exported; running fixed-objective eval" \
      >> tpu_runs/acc_handoff.log
    JAX_PLATFORMS=cpu nohup python -u -m bigdl_tpu.bench.accuracy_eval \
      --size medium --ckpt-dir acc_ckpt_medium --max-windows 24 --out ACCURACY_MEDIUM.md \
      >> tpu_runs/acc_medium_r5_eval.log 2>&1
    echo "$(date +%H:%M:%S) eval exit=$?" >> tpu_runs/acc_handoff.log
    exit 0
  fi
  if ! kill -0 "$PID" 2>/dev/null; then
    echo "$(date +%H:%M:%S) training pid $PID gone before export" \
      >> tpu_runs/acc_handoff.log
    exit 1
  fi
  sleep 60
done
