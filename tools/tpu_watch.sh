#!/bin/bash
# Opportunistic on-chip runner: probe the axon TPU tunnel every 2 min;
# when it answers, grab numbers while it's up (the tunnel flaps, and the
# 08:03 window lasted ~3 minutes). Results land in tpu_runs/.
#
# Ordering is window-economics-driven:
# 1. bench.py FIRST — the headline metric. Its per-config subprocesses
#    share a persistent XLA compile cache, so even a window too short to
#    finish one config banks its completed compiles for the next window;
#    adaptive ordering runs last window's failures LAST.
# 2. The kernel-isolation onchip suite second, then the qlora/serving/
#    speculative benches (each CPU-falls-back harmlessly if the tunnel
#    died mid-window).
# 3. The gated 7B runtime-death reproducer LAST — a wedge there costs
#    nothing (everything else is already on disk).
cd "$(dirname "$0")/.." || exit 1
mkdir -p tpu_runs
while true; do
  ts=$(date +%Y%m%d_%H%M%S)
  if timeout 90 python -u -c "import jax; assert jax.devices()[0].platform == 'tpu'" >/dev/null 2>&1; then
    echo "$ts tunnel ALIVE — running on-chip suite" >> tpu_runs/watch.log
    # budget: one BENCH_CONFIG_TIMEOUT_S per A/B config (default read
    # from bench.py so the two never drift)
    bt=${BENCH_CONFIG_TIMEOUT_S:-$(python -c "import bench; print(bench.CONFIG_TIMEOUT_S)" 2>/dev/null || echo 900)}
    ncfg=$(python -c "import bench; print(len(bench.AB_CONFIGS))" 2>/dev/null || echo 8)
    # unsupervised watcher runs get the full per-config budget plus
    # startup/overhead headroom (the driver-facing default inside
    # bench.py is tighter)
    BENCH_TOTAL_BUDGET_S=$((ncfg * bt + 1200)) \
      timeout $((ncfg * bt + 1500)) python -u bench.py > "tpu_runs/bench_$ts.json" 2> "tpu_runs/bench_$ts.log"
    echo "$ts bench exit=$?" >> tpu_runs/watch.log
    ONCHIP_STEP_TIMEOUT=${ONCHIP_STEP_TIMEOUT:-300} timeout 1500 python -u tools/tpu_onchip.py > "tpu_runs/onchip_$ts.log" 2>&1
    echo "$ts onchip exit=$?" >> tpu_runs/watch.log
    timeout 1800 python -u bench_qlora.py > "tpu_runs/qlora_$ts.json" 2> "tpu_runs/qlora_$ts.log"
    echo "$ts bench_qlora exit=$?" >> tpu_runs/watch.log
    timeout 2400 python -u bench_serving.py > "tpu_runs/serving_$ts.json" 2> "tpu_runs/serving_$ts.log"
    echo "$ts bench_serving exit=$?" >> tpu_runs/watch.log
    timeout 1800 python -u bench_speculative.py > "tpu_runs/spec_$ts.json" 2> "tpu_runs/spec_$ts.log"
    echo "$ts bench_speculative exit=$?" >> tpu_runs/watch.log
    # LAST: the 7B runtime-death reproducer — isolated, phase-printing;
    # a wedge here must not cost the window's other numbers
    ONCHIP_7B=1 ONCHIP_ONLY=model_forward_7b ONCHIP_STEP_TIMEOUT=900 \
      timeout 1000 python -u tools/tpu_onchip.py \
      > "tpu_runs/onchip7b_$ts.log" 2>&1
    echo "$ts onchip7b exit=$?" >> tpu_runs/watch.log
    sleep 60
  else
    echo "$ts tunnel dead" >> tpu_runs/watch.log
    sleep 120
  fi
done
