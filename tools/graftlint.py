#!/usr/bin/env python
"""CI gate wrapper for graftlint (``bigdl_tpu.analysis``).

Thin by design: resolves the repo root onto ``sys.path`` so the gate
runs from a bare checkout (no install), then delegates to the package
CLI. Exit codes pass through unchanged (0 clean, 1 new findings vs
``tools/graftlint_baseline.json``, 2 ratchet violation / parse error),
so a CI step can be exactly ``python tools/graftlint.py``.
"""

import pathlib
import sys

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent

if __name__ == "__main__":
    sys.path.insert(0, str(REPO_ROOT))
    from bigdl_tpu.analysis.__main__ import main

    sys.exit(main())
