#!/usr/bin/env python
"""promlint — lint a Prometheus text-exposition scrape.

The in-tree registry (bigdl_tpu.observability.metrics) renders format
0.0.4; this tool holds every scrape — live ``/metrics`` output or a
saved file — to the conventions Prometheus itself and promtool
enforce, so a metric that would be rejected or silently mangled
downstream fails tier-1 here first:

- metric names match ``[a-zA-Z_:][a-zA-Z0-9_:]*`` and label names
  ``[a-zA-Z_][a-zA-Z0-9_]*`` (no ``__`` reserved prefix),
- every family has exactly one ``# TYPE`` and exactly one non-empty
  ``# HELP`` (HELP first), with a known kind,
- counters end in ``_total``; non-counters must NOT, and the
  ``_bucket``/``_sum``/``_count`` suffixes are reserved for histogram
  /summary expansion,
- the ``le`` label is reserved for histogram buckets (``quantile``
  for summaries),
- every series belongs to a declared family, family blocks are
  contiguous, and no (name, labelset) repeats,
- sample values parse as floats (``+Inf``/``-Inf``/``NaN`` allowed).

Usage::

    python tools/promlint.py metrics.txt
    curl -s localhost:8000/metrics | python tools/promlint.py -

Exit status 1 if any violation is found. Importable: ``lint_text()``
returns the violation list (the tier-1 test runs it over a live
engine registry render).
"""

from __future__ import annotations

import re
import sys
from typing import Dict, List, Optional, Set, Tuple

METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

KNOWN_KINDS = ("counter", "gauge", "histogram", "summary", "untyped")

#: suffixes minted by histogram/summary expansion — plain families may
#: not claim them (Prometheus would alias the series)
RESERVED_SUFFIXES = ("_bucket", "_sum", "_count")

_SERIES_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r"\s+(?P<value>\S+)(?:\s+(?P<ts>-?\d+))?$")

_LABEL_PAIR_RE = re.compile(
    r'\s*(?P<name>[a-zA-Z_][a-zA-Z0-9_]*|[^=,{}]+)\s*=\s*'
    r'"(?P<value>(?:[^"\\]|\\.)*)"\s*(?:,|$)')


def _base_family(name: str, types: Dict[str, str]) -> Optional[str]:
    """The declared family a series line belongs to: exact match, or
    the histogram/summary base when the name carries an expansion
    suffix."""
    if name in types:
        return name
    for suf in RESERVED_SUFFIXES:
        if name.endswith(suf):
            base = name[: -len(suf)]
            if types.get(base) in ("histogram", "summary"):
                return base
    return None


def _parse_float(raw: str) -> bool:
    try:
        float(raw)
        return True
    except ValueError:
        return False


def lint_text(text: str) -> List[str]:
    """All violations in one scrape, as ``line N: message`` strings."""
    out: List[str] = []
    types: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    type_lines: Dict[str, int] = {}
    help_lines: Dict[str, int] = {}
    series_seen: Set[Tuple[str, Tuple[Tuple[str, str], ...]]] = set()
    families_with_series: Set[str] = set()
    closed_families: Set[str] = set()
    current_family: Optional[str] = None

    def err(lineno: int, msg: str) -> None:
        out.append(f"line {lineno}: {msg}")

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line[len("# HELP "):].split(None, 1)
            name = parts[0] if parts else ""
            body = parts[1] if len(parts) > 1 else ""
            if not METRIC_NAME_RE.match(name):
                err(lineno, f"HELP for invalid metric name {name!r}")
                continue
            if name in help_lines:
                err(lineno, f"duplicate HELP for {name} (first at line "
                            f"{help_lines[name]})")
            else:
                help_lines[name] = lineno
                helps[name] = body
            if not body.strip():
                err(lineno, f"empty HELP text for {name}")
            if name in type_lines:
                err(lineno, f"HELP for {name} must precede its TYPE "
                            f"(TYPE at line {type_lines[name]})")
            continue
        if line.startswith("# TYPE "):
            parts = line[len("# TYPE "):].split()
            if len(parts) != 2:
                err(lineno, f"malformed TYPE line: {line!r}")
                continue
            name, kind = parts
            if not METRIC_NAME_RE.match(name):
                err(lineno, f"TYPE for invalid metric name {name!r}")
                continue
            if kind not in KNOWN_KINDS:
                err(lineno, f"unknown metric type {kind!r} for {name}")
            if name in type_lines:
                err(lineno, f"duplicate TYPE for {name} (first at line "
                            f"{type_lines[name]})")
                continue
            type_lines[name] = lineno
            types[name] = kind
            if kind == "counter" and not name.endswith("_total"):
                err(lineno, f"counter {name} must end in _total")
            if kind != "counter" and name.endswith("_total"):
                err(lineno, f"{kind} {name} ends in _total (reserved "
                            "for counters)")
            if kind not in ("histogram", "summary"):
                for suf in RESERVED_SUFFIXES:
                    if name.endswith(suf):
                        err(lineno, f"{kind} {name} ends in {suf} "
                                    "(reserved for histogram/summary "
                                    "expansion)")
            if current_family is not None:
                closed_families.add(current_family)
            current_family = name
            continue
        if line.startswith("#"):
            continue    # free-form comment
        m = _SERIES_RE.match(line)
        if m is None:
            err(lineno, f"unparseable series line: {line!r}")
            continue
        name = m.group("name")
        fam = _base_family(name, types)
        if fam is None:
            err(lineno, f"series {name} has no preceding TYPE")
        else:
            families_with_series.add(fam)
            if fam in closed_families:
                err(lineno, f"series {name} outside its contiguous "
                            f"family block (TYPE at line "
                            f"{type_lines[fam]})")
            kind = types[fam]
            is_bucket = kind in ("histogram", "summary") \
                and name.endswith("_bucket")
        labels: List[Tuple[str, str]] = []
        raw_labels = m.group("labels")
        if raw_labels:
            consumed = 0
            for pm in _LABEL_PAIR_RE.finditer(raw_labels):
                consumed = pm.end()
                ln = pm.group("name")
                if not LABEL_NAME_RE.match(ln):
                    err(lineno, f"invalid label name {ln!r} on {name}")
                elif ln.startswith("__"):
                    err(lineno, f"label {ln!r} on {name} uses the "
                                "reserved __ prefix")
                elif fam is not None:
                    if ln == "le" and not is_bucket:
                        err(lineno, f"label 'le' on {name} is reserved "
                                    "for histogram buckets")
                    if ln == "quantile" and types[fam] != "summary":
                        err(lineno, f"label 'quantile' on {name} is "
                                    "reserved for summaries")
                labels.append((ln, pm.group("value")))
            if consumed != len(raw_labels):
                err(lineno, f"unparseable label block on {name}: "
                            f"{raw_labels[consumed:]!r}")
        key = (name, tuple(sorted(labels)))
        if key in series_seen:
            err(lineno, f"duplicate series {name}{dict(labels)}")
        series_seen.add(key)
        if not _parse_float(m.group("value")):
            err(lineno, f"unparseable sample value "
                        f"{m.group('value')!r} on {name}")

    for name in sorted(types):
        if name not in helps:
            out.append(f"family {name}: missing HELP")
    return out


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if len(argv) != 1 or argv[0] in ("-h", "--help"):
        print(__doc__, file=sys.stderr)
        return 2
    if argv[0] == "-":
        text = sys.stdin.read()
    else:
        with open(argv[0], encoding="utf-8") as f:
            text = f.read()
    violations = lint_text(text)
    for v in violations:
        print(v)
    n_fams = len(re.findall(r"(?m)^# TYPE ", text))
    print(f"promlint: {len(violations)} violation(s), "
          f"{n_fams} famil{'y' if n_fams == 1 else 'ies'} checked",
          file=sys.stderr)
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
