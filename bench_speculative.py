"""Self-speculative decoding benchmark: spec-vs-plain on one chip.

The reference claims ~30% latency reduction from self-speculation
(reference README.md:18, "as fast as 33.7 ms/token with Self-Speculative
Decoding" vs ~48 ms fp16 plain); this measures the analog: llama2-7B,
sym_int8 target + sym_int4 draft (the self-speculation pairing closest
to the reference's fp16+int4 that fits one v5e), plain greedy vs
speculative wall-clock over the same decode budget.

Caveat carried in the record: on RANDOM weights the draft and target
(two quantizations of the same tensor) agree almost always, so the
MEASURED acceptance is an upper bound; the record therefore also
reports the per-round mechanics (draft step time, verify time) and a
projected speedup at a realistic 80% acceptance, computed from the
measured round timings.

Run: python bench_speculative.py  — prints ONE JSON line like bench.py.
(Not driver-run: bench.py stays the headline; this is the VERDICT r4 #9
on-chip evidence.)
"""

from __future__ import annotations

import json
import os
import sys
import time


def main() -> None:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from bench import _probe_backend, chip_peaks

    backend = _probe_backend()
    if backend is None:
        print("bench_speculative: backend unresponsive; falling back to "
              "CPU", file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"
        backend = "cpu"
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from bigdl_tpu.config import enable_compilation_cache

    enable_compilation_cache()   # reuse compiles across windows
    import jax.numpy as jnp
    import numpy as np

    from bigdl_tpu.generation import generate_on_device
    from bigdl_tpu.models import llama as llama_mod
    from bigdl_tpu.speculative import (SpecStats, prompt_lookup_generate, speculative_generate)
    from bigdl_tpu.utils.testing import (LLAMA2_7B, TINY_LLAMA,
                                         random_llama_params)

    on_tpu = jax.default_backend() == "tpu"
    cfg = LLAMA2_7B if on_tpu else TINY_LLAMA
    prompt_len, new_tokens, max_seq = (256, 128, 1024) if on_tpu \
        else (16, 16, 64)
    gamma = 4

    target = random_llama_params(cfg, qtype="sym_int8", seed=0)
    draft = random_llama_params(cfg, qtype="sym_int4", seed=0)
    jax.block_until_ready(jax.tree_util.tree_leaves(target)[0])
    prompt = jnp.ones((1, prompt_len), jnp.int32)

    def plain_run():
        cache = llama_mod.new_cache(cfg, 1, max_seq)
        t0 = time.perf_counter()
        out, _ = generate_on_device(
            target, cfg, llama_mod.forward, prompt, cache,
            max_new_tokens=new_tokens)
        np.asarray(out)
        return time.perf_counter() - t0

    def spec_run():
        stats = SpecStats()
        t0 = time.perf_counter()
        out = speculative_generate(
            target, draft, cfg, cfg, prompt,
            family_forward=llama_mod.forward,
            family_prefill=llama_mod.forward_last_token,
            new_cache=llama_mod.new_cache,
            max_new_tokens=new_tokens, gamma=gamma, max_seq=max_seq,
            th_stop_draft=0.0, stats=stats)
        np.asarray(out)
        return time.perf_counter() - t0, stats

    def best_of(run, n=3):
        run()                         # compile
        best = None
        for _ in range(n):
            r = run()
            key = r[0] if isinstance(r, tuple) else r
            if best is None or key < (best[0] if isinstance(best, tuple)
                                      else best):
                best = r
        return best

    plain_s = best_of(plain_run)
    spec_s, stats = best_of(spec_run)

    plain_ms = plain_s / new_tokens * 1e3
    spec_ms = spec_s / new_tokens * 1e3
    accept = stats.accept_rate
    tokens_per_round = stats.mean_accept + 1.0
    round_ms = spec_s / max(len(stats.accepted), 1) * 1e3
    # projected: tokens/round at acceptance a = a*gamma + 1 (geometric
    # prefix accept approximated linearly, the standard projection)
    proj_ms_80 = round_ms / (0.8 * gamma + 1.0)

    speedup = plain_ms / spec_ms if spec_ms > 0 else 0.0
    # physics floor: a verify step reads the int8 weights once -> no
    # per-round time below weight_bytes/BW is real
    wb = sum(getattr(l, "nbytes", l.nbytes)
             for l in jax.tree_util.tree_leaves(target))
    _, peak_gbps = chip_peaks()
    floor_round_ms = wb / (peak_gbps * 1e9) * 1e3 * 0.8
    valid = bool(on_tpu and round_ms > floor_round_ms and spec_s > 0)

    # prompt-lookup leg: n-gram drafts, NO draft model (beyond both the
    # reference and the draft-model path above) — repetition-heavy
    # prompts are its habitat, so bench a repeated-pattern prompt
    lookup_gamma = 8
    rep = np.tile(np.arange(1, 17, dtype=np.int32),
                  prompt_len // 16)[None, :prompt_len]

    def lookup_run():
        st = SpecStats()
        t0 = time.perf_counter()
        out = prompt_lookup_generate(
            target, cfg, rep,
            family_forward=llama_mod.forward,
            family_prefill=llama_mod.forward_last_token,
            new_cache=llama_mod.new_cache,
            max_new_tokens=new_tokens, gamma=lookup_gamma, max_seq=max_seq,
            stats=st)
        np.asarray(out)
        return time.perf_counter() - t0, st

    lookup_s, lstats = best_of(lookup_run)
    lookup_ms = lookup_s / new_tokens * 1e3
    lookup_round_ms = lookup_s / max(lstats.rounds, 1) * 1e3
    lookup_valid = bool(on_tpu and lookup_round_ms > floor_round_ms)

    rec = {
        "metric": "llama2_7b_selfspec_decode_speedup",
        "value": round(speedup, 3),
        "unit": "x",
        "vs_baseline": round(speedup / 1.3, 3),   # reference ~30% claim
        "valid": valid,
        "backend": "tpu" if on_tpu else "cpu",
        "plain_ms_per_token": round(plain_ms, 3),
        "spec_ms_per_token": round(spec_ms, 3),
        "gamma": gamma,
        "accept_rate": round(accept, 4),
        "tokens_per_round": round(tokens_per_round, 3),
        "round_ms": round(round_ms, 3),
        "projected_ms_per_token_at_80pct_accept": round(proj_ms_80, 3),
        "note": ("random-weight acceptance is an upper bound; "
                 "projected_* uses measured round mechanics at 80% "
                 "acceptance"),
        "prompt_len": prompt_len,
        "decode_steps": new_tokens,
        "model": "llama2-7b" if on_tpu else "tiny-llama(cpu-fallback)",
        "prompt_lookup": {
            "ms_per_token": round(lookup_ms, 3),
            "speedup_vs_plain": round(plain_ms / lookup_ms, 3)
            if lookup_ms > 0 else 0.0,
            "accept_rate": round(lstats.accept_rate, 4),
            "rounds": lstats.rounds,
            "valid": lookup_valid,
            "gamma": lookup_gamma,
            "note": "repeated-pattern prompt (lookup's habitat); no "
                    "draft model loaded",
        },
    }
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
