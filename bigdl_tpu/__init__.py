"""bigdl_tpu: a TPU-native low-bit LLM inference & finetuning framework.

A ground-up JAX/XLA/Pallas re-design with the capabilities of the reference
ipex-llm stack (see SURVEY.md): one-line low-bit loading of HF models,
quantized checkpoint save/load, fused decode kernels, speculative decoding,
QLoRA finetuning, tensor-parallel multi-chip inference, and serving.
"""

__version__ = "0.1.0"

from bigdl_tpu.ops.quant import (  # noqa: F401
    QTensor,
    QTYPES,
    FLOAT_QTYPES,
    get_qtype,
    quantize,
    dequantize,
    quantize_linear,
    dequantize_linear,
)
from bigdl_tpu.optimize import optimize_model  # noqa: F401
from bigdl_tpu.llm_patching import llm_patch, llm_unpatch  # noqa: F401


def __getattr__(name):
    # heavyweight subsystems resolve lazily so `import bigdl_tpu` stays light
    if name == "AutoModelForCausalLM":
        from bigdl_tpu.transformers.model import AutoModelForCausalLM

        return AutoModelForCausalLM
    if name == "AutoModel":
        from bigdl_tpu.transformers.model import AutoModel

        return AutoModel
    if name == "AutoModelForSpeechSeq2Seq":
        from bigdl_tpu.transformers.seq2seq import AutoModelForSpeechSeq2Seq

        return AutoModelForSpeechSeq2Seq
    if name == "LLMEngine":
        from bigdl_tpu.serving import LLMEngine

        return LLMEngine
    if name == "speculative_generate":
        from bigdl_tpu.speculative import speculative_generate

        return speculative_generate
    if name in ("collect_imatrix", "load_imatrix", "save_imatrix"):
        from bigdl_tpu import imatrix

        return getattr(imatrix, name)
    raise AttributeError(f"module 'bigdl_tpu' has no attribute {name!r}")
