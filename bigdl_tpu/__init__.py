"""bigdl_tpu: a TPU-native low-bit LLM inference & finetuning framework.

A ground-up JAX/XLA/Pallas re-design with the capabilities of the reference
ipex-llm stack (see SURVEY.md): one-line low-bit loading of HF models,
quantized checkpoint save/load, fused decode kernels, speculative decoding,
QLoRA finetuning, tensor-parallel multi-chip inference, and serving.
"""

__version__ = "0.1.0"

from bigdl_tpu.ops.quant import (  # noqa: F401
    QTensor,
    QTYPES,
    FLOAT_QTYPES,
    get_qtype,
    quantize,
    dequantize,
    quantize_linear,
    dequantize_linear,
)
from bigdl_tpu.optimize import optimize_model  # noqa: F401
