"""QLoRA / LoRA / QA-LoRA finetuning over frozen quantized weights.

TPU-native re-design of the reference's PEFT integration (reference
transformers/qlora.py: `LoraLowBitLinear` at :65, `LoraBF16Linear` at :137,
`get_peft_model` at :271, `LoraConfig(training_mode=...)` at :243) and its
autograd path through quantized weights (`MatMulLowBit`,
transformers/low_bit_linear.py:456-487: forward = dequant-matmul kernel,
backward = explicit dequantize + matmul, no gradient for the weight).

Design differences, by design:

- No nn.Module wrapping/monkey-patching. A LoRA-adapted weight is a pytree
  node (`LoraWeight`) that *replaces* the weight leaf in the parameter tree;
  `bigdl_tpu.ops.matmul.linear` dispatches on it, so every model family gains
  LoRA support with zero model-code changes — including under `lax.scan`
  (stacked per-layer LoraWeights slice leaf-wise like everything else).
- The backward through the frozen base is a `jax.custom_vjp`
  (`q_matmul_frozen`): dx = dy @ dequantize(W)^T, and the QTensor gets zero
  cotangent — the exact MatMulLowBit contract, but the "kernel" is the same
  fused dequant-matmul used in inference.
- QA-LoRA (reference qlora.py:102-134: AvgPool1d(qk_size) on the adapter
  input) is the `pool` field: the A-side input is mean-pooled over
  quantization groups, so merged adapters stay exactly representable in the
  quantized format.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
import jax.numpy as jnp

from bigdl_tpu.ops.matmul import linear, q_matmul
from bigdl_tpu.ops.quant import QTensor, dequantize_impl as dequantize, quantize

# Default adapter targets: every linear in a llama-family block (the
# reference's alpaca recipes target the same set).
DEFAULT_TARGET_MODULES: Tuple[str, ...] = (
    "q_proj", "k_proj", "v_proj", "o_proj",
    "gate_proj", "up_proj", "down_proj",
)


# ---------------------------------------------------------------------------
# Frozen-base matmul (MatMulLowBit equivalent)
# ---------------------------------------------------------------------------

# The custom VJP (fast fused fwd, dequant-matmul bwd, zero weight cotangent —
# the MatMulLowBit contract, low_bit_linear.py:456-487) lives on q_matmul
# itself (ops/matmul.py): every quantized matmul in the framework is
# trainable-through by construction.
q_matmul_frozen = q_matmul


def frozen_linear(x: jax.Array, w, bias: Optional[jax.Array] = None) -> jax.Array:
    """Linear through a frozen base weight (QTensor or dense)."""
    if isinstance(w, QTensor):
        y = q_matmul_frozen(x, w)
    else:
        y = jnp.dot(x, jax.lax.stop_gradient(w).astype(x.dtype),
                    preferred_element_type=jnp.float32).astype(x.dtype)
    if bias is not None:
        y = y + jax.lax.stop_gradient(bias).astype(y.dtype)
    return y


def _dequantize_any(base, dtype=jnp.float32) -> jax.Array:
    """Dequantize a QTensor, including layer-stacked ([L, ...]) ones."""
    if not isinstance(base, QTensor):
        return base.astype(dtype)
    lead = tuple(base.scale.shape[:-2])
    if not lead:
        return dequantize(base, dtype=dtype)
    flat = jax.tree.map(
        lambda x: x.reshape((-1,) + x.shape[len(lead):]), base)
    out = jax.vmap(lambda q: dequantize(q, dtype=dtype))(flat)
    return out.reshape(lead + out.shape[1:])


# ---------------------------------------------------------------------------
# LoraWeight pytree node
# ---------------------------------------------------------------------------

@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class LoraWeight:
    """A weight leaf with a trainable low-rank delta on a frozen base.

    y = frozen_linear(x, base) + (alpha/r) * (pool(x) @ a) @ b

    base: QTensor or dense [K, N] (leading layer-stack axes allowed)
    a:    [..., K//pool, r] trainable
    b:    [..., r, N] trainable (zero-init: adapter starts as identity)
    pool: QA-LoRA group size (1 = plain LoRA)
    """
    base: Any
    a: jax.Array
    b: jax.Array
    alpha: float = 16.0
    pool: int = 1

    def tree_flatten(self):
        return (self.base, self.a, self.b), (self.alpha, self.pool)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], children[2], aux[0], aux[1])

    @property
    def rank(self) -> int:
        return self.a.shape[-1]

    @property
    def scaling(self) -> float:
        return self.alpha / self.rank

    def apply_linear(self, x: jax.Array, bias: Optional[jax.Array] = None,
                     **_: Any) -> jax.Array:
        y = frozen_linear(x, self.base, bias)
        xa = x
        if self.pool > 1:
            k = x.shape[-1]
            xa = x.reshape(*x.shape[:-1], k // self.pool, self.pool)
            xa = jnp.mean(xa, axis=-1)
        delta = jnp.dot(jnp.dot(xa, self.a.astype(xa.dtype)),
                        self.b.astype(xa.dtype),
                        preferred_element_type=jnp.float32)
        return y + (self.scaling * delta).astype(y.dtype)

    def merged_dense(self, dtype=jnp.float32) -> jax.Array:
        """Base + adapter as one dense [..., K, N] array."""
        wd = _dequantize_any(self.base, dtype)
        a = self.a.astype(dtype)
        if self.pool > 1:
            # pooled-mean input == full-K input against a row-repeated A/pool
            a = jnp.repeat(a, self.pool, axis=-2) / self.pool
        return wd + self.scaling * jnp.matmul(a, self.b.astype(dtype))


# ---------------------------------------------------------------------------
# Attach / merge / filter (the get_peft_model surface)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class LoraConfig:
    """Reference `LoraConfig(training_mode=...)` (qlora.py:243) equivalent.

    training_mode: "qlora" (frozen QTensor base), "lora" (frozen dense
    base), "qalora" (qlora + group pooling). The base kind is whatever the
    params carry; the mode just sets pooling defaults.
    """
    r: int = 8
    lora_alpha: float = 16.0
    target_modules: Sequence[str] = DEFAULT_TARGET_MODULES
    training_mode: str = "qlora"
    qa_pool: int = 1

    def __post_init__(self):
        if self.training_mode == "qalora" and self.qa_pool == 1:
            object.__setattr__(self, "qa_pool", 16)


def _leaf_kn(w) -> Tuple[int, int]:
    if isinstance(w, QTensor):
        return w.k, w.n
    return w.shape[-2], w.shape[-1]


def _stack_dims(w) -> Tuple[int, ...]:
    """Leading (layer-stack) dims of a possibly-stacked weight leaf."""
    if isinstance(w, QTensor):
        return tuple(w.scale.shape[:-2])
    return tuple(w.shape[:-2])


def attach_lora(
    params: Dict[str, Any],
    config: LoraConfig = LoraConfig(),
    *,
    key: Optional[jax.Array] = None,
    dtype=jnp.float32,
) -> Dict[str, Any]:
    """Wrap target weight leaves in LoraWeight. Returns a new pytree.

    The reference walks nn.Modules replacing Linear with LoraLowBitLinear
    (qlora.py:201-232); here the walk is over the parameter dict, and the
    stacked-layer layout means ONE LoraWeight covers all L layers of a
    projection (a: [L, K/pool, r]).
    """
    if key is None:
        key = jax.random.PRNGKey(0)

    out = dict(params)
    layers = dict(params["layers"])
    merged = {"qkv_proj", "gate_up_proj"} & set(layers)
    if merged and any(t not in layers for t in config.target_modules):
        # silently skipping q/k/v would train an adapter-less attention;
        # fail loudly with the fix
        raise ValueError(
            f"params carry merged projections {sorted(merged)} but "
            "target_modules name the split layout; load the model with "
            "merge_projections=False (or run models.llama."
            "unmerge_projections) before attach_lora")
    for name in config.target_modules:
        if name not in layers:
            continue
        w = layers[name]
        kdim, ndim = _leaf_kn(w)
        lead = _stack_dims(w)
        if kdim % config.qa_pool:
            raise ValueError(
                f"qa_pool={config.qa_pool} must divide K={kdim} ({name})")
        key, ka = jax.random.split(key)
        a = jax.random.normal(
            ka, (*lead, kdim // config.qa_pool, config.r), dtype
        ) * (1.0 / jnp.sqrt(jnp.array(kdim, jnp.float32))).astype(dtype)
        b = jnp.zeros((*lead, config.r, ndim), dtype)
        layers[name] = LoraWeight(w, a, b, config.lora_alpha, config.qa_pool)
    out["layers"] = layers
    return out


def merge_lora(params: Dict[str, Any], *, requantize: bool = True) -> Dict[str, Any]:
    """Fold adapters into base weights (export / ReLoRA restart).

    requantize=True re-quantizes merged weights to the base qtype (the
    reference merges into dequantized fp and saves fp16; requantizing keeps
    the deployed artifact low-bit).
    """
    def merge_leaf(w):
        if not isinstance(w, LoraWeight):
            return w
        dense = w.merged_dense()
        if isinstance(w.base, QTensor) and requantize:
            qt = w.base.qtype
            lead = _stack_dims(w.base)
            if lead:
                flat = dense.reshape((-1,) + dense.shape[len(lead):])
                qs = [quantize(flat[i], qt) for i in range(flat.shape[0])]
                stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *qs)
                return jax.tree.map(
                    lambda s: s.reshape(lead + s.shape[1:]), stacked)
            return quantize(dense, qt)
        return dense.astype(jnp.bfloat16)

    return jax.tree.map(
        merge_leaf, params,
        is_leaf=lambda x: isinstance(x, (LoraWeight, QTensor)))


def lora_trainable_mask(params: Any) -> Any:
    """Pytree of bool: True only on adapter (a/b) leaves.

    Feed to `make_train_step(trainable_filter=...)` and `optax.masked` so
    frozen-base optimizer state is never allocated (the 7B-scale equivalent
    of the reference freezing base modules in prepare_model_for_kbit_training,
    qlora.py:294-342).
    """
    def mask_leaf(w):
        if isinstance(w, LoraWeight):
            return LoraWeight(
                jax.tree.map(lambda _: False, w.base),
                True, True, w.alpha, w.pool)
        if isinstance(w, QTensor):
            return jax.tree.map(lambda _: False, w)
        return False

    return jax.tree.map(
        mask_leaf, params,
        is_leaf=lambda x: isinstance(x, (LoraWeight, QTensor)))


def mark_only_lora_trainable(params: Any) -> Callable[[Any], Any]:
    """trainable_filter factory for bigdl_tpu.training.make_train_step."""
    return lambda p: lora_trainable_mask(p)


# ---------------------------------------------------------------------------
# Adapter persistence (the reference's PEFT adapter checkpoints: alpaca
# scripts save adapters with Trainer, export_merged_model.py merges them;
# SURVEY.md §5 checkpoint/resume)
# ---------------------------------------------------------------------------


def _walk_adapters(tree: Any, prefix: Tuple[str, ...], out: Dict[str, Any]):
    if isinstance(tree, LoraWeight):
        out[".".join(prefix)] = tree
    elif isinstance(tree, dict):
        for k, v in tree.items():
            _walk_adapters(v, prefix + (str(k),), out)


def save_adapter(params: Any, path: str) -> None:
    """Persist ONLY the LoRA a/b deltas (+ static alpha/pool) to `path`.

    Tiny files (rank x dims), the base stays wherever it was loaded from —
    the same separation as PEFT adapter checkpoints. Serialization reuses
    lowbit_io's dtype-preserving converters (bf16 adapters round-trip as
    bf16 — dtype drift on resume would silently change training)."""
    import json
    import os

    from safetensors.numpy import save_file

    from bigdl_tpu.transformers.lowbit_io import _to_numpy

    os.makedirs(path, exist_ok=True)
    found: Dict[str, LoraWeight] = {}
    _walk_adapters(params, (), found)
    if not found:
        raise ValueError("no LoraWeight leaves in params; attach_lora first")
    arrays = {}
    dtypes = {}
    meta = {}
    for key, lw in found.items():
        arrays[f"{key}#a"], dtypes[f"{key}#a"] = _to_numpy(lw.a)
        arrays[f"{key}#b"], dtypes[f"{key}#b"] = _to_numpy(lw.b)
        meta[key] = {"alpha": lw.alpha, "pool": lw.pool}
    save_file(arrays, os.path.join(path, "adapter_weights.safetensors"))
    with open(os.path.join(path, "adapter_manifest.json"), "w") as f:
        # v2: arrays are stored via lowbit_io._to_numpy (bf16 as uint16
        # views) and need the "dtypes" map to decode — v1 readers would
        # reinterpret them as raw integers, so the version must gate
        json.dump({"format_version": 2, "adapters": meta,
                   "dtypes": dtypes}, f, indent=1)


def load_adapter(params: Any, path: str) -> Any:
    """Re-attach saved adapters onto a matching base pytree.

    `params` is the freshly loaded (quantized) base; every adapter key in
    the checkpoint must resolve to a leaf at the same tree path, and the
    saved a/b shapes must fit that leaf's [K, N] (fail here with names,
    not later inside a jitted dot_general)."""
    import json
    import os

    from safetensors.numpy import load_file

    from bigdl_tpu.transformers.lowbit_io import _from_numpy

    with open(os.path.join(path, "adapter_manifest.json")) as f:
        manifest = json.load(f)
    version = manifest.get("format_version")
    if version not in (1, 2):
        raise ValueError(
            f"adapter checkpoint format_version {version!r} is not one "
            "this build understands (known: 1, 2) — a newer bigdl_tpu "
            "wrote it, or the manifest is corrupt")
    store = load_file(os.path.join(path, "adapter_weights.safetensors"))
    dtypes = manifest.get("dtypes", {})

    def get(key):
        return _from_numpy(store[key], dtypes.get(key, str(store[key].dtype)))

    def attach(node, prefix):
        if isinstance(node, dict):
            return {k: attach(v, prefix + (str(k),)) for k, v in
                    node.items()}
        key = ".".join(prefix)
        if key in manifest["adapters"]:
            info = manifest["adapters"][key]
            base = node.base if isinstance(node, LoraWeight) else node
            a = get(f"{key}#a")
            b = get(f"{key}#b")
            k_dim, n_dim = _leaf_kn(base)
            pool = int(info["pool"])
            stack = _stack_dims(base)     # leading [L, ...] layer axes
            if (a.shape[-2] * pool != k_dim or b.shape[-1] != n_dim
                    or a.shape[-1] != b.shape[-2]
                    or tuple(a.shape[:-2]) != stack
                    or tuple(b.shape[:-2]) != stack):
                raise ValueError(
                    f"adapter {key!r} shapes a{tuple(a.shape)} / "
                    f"b{tuple(b.shape)} (pool={pool}) do not fit base "
                    f"[*{stack}, K={k_dim}, N={n_dim}] — adapter saved "
                    "from a different model size?")
            return LoraWeight(base, a, b, float(info["alpha"]), pool)
        return node

    out = attach(params, ())
    missing = [k for k in manifest["adapters"]
               if _tree_get(out, k) is None]
    if missing:
        raise ValueError(f"adapter keys not found in base params: {missing}")
    return out


def _tree_get(tree: Any, dotted: str):
    node = tree
    for part in dotted.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    return node if isinstance(node, LoraWeight) else None
