"""GGUF direct loader: parse GGUF files into quantized parameter pytrees.

TPU-native equivalent of the reference's pure-Python GGUF stack (reference
transformers/gguf/gguf.py:31-231: GGUFReader/GGUFHeader/GGUFConfig/
GGUFTensorInfos/GGUFTensorLoader; per-arch weight mapping in
transformers/gguf/models/*.py; entry `load_gguf_model` at gguf/api.py:31).

The key design difference: the reference dequantizes GGUF blocks to float
and re-quantizes into its own format. Here q4_0/q4_1/q5_0/q5_1/q8_0 blocks
are **repacked bit-faithfully** into QTensors — our quantization formulas
and split-block nibble layout were chosen to match ggml exactly
(ops/quant.py), so the import is a pure byte shuffle:

  q4_0 value = (nibble - 8) * d      == sym_int4
  q4_1 value = nibble * d + m        == asym_int4
  q5_0 value = (q5 - 16) * d         == sym_int5 (qh bit plane == aux)
  q5_1 value = q5 * d + m            == asym_int5
  q8_0 value = int8 * d              == sym_int8

The only lossy step is fp16 -> bf16 scale conversion (TPU has no fp16
compute; ~0.2% relative, far below int4 quantization noise).

A minimal GGUF *writer* (f32/f16/q4_0/q4_1/q5_0/q5_1/q8_0) is included for tests and for
exporting quantized checkpoints to the llama.cpp ecosystem.
"""

from __future__ import annotations

import struct
from typing import Any, BinaryIO, Dict, List, Optional, Tuple

import numpy as np

GGUF_MAGIC = b"GGUF"

# GGUF metadata value types
_T_U8, _T_I8, _T_U16, _T_I16, _T_U32, _T_I32, _T_F32, _T_BOOL = range(8)
_T_STR, _T_ARR, _T_U64, _T_I64, _T_F64 = 8, 9, 10, 11, 12

_SCALARS = {
    _T_U8: "<B", _T_I8: "<b", _T_U16: "<H", _T_I16: "<h",
    _T_U32: "<I", _T_I32: "<i", _T_F32: "<f", _T_U64: "<Q",
    _T_I64: "<q", _T_F64: "<d",
}

# ggml tensor dtypes (ggml.h enum ggml_type)
GGML_F32, GGML_F16 = 0, 1
GGML_Q4_0, GGML_Q4_1 = 2, 3
GGML_Q5_0, GGML_Q5_1 = 6, 7
GGML_Q8_0 = 8
GGML_Q2_K = 10
GGML_Q3_K, GGML_Q4_K, GGML_Q5_K, GGML_Q6_K = 11, 12, 13, 14
GGML_IQ2_XXS, GGML_IQ2_XS = 16, 17
GGML_IQ1_S = 19
GGML_IQ1_M = 29
GGML_BF16 = 30

# (block size in values, bytes per block)
_BLOCK = {
    GGML_F32: (1, 4), GGML_F16: (1, 2), GGML_BF16: (1, 2),
    GGML_Q4_0: (32, 18), GGML_Q4_1: (32, 20),
    GGML_Q5_0: (32, 22), GGML_Q5_1: (32, 24),
    GGML_Q8_0: (32, 34),
    GGML_Q2_K: (256, 84),
    # k-quant superblocks (dequantize-on-load; the de-facto standard
    # community formats q3_K..q6_K — block_q*_K in ggml-quants.h)
    GGML_Q3_K: (256, 110), GGML_Q4_K: (256, 144),
    GGML_Q5_K: (256, 176), GGML_Q6_K: (256, 210),
    # ultra-low-bit iq formats (dequantize-on-load; grid tables are
    # pluggable constants — bigdl_tpu/ops/iq_grids.py)
    GGML_IQ2_XXS: (256, 66), GGML_IQ2_XS: (256, 74),
    GGML_IQ1_S: (256, 50), GGML_IQ1_M: (256, 56),
}

_GGML_TO_QTYPE = {
    GGML_Q4_0: "sym_int4", GGML_Q4_1: "asym_int4",
    GGML_Q5_0: "sym_int5", GGML_Q5_1: "asym_int5",
    GGML_Q8_0: "sym_int8", GGML_Q2_K: "q2_k",
}


def _scale_min_k4(scales: np.ndarray):
    """ggml get_scale_min_k4: 12 packed bytes -> 8 (6-bit sc, 6-bit m)
    pairs per superblock. scales [nblk, 12] -> (sc, m) each [nblk, 8]."""
    s = scales.astype(np.uint8)
    sc = np.empty((s.shape[0], 8), np.float32)
    m = np.empty((s.shape[0], 8), np.float32)
    sc[:, :4] = (s[:, :4] & 63)
    m[:, :4] = (s[:, 4:8] & 63)
    sc[:, 4:] = (s[:, 8:12] & 0x0F) | ((s[:, :4] >> 6) << 4)
    m[:, 4:] = (s[:, 8:12] >> 4) | ((s[:, 4:8] >> 6) << 4)
    return sc, m


def _decode_q4k(blk: np.ndarray) -> np.ndarray:
    """block_q4_K {d, dmin, scales[12], qs[128]} -> [nblk, 256] f32
    (dequantize_row_q4_K: per 64-value chunk, low nibbles then high)."""
    d = blk[:, 0:2].copy().view(np.float16).astype(np.float32)[:, 0]
    dmin = blk[:, 2:4].copy().view(np.float16).astype(np.float32)[:, 0]
    sc, mn = _scale_min_k4(blk[:, 4:16])
    qs = blk[:, 16:144].reshape(-1, 4, 32)            # [nblk, chunk, 32]
    lo = (qs & 0x0F).astype(np.float32)
    hi = (qs >> 4).astype(np.float32)
    out = np.empty((blk.shape[0], 4, 2, 32), np.float32)
    for c in range(4):
        out[:, c, 0] = (d[:, None] * sc[:, 2 * c, None] * lo[:, c]
                        - dmin[:, None] * mn[:, 2 * c, None])
        out[:, c, 1] = (d[:, None] * sc[:, 2 * c + 1, None] * hi[:, c]
                        - dmin[:, None] * mn[:, 2 * c + 1, None])
    return out.reshape(-1, 256)


def _decode_q5k(blk: np.ndarray) -> np.ndarray:
    """block_q5_K {d, dmin, scales[12], qh[32], qs[128]} (dequantize_
    row_q5_K: qh bit pairs (u1, u2) shift left 2 per 64-value chunk)."""
    d = blk[:, 0:2].copy().view(np.float16).astype(np.float32)[:, 0]
    dmin = blk[:, 2:4].copy().view(np.float16).astype(np.float32)[:, 0]
    sc, mn = _scale_min_k4(blk[:, 4:16])
    qh = blk[:, 16:48]                                # [nblk, 32]
    qs = blk[:, 48:176].reshape(-1, 4, 32)
    out = np.empty((blk.shape[0], 4, 2, 32), np.float32)
    for c in range(4):
        hi_lo = ((qh >> (2 * c)) & 1).astype(np.float32) * 16.0
        hi_hi = ((qh >> (2 * c + 1)) & 1).astype(np.float32) * 16.0
        lo = (qs[:, c] & 0x0F).astype(np.float32) + hi_lo
        hi = (qs[:, c] >> 4).astype(np.float32) + hi_hi
        out[:, c, 0] = (d[:, None] * sc[:, 2 * c, None] * lo
                        - dmin[:, None] * mn[:, 2 * c, None])
        out[:, c, 1] = (d[:, None] * sc[:, 2 * c + 1, None] * hi
                        - dmin[:, None] * mn[:, 2 * c + 1, None])
    return out.reshape(-1, 256)


def _decode_q6k(blk: np.ndarray) -> np.ndarray:
    """block_q6_K {ql[128], qh[64], int8 scales[16], d} (dequantize_
    row_q6_K: two 128-value halves of four 32-value strips each)."""
    ql = blk[:, :128]
    qh = blk[:, 128:192]
    sc = blk[:, 192:208].view(np.int8).astype(np.float32)
    d = blk[:, 208:210].copy().view(np.float16).astype(np.float32)[:, 0]
    out = np.empty((blk.shape[0], 2, 4, 32), np.float32)
    for half in range(2):
        qlh = ql[:, 64 * half:64 * (half + 1)]
        qhh = qh[:, 32 * half:32 * (half + 1)]
        strips = [
            (qlh[:, :32] & 0x0F) | (((qhh >> 0) & 3) << 4),
            (qlh[:, 32:] & 0x0F) | (((qhh >> 2) & 3) << 4),
            (qlh[:, :32] >> 4) | (((qhh >> 4) & 3) << 4),
            (qlh[:, 32:] >> 4) | (((qhh >> 6) & 3) << 4),
        ]
        for s_i, strip in enumerate(strips):
            q = strip.astype(np.float32) - 32.0
            # scale index: 16-value granularity -> two scales per strip
            isc = 8 * half + 2 * s_i
            out[:, half, s_i, :16] = d[:, None] * sc[:, isc, None] \
                * q[:, :16]
            out[:, half, s_i, 16:] = d[:, None] * sc[:, isc + 1, None] \
                * q[:, 16:]
    return out.reshape(-1, 256)


def _decode_q3k(blk: np.ndarray) -> np.ndarray:
    """block_q3_K {hmask[32], qs[64], scales[12], d} (dequantize_
    row_q3_K: kmask scale unpack; 2-bit quants with a SUBTRACTED-when-
    clear high mask bit)."""
    hmask = blk[:, :32]
    qs = blk[:, 32:96]
    s = blk[:, 96:108].astype(np.uint16)
    d = blk[:, 108:110].copy().view(np.float16).astype(np.float32)[:, 0]
    # scale unpack (aux/kmask form, rewritten per byte): scales i<8 take
    # low 4 bits of byte i; i>=8 take high 4 bits of byte i-8; the top 2
    # bits come from byte 8..11 in 2-bit lanes
    sc = np.empty((blk.shape[0], 16), np.int16)
    for i in range(16):
        if i < 8:
            low = s[:, i] & 0x0F
        else:
            low = s[:, i - 8] >> 4
        hi2 = (s[:, 8 + (i % 4)] >> (2 * (i // 4))) & 3
        sc[:, i] = ((hi2 << 4) | low).astype(np.int16) - 32
    out = np.empty((blk.shape[0], 2, 4, 32), np.float32)
    for half in range(2):
        qsh = qs[:, 32 * half:32 * (half + 1)]
        for j in range(4):
            two = ((qsh >> (2 * j)) & 3).astype(np.float32)
            mbit = 1 << (4 * half + j)
            high = ((hmask & mbit) == 0).astype(np.float32) * 4.0
            q = two - high
            isc = 8 * half + 2 * j
            out[:, half, j, :16] = d[:, None] * sc[:, isc, None] \
                * q[:, :16]
            out[:, half, j, 16:] = d[:, None] * sc[:, isc + 1, None] \
                * q[:, 16:]
    return out.reshape(-1, 256)


def _decode_q2k(blk: np.ndarray):
    """Q2_K blocks [nblk, 84] -> (codes [nblk,256] u8, scales [nblk,16] u8,
    d [nblk] f32, dmin [nblk] f32). ggml block_q2_K layout: scales[16],
    qs[64], d fp16, dmin fp16; value (c*128 + s*32 + l) = (qs[c*32+l]>>2s)&3.
    """
    scales = blk[:, :16]
    qs = blk[:, 16:80].reshape(-1, 2, 32)
    codes = np.stack([(qs >> s) & 3 for s in (0, 2, 4, 6)],
                     axis=2).reshape(-1, 256).astype(np.uint8)
    d = np.ascontiguousarray(blk[:, 80:82]).view(np.float16)[:, 0]
    dmin = np.ascontiguousarray(blk[:, 82:84]).view(np.float16)[:, 0]
    return codes, scales, d.astype(np.float32), dmin.astype(np.float32)


def _decode_iq2_xxs(blk: np.ndarray) -> np.ndarray:
    """block_iq2_xxs {d fp16, qs u16[32]} -> [nblk, 256] f32.

    dequantize_row_iq2_xxs: per 32-value group, 4 bytes of grid indices
    (qs[0..1]) + one u32 (qs[2..3]) holding 4x 7-bit sign indices and a
    4-bit scale; db = d * (0.5 + scale) * 0.25. Grid table is the
    pluggable iq2xxs_grid constant (ops/iq_grids.py)."""
    from bigdl_tpu.ops.iq_grids import require_grid, signs_from_index

    grid = require_grid("iq2xxs_grid")                     # [256, 8]
    d = blk[:, 0:2].copy().view(np.float16).astype(np.float32)[:, 0]
    q2 = np.ascontiguousarray(blk[:, 2:]).view(np.uint16).reshape(-1, 8, 4)
    aux8 = np.ascontiguousarray(q2[:, :, :2]).view(np.uint8) \
        .reshape(-1, 8, 4)                                 # grid indices
    aux32 = (q2[:, :, 2].astype(np.uint32)
             | (q2[:, :, 3].astype(np.uint32) << 16))      # [nblk, 8]
    db = d[:, None] * (0.5 + (aux32 >> 28).astype(np.float32)) * 0.25
    shifts = (np.arange(4, dtype=np.uint32) * 7)[None, None, :]
    sidx = (aux32[:, :, None] >> shifts) & 127             # [nblk, 8, 4]
    signs = signs_from_index(sidx)                         # [nblk, 8, 4, 8]
    mags = grid[aux8]                                      # [nblk, 8, 4, 8]
    vals = db[:, :, None, None] * mags * signs
    return vals.reshape(-1, 256)


def _decode_iq2_xs(blk: np.ndarray) -> np.ndarray:
    """block_iq2_xs {d fp16, qs u16[32], scales u8[8]} -> [nblk, 256].

    dequantize_row_iq2_xs: qs entry = 9-bit grid index | 7-bit sign
    index << 9; scales nibble per 16 values, db = d*(0.5+s)*0.25."""
    from bigdl_tpu.ops.iq_grids import require_grid, signs_from_index

    grid = require_grid("iq2xs_grid")                      # [512, 8]
    d = blk[:, 0:2].copy().view(np.float16).astype(np.float32)[:, 0]
    qs = np.ascontiguousarray(blk[:, 2:66]).view(np.uint16) \
        .reshape(-1, 8, 4)
    scales = blk[:, 66:74]                                 # [nblk, 8]
    db_lo = d[:, None] * (0.5 + (scales & 0x0F).astype(np.float32)) * 0.25
    db_hi = d[:, None] * (0.5 + (scales >> 4).astype(np.float32)) * 0.25
    # l = 0,1 use the low nibble scale; l = 2,3 the high one
    db = np.stack([db_lo, db_lo, db_hi, db_hi], axis=2)    # [nblk, 8, 4]
    mags = grid[qs & 511]                                  # [nblk, 8, 4, 8]
    signs = signs_from_index(qs >> 9)
    vals = db[..., None] * mags * signs
    return vals.reshape(-1, 256)


def _decode_iq1_s(blk: np.ndarray) -> np.ndarray:
    """block_iq1_s {d fp16, qs u8[32], qh u16[8]} -> [nblk, 256].

    dequantize_row_iq1_s: 11-bit grid index = qs[l] | ((qh >> 3l) & 7)
    << 8 into the ternary iq1s_grid; dl = d * (2*((qh>>12)&7) + 1);
    every value shifted by +-IQ1S_DELTA = 0.125 per qh bit 15."""
    from bigdl_tpu.ops.iq_grids import require_grid

    grid = require_grid("iq1s_grid")                       # [2048, 8]
    d = blk[:, 0:2].copy().view(np.float16).astype(np.float32)[:, 0]
    qs = blk[:, 2:34].reshape(-1, 8, 4)                    # [nblk, 8, 4]
    qh = np.ascontiguousarray(blk[:, 34:50]).view(np.uint16)  # [nblk, 8]
    dl = d[:, None] * (2.0 * ((qh >> 12) & 7).astype(np.float32) + 1.0)
    delta = np.where((qh & 0x8000) != 0, -0.125, 0.125).astype(np.float32)
    shifts = (np.arange(4, dtype=np.uint16) * 3)[None, None, :]
    hi3 = ((qh[:, :, None] >> shifts) & 7).astype(np.int32)
    idx = qs.astype(np.int32) | (hi3 << 8)                 # [nblk, 8, 4]
    g = grid[idx]                                          # [nblk, 8, 4, 8]
    vals = dl[:, :, None, None] * (g + delta[:, :, None, None])
    return vals.reshape(-1, 256)


IQ1M_DELTA = 0.0625


def _decode_iq1_m(blk: np.ndarray) -> np.ndarray:
    """block_iq1_m {qs u8[32], qh u8[16], scales u8[8]} -> [nblk, 256].

    dequantize_row_iq1_m: the fp16 super-scale hides in the top nibbles
    of the four scale uint16s; per 32-value sub-block two 3-bit scales
    (dl = d * (2*s+1)) cover 16 values each; the 11-bit grid index is
    qs[l] | low/high qh nibble bits 0-2 << 8, with nibble bit 3 choosing
    the +-IQ1M_DELTA shift per group of 8."""
    from bigdl_tpu.ops.iq_grids import require_grid

    grid = require_grid("iq1s_grid")                       # [2048, 8]
    qs = blk[:, 0:32].reshape(-1, 8, 4)                    # [nblk, 8, 4]
    qh = blk[:, 32:48].reshape(-1, 8, 2)                   # [nblk, 8, 2]
    sc = np.ascontiguousarray(blk[:, 48:56]).view(np.uint16)  # [nblk, 4]
    d16 = ((sc[:, 0] >> 12)
           | ((sc[:, 1] >> 8) & 0x00F0)
           | ((sc[:, 2] >> 4) & 0x0F00)
           | (sc[:, 3] & 0xF000)).astype(np.uint16)
    d = d16.view(np.float16).astype(np.float32)            # [nblk]

    ib = np.arange(8)
    swords = sc[:, ib // 2]                                # [nblk, 8]
    shift = 6 * (ib % 2)
    dl1 = d[:, None] * (2.0 * ((swords >> shift) & 7) + 1.0)
    dl2 = d[:, None] * (2.0 * ((swords >> (shift + 3)) & 7) + 1.0)
    dl = np.stack([dl1, dl1, dl2, dl2], axis=2)            # [nblk, 8, 4]

    # per-group high bits + delta bit ride the qh nibbles: l=0/2 the low
    # nibble, l=1/3 the high one
    nib = np.stack([qh[:, :, 0] & 0x0F, qh[:, :, 0] >> 4,
                    qh[:, :, 1] & 0x0F, qh[:, :, 1] >> 4],
                   axis=2).astype(np.int32)                # [nblk, 8, 4]
    idx = qs.astype(np.int32) | ((nib & 7) << 8)
    delta = np.where((nib & 8) != 0, -IQ1M_DELTA,
                     IQ1M_DELTA).astype(np.float32)
    g = grid[idx]                                          # [nblk, 8, 4, 8]
    vals = dl[..., None] * (g + delta[..., None])
    return vals.reshape(-1, 256)


def _read_str(f: BinaryIO) -> str:
    (n,) = struct.unpack("<Q", f.read(8))
    return f.read(n).decode("utf-8", errors="replace")


def _read_value(f: BinaryIO, vtype: int):
    if vtype in _SCALARS:
        fmt = _SCALARS[vtype]
        (v,) = struct.unpack(fmt, f.read(struct.calcsize(fmt)))
        return v
    if vtype == _T_BOOL:
        return f.read(1) != b"\x00"
    if vtype == _T_STR:
        return _read_str(f)
    if vtype == _T_ARR:
        (etype,) = struct.unpack("<I", f.read(4))
        (count,) = struct.unpack("<Q", f.read(8))
        if etype in _SCALARS:
            fmt = _SCALARS[etype]
            sz = struct.calcsize(fmt)
            raw = f.read(sz * count)
            return list(struct.unpack(f"<{count}{fmt[-1]}", raw))
        return [_read_value(f, etype) for _ in range(count)]
    raise ValueError(f"unknown GGUF value type {vtype}")


class GGUFFile:
    """Parsed GGUF container: metadata KVs + lazily-loaded tensors."""

    def __init__(self, path: str):
        self.path = path
        self.kv: Dict[str, Any] = {}
        # name -> (shape tuple (numpy order, [out, in]), ggml dtype, offset)
        self.tensors: Dict[str, Tuple[Tuple[int, ...], int, int]] = {}
        with open(path, "rb") as f:
            if f.read(4) != GGUF_MAGIC:
                raise ValueError(f"{path}: not a GGUF file")
            (self.version,) = struct.unpack("<I", f.read(4))
            if self.version not in (2, 3):
                raise ValueError(f"GGUF version {self.version} not supported")
            n_tensors, n_kv = struct.unpack("<QQ", f.read(16))
            for _ in range(n_kv):
                key = _read_str(f)
                (vtype,) = struct.unpack("<I", f.read(4))
                self.kv[key] = _read_value(f, vtype)
            order: List[str] = []
            for _ in range(n_tensors):
                name = _read_str(f)
                (nd,) = struct.unpack("<I", f.read(4))
                dims = struct.unpack(f"<{nd}Q", f.read(8 * nd))
                dtype, offset = struct.unpack("<IQ", f.read(12))
                # GGUF dims are innermost-first; numpy shape is the reverse
                self.tensors[name] = (tuple(reversed(dims)), dtype, offset)
                order.append(name)
            align = int(self.kv.get("general.alignment", 32))
            pos = f.tell()
            self.data_start = (pos + align - 1) // align * align

    @property
    def architecture(self) -> str:
        return self.kv.get("general.architecture", "llama")

    def _arch_kv(self, suffix: str, default=None):
        return self.kv.get(f"{self.architecture}.{suffix}", default)

    def hf_config(self) -> Dict[str, Any]:
        """Synthesize an HF-style config dict from GGUF metadata (the
        reference builds an HF model config the same way, gguf/api.py)."""
        arch = self.architecture
        heads = int(self._arch_kv("attention.head_count", 32))
        vocab = len(self.kv.get("tokenizer.ggml.tokens", ())) or None
        if vocab is None and "token_embd.weight" in self.tensors:
            vocab = self.tensors["token_embd.weight"][0][0]
        if arch in ("bloom", "falcon", "mpt"):
            return self._hf_config_nonllama(arch, heads, int(vocab or 0))
        arch_map = {"llama": "LlamaForCausalLM",
                    "mistral": "MistralForCausalLM",
                    "qwen2": "Qwen2ForCausalLM",
                    "mixtral": "MixtralForCausalLM",
                    "baichuan": "BaichuanForCausalLM"}
        cfg = {
            "architectures": [arch_map.get(arch, "LlamaForCausalLM")],
            "model_type": arch,
            "vocab_size": int(vocab or 32000),
            "hidden_size": int(self._arch_kv("embedding_length", 4096)),
            "intermediate_size": int(
                self._arch_kv("feed_forward_length", 11008)),
            "num_hidden_layers": int(self._arch_kv("block_count", 32)),
            "num_attention_heads": heads,
            "num_key_value_heads": int(
                self._arch_kv("attention.head_count_kv", heads)),
            "rms_norm_eps": float(
                self._arch_kv("attention.layer_norm_rms_epsilon", 1e-5)),
            "rope_theta": float(self._arch_kv("rope.freq_base", 10000.0)),
            "max_position_embeddings": int(
                self._arch_kv("context_length", 4096)),
            "tie_word_embeddings": "output.weight" not in self.tensors,
            "bos_token_id": self.kv.get("tokenizer.ggml.bos_token_id"),
            "eos_token_id": self.kv.get("tokenizer.ggml.eos_token_id"),
        }
        if self._arch_kv("expert_count"):
            cfg["num_local_experts"] = int(self._arch_kv("expert_count"))
            cfg["num_experts_per_tok"] = int(
                self._arch_kv("expert_used_count", 2))
            # llama.cpp writes mixtral under arch "llama" with
            # llama.expert_count set — dispatch by the MoE marker. ONLY
            # for the llama-shaped archs: qwen2moe/deepseek2/dbrx-style
            # MoE GGUFs carry shared-expert tensors and different
            # routing the mixtral family would silently drop.
            if arch in ("llama", "mistral", "mixtral"):
                cfg["architectures"] = ["MixtralForCausalLM"]
                cfg["model_type"] = "mixtral"
        return cfg

    def _hf_config_nonllama(self, arch: str, heads: int,
                            vocab: int) -> Dict[str, Any]:
        """HF-style config for the non-llama GGUF archs the reference
        also maps (reference transformers/gguf/api.py:31-70 dispatching
        to gguf/models/{bloom,falcon,mpt}.py). Keys match what each
        family's config_from_hf reads (models/families.py), so one
        synthesis feeds the existing converter configs."""
        d = int(self._arch_kv("embedding_length", 4096))
        L = int(self._arch_kv("block_count", 24))
        ff = int(self._arch_kv("feed_forward_length", 4 * d))
        eps = float(self._arch_kv("attention.layer_norm_epsilon", 1e-5))
        hkv = int(self._arch_kv("attention.head_count_kv", heads) or heads)
        tie = "output.weight" not in self.tensors
        common = {
            "model_type": arch,
            "bos_token_id": self.kv.get("tokenizer.ggml.bos_token_id"),
            "eos_token_id": self.kv.get("tokenizer.ggml.eos_token_id"),
        }
        if arch == "bloom":
            return {**common,
                    "architectures": ["BloomForCausalLM"],
                    "vocab_size": vocab, "hidden_size": d,
                    "intermediate_size": ff,
                    "n_head": heads, "n_layer": L,
                    "layer_norm_epsilon": eps}
        if arch == "falcon":
            return {**common,
                    "architectures": ["FalconForCausalLM"],
                    "vocab_size": vocab, "hidden_size": d,
                    "intermediate_size": ff,
                    "num_attention_heads": heads,
                    "num_hidden_layers": L,
                    "layer_norm_epsilon": eps,
                    "multi_query": hkv == 1,
                    # 40b/180b new_decoder_architecture: grouped KV +
                    # attn_norm_2 — the family converter rejects it
                    # loudly, same as the HF path
                    "new_decoder_architecture": 1 < hkv < heads,
                    "parallel_attn": True,
                    "bias": any(t.endswith("attn_qkv.bias")
                                for t in self.tensors),
                    "rope_theta": float(
                        self._arch_kv("rope.freq_base", 10000.0)),
                    "max_position_embeddings": int(
                        self._arch_kv("context_length", 2048)),
                    "tie_word_embeddings": tie}
        # mpt
        return {**common,
                "architectures": ["MPTForCausalLM"],
                "vocab_size": vocab, "d_model": d,
                "n_heads": heads, "n_layers": L,
                "expansion_ratio": max(ff // d, 1),
                "max_seq_len": int(self._arch_kv("context_length", 2048))}

    def tokenizer_info(self) -> Dict[str, Any]:
        """Raw vocab for tokenizer reconstruction."""
        return {
            "model": self.kv.get("tokenizer.ggml.model"),
            "tokens": self.kv.get("tokenizer.ggml.tokens"),
            "scores": self.kv.get("tokenizer.ggml.scores"),
            "token_type": self.kv.get("tokenizer.ggml.token_type"),
            "merges": self.kv.get("tokenizer.ggml.merges"),
            "bos_token_id": self.kv.get("tokenizer.ggml.bos_token_id"),
            "eos_token_id": self.kv.get("tokenizer.ggml.eos_token_id"),
        }

    # -- raw tensor access ---------------------------------------------------

    def _raw(self, name: str) -> Tuple[np.ndarray, Tuple[int, ...], int]:
        shape, dtype, offset = self.tensors[name]
        if dtype not in _BLOCK:
            raise ValueError(
                f"{name}: ggml dtype {dtype} not supported "
                f"(supported: {sorted(_BLOCK)})")
        block, bpb = _BLOCK[dtype]
        nvals = int(np.prod(shape))
        nbytes = nvals // block * bpb
        mm = np.memmap(self.path, mode="r", dtype=np.uint8,
                       offset=self.data_start + offset, shape=(nbytes,))
        return np.asarray(mm), shape, dtype

    def load_dense(self, name: str, dtype=np.float32) -> np.ndarray:
        """Load any supported tensor fully dequantized to numpy [*shape]."""
        raw, shape, gt = self._raw(name)
        if gt == GGML_F32:
            return raw.view(np.float32).reshape(shape).astype(dtype)
        if gt == GGML_F16:
            return raw.view(np.float16).reshape(shape).astype(dtype)
        if gt == GGML_BF16:
            u = raw.view(np.uint16).astype(np.uint32) << 16
            return u.view(np.float32).reshape(shape).astype(dtype)
        n, k = shape[0], int(np.prod(shape[1:]))
        block, bpb = _BLOCK[gt]
        blk = raw.reshape(n * k // block, bpb)
        if gt == GGML_Q8_0:
            d = blk[:, :2].copy().view(np.float16).astype(np.float32)
            q = blk[:, 2:].view(np.int8).astype(np.float32)
            return (q * d).reshape(shape).astype(dtype)
        if gt in (GGML_Q4_0, GGML_Q4_1):
            hdr = 2 if gt == GGML_Q4_0 else 4
            qs = blk[:, hdr:]
            lo = (qs & 0x0F).astype(np.float32)
            hi = (qs >> 4).astype(np.float32)
            q = np.concatenate([lo, hi], axis=1)      # split-block order
            d = blk[:, :2].copy().view(np.float16).astype(np.float32)
            if gt == GGML_Q4_0:
                vals = (q - 8.0) * d
            else:
                m = blk[:, 2:4].copy().view(np.float16).astype(np.float32)
                vals = q * d + m
            return vals.reshape(shape).astype(dtype)
        if gt == GGML_Q2_K:
            codes, scales, d, dmin = _decode_q2k(blk)
            sc = (scales & 0x0F).astype(np.float32)        # [nblk, 16]
            m = (scales >> 4).astype(np.float32)
            sc_r = np.repeat(sc, 16, axis=1)               # [nblk, 256]
            m_r = np.repeat(m, 16, axis=1)
            vals = (d[:, None] * sc_r * codes.astype(np.float32)
                    - dmin[:, None] * m_r)
            return vals.reshape(shape).astype(dtype)
        if gt == GGML_Q3_K:
            return _decode_q3k(blk).reshape(shape).astype(dtype)
        if gt == GGML_Q4_K:
            return _decode_q4k(blk).reshape(shape).astype(dtype)
        if gt == GGML_Q5_K:
            return _decode_q5k(blk).reshape(shape).astype(dtype)
        if gt == GGML_Q6_K:
            return _decode_q6k(blk).reshape(shape).astype(dtype)
        if gt == GGML_IQ2_XXS:
            return _decode_iq2_xxs(blk).reshape(shape).astype(dtype)
        if gt == GGML_IQ2_XS:
            return _decode_iq2_xs(blk).reshape(shape).astype(dtype)
        if gt == GGML_IQ1_S:
            return _decode_iq1_s(blk).reshape(shape).astype(dtype)
        if gt == GGML_IQ1_M:
            return _decode_iq1_m(blk).reshape(shape).astype(dtype)
        if gt in (GGML_Q5_0, GGML_Q5_1):
            hdr = 2 if gt == GGML_Q5_0 else 4
            qh = blk[:, hdr:hdr + 4].copy().view(np.uint32)[:, 0]
            qs = blk[:, hdr + 4:]
            lo4 = (qs & 0x0F).astype(np.uint8)
            hi4 = (qs >> 4).astype(np.uint8)
            bits = ((qh[:, None] >> np.arange(32, dtype=np.uint32)[None, :])
                    & 1).astype(np.uint8)             # [nblk, 32]
            q = np.concatenate([lo4, hi4], axis=1) | (bits << 4)
            q = q.astype(np.float32)
            d = blk[:, :2].copy().view(np.float16).astype(np.float32)
            if gt == GGML_Q5_0:
                vals = (q - 16.0) * d
            else:
                m = blk[:, 2:4].copy().view(np.float16).astype(np.float32)
                vals = q * d + m
            return vals.reshape(shape).astype(dtype)
        raise AssertionError(gt)

    def load_qtensor(self, name: str):
        """Load a 2-D quantized weight as a QTensor [K, N], bit-faithfully.

        GGUF stores linear weights [out=N, in=K] with blocks along K; our
        contraction-major layout is the byte-level transpose of that.
        """
        import jax.numpy as jnp

        from bigdl_tpu.ops.quant import QTensor

        raw, shape, gt = self._raw(name)
        if gt not in _GGML_TO_QTYPE:
            raise ValueError(f"{name}: ggml dtype {gt} is not a supported "
                             "quantized type for direct repack")
        if len(shape) != 2:
            raise ValueError(f"{name}: expected 2-D weight, got {shape}")
        n, k = shape          # ggml [out, in] -> ours [K=in, N=out]
        block, bpb = _BLOCK[gt]
        nblk = k // block
        blk = raw.reshape(n, nblk, bpb)
        qtype = _GGML_TO_QTYPE[gt]

        def f16(sl):
            return np.ascontiguousarray(sl).view(np.float16)[..., 0]

        if gt == GGML_Q2_K:
            # decode codes in ggml order, re-encode into our 4-plane layout
            codes, scales, d, dmin = _decode_q2k(blk.reshape(-1, bpb))
            codes = codes.reshape(n, nblk, 256)
            # ours: byte j of a 256-block holds values j, j+64, j+128, j+192
            planes = codes.reshape(n, nblk, 4, 64)
            packed = (planes[:, :, 0] | (planes[:, :, 1] << 2)
                      | (planes[:, :, 2] << 4) | (planes[:, :, 3] << 6))
            data = packed.reshape(n, k // 4).T             # [K/4, N]
            aux = scales.reshape(n, k // 16).T             # [K/16, N]
            return QTensor(
                jnp.asarray(np.ascontiguousarray(data)),
                jnp.asarray(d.reshape(n, nblk).T).astype(jnp.bfloat16),
                jnp.asarray(dmin.reshape(n, nblk).T).astype(jnp.bfloat16),
                "q2_k", (k, n),
                aux=jnp.asarray(np.ascontiguousarray(aux)))
        if gt == GGML_Q8_0:
            d = f16(blk[:, :, 0:2])                    # [N, nblk]
            q = blk[:, :, 2:].view(np.int8)            # [N, nblk, 32]
            data = q.reshape(n, k).T                   # [K, N] int8
            return QTensor(jnp.asarray(np.ascontiguousarray(data)),
                           jnp.asarray(d.T).astype(jnp.bfloat16),
                           None, qtype, (k, n))

        hdr = {GGML_Q4_0: 2, GGML_Q4_1: 4, GGML_Q5_0: 2, GGML_Q5_1: 4}[gt]
        has_min = gt in (GGML_Q4_1, GGML_Q5_1)
        has_high = gt in (GGML_Q5_0, GGML_Q5_1)
        d = f16(blk[:, :, 0:2])
        m = f16(blk[:, :, 2:4]) if has_min else None
        qs_off = hdr + (4 if has_high else 0)
        qs = blk[:, :, qs_off:]                        # [N, nblk, block//2]
        # ggml qs byte j of a block packs values (j, j+block/2) — identical
        # to our split-block scheme, so the packed plane is a transpose:
        data = qs.transpose(1, 2, 0).reshape(k // 2, n)
        out = {
            "data": jnp.asarray(np.ascontiguousarray(data)),
            "scale": jnp.asarray(d.T).astype(jnp.bfloat16),
            "zero": (jnp.asarray(m.T).astype(jnp.bfloat16)
                     if has_min else None),
            "aux": None,
        }
        if has_high:
            qh = blk[:, :, hdr:hdr + 4]                # [N, nblk, 4] LE u32
            # bit j of byte i == high bit of value 8i+j — our plane layout
            aux = qh.transpose(1, 2, 0).reshape(k // 8, n)
            out["aux"] = jnp.asarray(np.ascontiguousarray(aux))
        return QTensor(out["data"], out["scale"], out["zero"], qtype,
                       (k, n), aux=out["aux"])


# ---------------------------------------------------------------------------
# Model import: GGUF -> family parameter pytree
# ---------------------------------------------------------------------------

# GGUF blk.* tensor names -> our generalized-decoder pytree keys
# (shared by llama-shaped archs AND the non-llama archs the reference
# maps: baichuan writes llama-style attn_q/k/v; bloom/falcon/mpt write
# a fused attn_qkv handled separately in load_gguf)
_LLAMA_MAP = {
    "attn_q": "q_proj", "attn_k": "k_proj", "attn_v": "v_proj",
    "attn_output": "o_proj", "ffn_gate": "gate_proj", "ffn_up": "up_proj",
    "ffn_down": "down_proj", "attn_norm": "input_layernorm",
    "ffn_norm": "post_attention_layernorm",
}
_NORM_KEYS = {"input_layernorm", "post_attention_layernorm"}


def load_gguf(path: str, compute_dtype=None):
    """Load a llama-family GGUF checkpoint.

    Returns (params, hf_config, tokenizer_info). Quantized weights become
    QTensors via bit-faithful repack; f16/f32 weights become dense
    compute_dtype (default bfloat16) leaves.
    """
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.ops.quant import QTensor, split_qtensor_n

    if compute_dtype is None:
        compute_dtype = jnp.bfloat16
    gf = GGUFFile(path)
    hf_config = gf.hf_config()
    # layer count straight from the GGUF metadata — the synthesized
    # config spells it per-arch (n_layer / n_layers / num_hidden_layers)
    L = int(gf._arch_kv("block_count",
                        hf_config.get("num_hidden_layers", 32)))
    n_exp = int(gf._arch_kv("expert_count") or 0)
    moe = n_exp > 0
    if moe and gf.architecture not in ("llama", "mistral", "mixtral"):
        raise NotImplementedError(
            f"GGUF arch {gf.architecture!r} is an MoE family with "
            "shared-expert/routing tensors this importer does not map "
            "(only mixtral-style llama-arch MoE is supported); load the "
            "original HF checkpoint instead")

    params: Dict[str, Any] = {}
    layer_acc: Dict[str, list] = {}
    # MoE expert accumulators: key -> [L][E] entries (old-style
    # per-expert 2D tensors, repacked bit-faithfully)
    expert_acc: Dict[str, list] = {}
    _EXP_MAP = {"ffn_gate": "experts_gate", "ffn_up": "experts_up",
                "ffn_down": "experts_down"}

    def cvt(name: str, want_linear: bool):
        _, gt, _ = (gf.tensors[name][0], gf.tensors[name][1],
                    gf.tensors[name][2])
        if want_linear and gt in _GGML_TO_QTYPE:
            return gf.load_qtensor(name)
        dense = gf.load_dense(name, np.float32)
        if want_linear:
            dense = dense.T    # [out, in] -> contraction-major [in, out]
        return jnp.asarray(dense).astype(compute_dtype)

    heads = int(gf._arch_kv("attention.head_count", 32))
    hkv = int(gf._arch_kv("attention.head_count_kv", heads) or heads)
    hd = int(gf._arch_kv("embedding_length", 4096)) // heads
    qkv_sizes = [heads * hd, hkv * hd, hkv * hd]

    _TOP = {  # exact-name top-level tensors -> pytree keys
        "output_norm.weight": "norm", "output_norm.bias": "norm_bias",
        "token_embd_norm.weight": "embed_norm",
        "token_embd_norm.bias": "embed_norm_bias",
    }

    for name in gf.tensors:
        if name == "token_embd.weight":
            params["embed_tokens"] = jnp.asarray(
                gf.load_dense(name, np.float32)).astype(compute_dtype)
        elif name in _TOP:
            params[_TOP[name]] = jnp.asarray(
                gf.load_dense(name, np.float32)).astype(compute_dtype)
        elif name == "output.weight":
            params["lm_head"] = cvt(name, True)
        elif name.startswith("blk."):
            parts = name.split(".")
            idx = int(parts[1])
            base = parts[2]
            if base == "attn_qkv":
                # bloom/falcon/mpt fused QKV: llama.cpp's converters
                # write CONTIGUOUS [Q; K; V] output rows (e.g. bloom's
                # per-head interleave is reordered at convert time), so
                # a row split is exact; quantized tensors split along N
                # of the contraction-major QTensor (block-safe).
                leaf = parts[3]
                if leaf == "bias":
                    b = gf.load_dense(name, np.float32)
                    off = 0
                    for key, sz in zip(("q_proj_bias", "k_proj_bias",
                                        "v_proj_bias"), qkv_sizes):
                        layer_acc.setdefault(key, [None] * L)[idx] = \
                            jnp.asarray(b[off:off + sz]).astype(
                                compute_dtype)
                        off += sz
                else:
                    val = cvt(name, True)
                    if isinstance(val, QTensor):
                        qs = split_qtensor_n(val, qkv_sizes)
                    else:
                        qs, off = [], 0
                        for sz in qkv_sizes:
                            qs.append(val[:, off:off + sz])
                            off += sz
                    for key, v in zip(("q_proj", "k_proj", "v_proj"), qs):
                        layer_acc.setdefault(key, [None] * L)[idx] = v
                continue
            if moe and base == "ffn_gate_inp":
                # router [E, D] -> contraction-major [D, E], full precision
                layer_acc.setdefault("router", [None] * L)[idx] = \
                    jnp.asarray(gf.load_dense(name, np.float32).T
                                ).astype(compute_dtype)
                continue
            if moe and base.endswith("_exps"):
                # fused 3D expert stack [E, out, in] (modern llama.cpp);
                # dequantize-on-load, per-expert transpose to [E, in, out]
                key = _EXP_MAP.get(base[:-5])
                if key is None:
                    continue
                dense = gf.load_dense(name, np.float32)
                layer_acc.setdefault(key, [None] * L)[idx] = jnp.asarray(
                    np.ascontiguousarray(dense.transpose(0, 2, 1))
                ).astype(compute_dtype)
                continue
            if moe and base in _EXP_MAP and len(parts) == 5 \
                    and parts[3].isdigit():
                # old-style per-expert tensors blk.N.ffn_gate.E.weight
                key = _EXP_MAP[base]
                eidx = int(parts[3])
                row = expert_acc.setdefault(key, [
                    [None] * n_exp for _ in range(L)])
                row[idx][eidx] = cvt(name, True)
                continue
            leaf = parts[3]
            if base not in _LLAMA_MAP:
                continue
            key = _LLAMA_MAP[base]
            if leaf == "bias":
                key = f"{key}_bias"
                val = jnp.asarray(
                    gf.load_dense(name, np.float32)).astype(compute_dtype)
            elif key in _NORM_KEYS:
                val = jnp.asarray(
                    gf.load_dense(name, np.float32)).astype(compute_dtype)
            else:
                val = cvt(name, True)
            layer_acc.setdefault(key, [None] * L)[idx] = val

    # stack old-style per-expert entries into [E, ...] trees per layer
    for key, rows in expert_acc.items():
        stacked = []
        for li, row in enumerate(rows):
            if any(x is None for x in row):
                raise ValueError(
                    f"GGUF layer {li}: missing expert tensors for {key}")
            stacked.append(jax.tree.map(lambda *xs: jnp.stack(xs), *row))
        layer_acc[key] = stacked

    if moe:
        required = {"q_proj", "k_proj", "v_proj", "o_proj", "router",
                    "experts_gate", "experts_up", "experts_down",
                    "input_layernorm", "post_attention_layernorm"}
    else:
        required = {"q_proj", "k_proj", "v_proj", "o_proj",
                    "up_proj", "down_proj", "input_layernorm"}
        # family shape decides the rest: non-gated archs (bloom/
        # falcon/mpt) have no ffn_gate; falcon's single shared norm
        # has no ffn_norm. Unknown archs fall back to the llama shape;
        # a config-synthesis failure for a KNOWN family must surface
        # as itself, not as a bogus missing-tensor report.
        from bigdl_tpu.models.registry import get_family

        try:
            fam = get_family(hf_config["architectures"][0], hf_config)
        except ValueError:          # unsupported architecture
            fam = None
        if fam is None:
            required |= {"gate_proj", "post_attention_layernorm"}
        else:
            fam_cfg = fam.config_from_hf(hf_config)
            if getattr(fam_cfg, "mlp_gated", True):
                required.add("gate_proj")
            if not getattr(fam_cfg, "shared_input_norm", False):
                required.add("post_attention_layernorm")
    missing = sorted(
        (required - set(layer_acc))
        | {k for k, v in layer_acc.items() if any(x is None for x in v)})
    if missing or "embed_tokens" not in params:
        raise ValueError(
            f"GGUF missing tensors for: {missing or ['token_embd']} "
            f"(arch {gf.architecture!r})")
    params["layers"] = {
        key: jax.tree.map(lambda *xs: jnp.stack(xs), *v)
        for key, v in layer_acc.items()
    }
    return params, hf_config, gf.tokenizer_info()


# ---------------------------------------------------------------------------
# Minimal GGUF writer (tests + export to the llama.cpp ecosystem)
# ---------------------------------------------------------------------------


def _write_str(f: BinaryIO, s: str) -> None:
    b = s.encode("utf-8")
    f.write(struct.pack("<Q", len(b)))
    f.write(b)


def _write_kv(f: BinaryIO, key: str, value: Any) -> None:
    _write_str(f, key)
    if isinstance(value, bool):
        f.write(struct.pack("<I", _T_BOOL))
        f.write(b"\x01" if value else b"\x00")
    elif isinstance(value, int):
        f.write(struct.pack("<Ii", _T_I32, value))
    elif isinstance(value, float):
        f.write(struct.pack("<If", _T_F32, value))
    elif isinstance(value, str):
        f.write(struct.pack("<I", _T_STR))
        _write_str(f, value)
    elif isinstance(value, (list, tuple)):
        f.write(struct.pack("<I", _T_ARR))
        if value and isinstance(value[0], str):
            f.write(struct.pack("<IQ", _T_STR, len(value)))
            for s in value:
                _write_str(f, s)
        elif value and isinstance(value[0], float):
            f.write(struct.pack("<IQ", _T_F32, len(value)))
            f.write(struct.pack(f"<{len(value)}f", *value))
        else:
            f.write(struct.pack("<IQ", _T_I32, len(value)))
            f.write(struct.pack(f"<{len(value)}i", *value))
    else:
        raise TypeError(f"cannot write KV {key}={value!r}")


def _safe_inv_np(d: np.ndarray) -> np.ndarray:
    df = d.astype(np.float32)
    return np.where(df == 0, 0.0, 1.0 / np.where(df == 0, 1.0, df))


def _quantize_block_np(w: np.ndarray, gt: int) -> np.ndarray:
    """numpy ggml block quantizer for the writer (q4_0/q4_1/q5_0/q5_1/
    q8_0 — the same formats the reader imports bit-faithfully, so
    write -> read round-trips exactly). w: [N, K] f32."""
    n, k = w.shape
    blk = w.reshape(n * k // 32, 32)
    nb = blk.shape[0]

    def signed_absmax():
        amax_i = np.argmax(np.abs(blk), axis=1)
        return blk[np.arange(nb), amax_i]

    F16_MAX = 65504.0

    def f16(x):                                # clamp: f16 overflow would
        return np.clip(x, -F16_MAX, F16_MAX).astype(np.float16)   # inf the

    def pack_split_nibbles(q):                 # value j -> low nibble of
        return (q[:, :16] & 0x0F) | (q[:, 16:] << 4)   # byte j; j+16 high

    def high_bit_plane(q5):                    # qh bit i = bit4 of value i
        bits = (q5 >> 4).astype(np.uint32)
        return (bits << np.arange(32, dtype=np.uint32)[None, :]).sum(
            axis=1, dtype=np.uint32)

    if gt == GGML_Q4_0:
        d = f16(signed_absmax() / -8.0)
        q = np.clip(np.round(blk * _safe_inv_np(d)[:, None]) + 8,
                    0, 15).astype(np.uint8)
        out = np.empty((nb, 18), np.uint8)
        out[:, :2] = d[:, None].view(np.uint8)
        out[:, 2:] = pack_split_nibbles(q)
        return out.reshape(-1)
    if gt == GGML_Q4_1:
        mn = blk.min(axis=1)
        d = f16((blk.max(axis=1) - mn) / 15.0)
        q = np.clip(np.round((blk - mn[:, None])
                             * _safe_inv_np(d)[:, None]),
                    0, 15).astype(np.uint8)
        out = np.empty((nb, 20), np.uint8)
        out[:, :2] = d[:, None].view(np.uint8)
        out[:, 2:4] = f16(mn)[:, None].view(np.uint8)
        out[:, 4:] = pack_split_nibbles(q)
        return out.reshape(-1)
    if gt == GGML_Q5_0:
        d = f16(signed_absmax() / -16.0)
        q = np.clip(np.round(blk * _safe_inv_np(d)[:, None]) + 16,
                    0, 31).astype(np.uint8)
        out = np.empty((nb, 22), np.uint8)
        out[:, :2] = d[:, None].view(np.uint8)
        out[:, 2:6] = high_bit_plane(q)[:, None].view(np.uint8)
        out[:, 6:] = pack_split_nibbles(q & 0x0F)
        return out.reshape(-1)
    if gt == GGML_Q5_1:
        mn = blk.min(axis=1)
        d = f16((blk.max(axis=1) - mn) / 31.0)
        q = np.clip(np.round((blk - mn[:, None])
                             * _safe_inv_np(d)[:, None]),
                    0, 31).astype(np.uint8)
        out = np.empty((nb, 24), np.uint8)
        out[:, :2] = d[:, None].view(np.uint8)
        out[:, 2:4] = f16(mn)[:, None].view(np.uint8)
        out[:, 4:8] = high_bit_plane(q)[:, None].view(np.uint8)
        out[:, 8:] = pack_split_nibbles(q & 0x0F)
        return out.reshape(-1)
    if gt == GGML_Q8_0:
        d = f16(signed_absmax() / -128.0)
        q = np.clip(np.round(blk * _safe_inv_np(d)[:, None]),
                    -128, 127).astype(np.int8)
        out = np.empty((nb, 34), np.uint8)
        out[:, :2] = d[:, None].view(np.uint8)
        out[:, 2:] = q.view(np.uint8)
        return out.reshape(-1)
    raise ValueError(f"writer does not support ggml dtype {gt}")


def write_gguf(
    path: str,
    kv: Dict[str, Any],
    tensors: Dict[str, tuple],   # name -> (f32 [out,in], ggml dtype)
                                 #      or (raw_u8, ggml dtype, shape)
    alignment: int = 32,
) -> None:
    """Write a GGUF v3 file. Tensors are given dense f32 and encoded to the
    requested ggml dtype (F32/F16/BF16/Q4_0/Q4_1/Q5_0/Q5_1/Q8_0). A
    3-tuple entry (raw_uint8, ggml_dtype, logical_shape) passes an
    ALREADY-PACKED payload through untouched (k-quants and other formats
    the encoder does not produce)."""
    payloads: List[bytes] = []
    infos: List[Tuple[str, Tuple[int, ...], int, int]] = []
    offset = 0
    for name, spec in tensors.items():
        if len(spec) == 3:
            raw, gt, shape = spec
            raw = np.asarray(raw, np.uint8)
            block, bpb = _BLOCK[gt]
            nvals = int(np.prod(shape))
            if nvals % block or raw.size * block != nvals * bpb:
                raise ValueError(
                    f"{name}: raw payload {raw.size}B does not match "
                    f"shape {shape} for ggml dtype {gt} "
                    f"(block {block}, {bpb}B/block)")
            data = raw.tobytes()
            shape = tuple(shape)
        else:
            arr, gt = spec
            arr = np.asarray(arr, np.float32)
            shape = arr.shape
            if gt == GGML_F32:
                data = arr.astype(np.float32).tobytes()
            elif gt == GGML_F16:
                data = arr.astype(np.float16).tobytes()
            elif gt == GGML_BF16:
                f = arr.astype(np.float32)
                u = f.view(np.uint32)
                # round-to-nearest-even into the top 16 bits; NaN must not
                # round into the Inf encoding (0x7F80) — emit canonical qNaN
                r = ((u + 0x7FFF + ((u >> 16) & 1)) >> 16).astype(np.uint16)
                r = np.where(np.isnan(f), np.uint16(0x7FC0), r)
                data = r.tobytes()
            elif gt in (GGML_Q4_0, GGML_Q4_1, GGML_Q5_0, GGML_Q5_1,
                        GGML_Q8_0):
                data = _quantize_block_np(
                    arr.reshape(arr.shape[0], -1), gt).tobytes()
            else:
                raise ValueError(
                    f"writer does not support ggml dtype {gt}")
        infos.append((name, shape, gt, offset))
        payloads.append(data)
        offset += len(data)
        pad = (-offset) % alignment
        if pad:
            payloads.append(b"\x00" * pad)
            offset += pad

    with open(path, "wb") as f:
        f.write(GGUF_MAGIC)
        f.write(struct.pack("<I", 3))
        f.write(struct.pack("<QQ", len(infos), len(kv) + 1))
        _write_kv(f, "general.alignment", alignment)
        for key, value in kv.items():
            _write_kv(f, key, value)
        for name, shape, gt, off in infos:
            _write_str(f, name)
            dims = tuple(reversed(shape))
            f.write(struct.pack("<I", len(dims)))
            f.write(struct.pack(f"<{len(dims)}Q", *dims))
            f.write(struct.pack("<IQ", gt, off))
        pos = f.tell()
        f.write(b"\x00" * ((-pos) % alignment))
        for p in payloads:
            f.write(p)
