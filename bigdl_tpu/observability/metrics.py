"""Dependency-free metrics registry with Prometheus text exposition.

The reference stack's only runtime telemetry is BenchmarkWrapper's
per-token wall clocks (reference dev/benchmark/benchmark_util.py) — no
counters, no scrape endpoint. This module is the substrate the serving
path (serving/engine.py), speculative decoding (speculative.py), the
kernel dispatch probes (ops/probing.py) and the bench harnesses report
through: Counter / Gauge / Histogram with labels, thread-safe, rendered
in the Prometheus text exposition format by ``MetricsRegistry.render()``
and as JSON by ``snapshot()`` / ``summary()``.

Deliberately stdlib-only (no prometheus_client, no numpy): it is
imported inside the engine's hot step loop and must never add a
dependency or measurable overhead. An observe/inc is a lock + a bisect
over a fixed bucket list.

Metric families are get-or-create: asking the registry for an existing
name returns the existing family (kind and labelnames must match), so
every subsystem can declare the metrics it touches without coordinating
module import order.

Canonical serving metric names (emitted by serving/engine.py; see that
module and observability/__init__ for the field mapping):

    bigdl_tpu_request_phase_seconds{phase=queue|prefill|decode}  histogram
    bigdl_tpu_ttft_seconds                                       histogram
    bigdl_tpu_tpot_seconds                                       histogram
    bigdl_tpu_slot_occupancy / bigdl_tpu_queue_depth             gauge
    bigdl_tpu_admissions_total / bigdl_tpu_preemptions_total     counter
    bigdl_tpu_stall_guard_trips_total                            counter
    bigdl_tpu_requests_finished_total{reason=...}                counter
    bigdl_tpu_engine_steps_total / bigdl_tpu_tokens_generated_total
    bigdl_tpu_kernel_probe_total{kernel=...,outcome=...}         counter
    bigdl_tpu_spec_accept_ratio{mode=draft|lookup}               histogram
    bigdl_tpu_spec_round_seconds{mode=...}                       histogram
    bigdl_tpu_spec_tokens_total{mode=...,kind=drafted|accepted}  counter
    bigdl_tpu_requests_quarantined_total{reason=nan_logits|crash_loop}
    bigdl_tpu_step_retries_total                                 counter
    bigdl_tpu_faults_injected_total{kind=...}                    counter
    bigdl_tpu_engine_draining                                    gauge
"""

from __future__ import annotations

import bisect
import math
import re
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# Fixed log-spaced latency buckets (seconds): third-of-a-decade steps
# from 100 us to 100 s. Latencies in this stack span host sampling
# (~100 us) to a cold 7B prefill over the tunnel (~10 s), so a fixed
# log grid keeps every phase resolvable with one bucket list.
LATENCY_BUCKETS_S: Tuple[float, ...] = tuple(
    round(10.0 ** (e / 3.0), 6) for e in range(-12, 7))

# Acceptance-rate style ratios live in [0, 1]; linear decile buckets.
RATIO_BUCKETS: Tuple[float, ...] = tuple(
    round(i / 10.0, 1) for i in range(1, 11))

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def _escape_label(value: str) -> str:
    return (str(value).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r"\""))


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _series(name: str, labelnames: Sequence[str], labelvalues: Sequence[str],
            extra: Tuple[str, str] = ()) -> str:
    pairs = list(zip(labelnames, labelvalues))
    if extra:
        pairs.append(extra)
    if not pairs:
        return name
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in pairs)
    return f"{name}{{{inner}}}"


class _CounterChild:
    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters can only increase")
        with self._lock:
            self.value += amount


class _GaugeChild:
    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value -= amount


class _HistogramChild:
    __slots__ = ("_lock", "buckets", "counts", "sum", "count")

    def __init__(self, buckets: Tuple[float, ...]):
        self._lock = threading.Lock()
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)     # +1 for +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        value = float(value)
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self.counts[idx] += 1
            self.sum += value
            self.count += 1

    def quantile(self, q: float) -> float:
        """Bucket-interpolated quantile estimate (0 when empty)."""
        with self._lock:
            total = self.count
            counts = list(self.counts)
        if total == 0:
            return 0.0
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            if cum + c >= rank and c > 0:
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = (self.buckets[i] if i < len(self.buckets)
                      else self.buckets[-1])
                frac = (rank - cum) / c
                return lo + (hi - lo) * frac
            cum += c
        return self.buckets[-1]


_CHILD_TYPES = {"counter": _CounterChild, "gauge": _GaugeChild,
                "histogram": _HistogramChild}


class MetricFamily:
    """One named metric with zero or more label dimensions.

    Unlabeled families expose the child API (inc/set/observe) directly;
    labeled families hand out children via ``labels(...)``.
    """

    def __init__(self, name: str, help: str, kind: str,
                 labelnames: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r}")
        if kind not in _CHILD_TYPES:
            raise ValueError(f"unknown metric kind {kind!r}")
        self.name = name
        self.help = help
        self.kind = kind
        self.labelnames = tuple(labelnames)
        if kind == "histogram":
            bk = tuple(sorted(float(b) for b in (buckets or
                                                 LATENCY_BUCKETS_S)))
            if not bk:
                raise ValueError("histogram needs at least one bucket")
            self.buckets = bk
        else:
            self.buckets = None
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        if not self.labelnames:
            self._children[()] = self._new_child()

    def _new_child(self):
        if self.kind == "histogram":
            return _HistogramChild(self.buckets)
        return _CHILD_TYPES[self.kind]()

    def labels(self, *values) -> object:
        vals = tuple(str(v) for v in values)
        if len(vals) != len(self.labelnames):
            raise ValueError(
                f"{self.name} takes {len(self.labelnames)} label value(s) "
                f"{self.labelnames}, got {len(vals)}")
        with self._lock:
            child = self._children.get(vals)
            if child is None:
                child = self._children[vals] = self._new_child()
        return child

    # -- unlabeled passthrough ----------------------------------------------

    def _default(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} is labeled {self.labelnames}; use .labels()")
        # safe unlocked: an unlabeled family materializes its sole ()
        # child in __init__ and labels() (the only _children writer)
        # rejects unlabeled use, so this dict never changes after
        # construction
        return self._children[()]  # graftlint: disable=lock-guarded-unlocked

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)

    def set(self, value: float) -> None:
        self._default().set(value)

    def observe(self, value: float) -> None:
        self._default().observe(value)

    def children(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return sorted(self._children.items())


class MetricsRegistry:
    """Thread-safe, get-or-create registry of MetricFamily objects."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, MetricFamily] = {}

    def _get_or_create(self, name: str, help: str, kind: str,
                       labelnames: Sequence[str],
                       buckets: Optional[Sequence[float]]) -> MetricFamily:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}, not {kind}")
                if fam.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered with labels "
                        f"{fam.labelnames}, not {tuple(labelnames)}")
                return fam
            fam = MetricFamily(name, help, kind, labelnames, buckets)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> MetricFamily:
        return self._get_or_create(name, help, "counter", labelnames, None)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> MetricFamily:
        return self._get_or_create(name, help, "gauge", labelnames, None)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> MetricFamily:
        return self._get_or_create(name, help, "histogram", labelnames,
                                   buckets)

    def families(self) -> List[MetricFamily]:
        with self._lock:
            return [self._families[k] for k in sorted(self._families)]

    # -- exposition ---------------------------------------------------------

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        out: List[str] = []
        for fam in self.families():
            if fam.help:
                out.append(f"# HELP {fam.name} "
                           + fam.help.replace("\\", r"\\")
                           .replace("\n", r"\n"))
            out.append(f"# TYPE {fam.name} {fam.kind}")
            for vals, child in fam.children():
                if fam.kind == "histogram":
                    cum = 0
                    for i, ub in enumerate(fam.buckets):
                        cum += child.counts[i]
                        out.append(_series(
                            fam.name + "_bucket", fam.labelnames, vals,
                            ("le", _fmt(ub))) + f" {cum}")
                    out.append(_series(
                        fam.name + "_bucket", fam.labelnames, vals,
                        ("le", "+Inf")) + f" {child.count}")
                    out.append(_series(fam.name + "_sum", fam.labelnames,
                                       vals) + f" {_fmt(child.sum)}")
                    out.append(_series(fam.name + "_count", fam.labelnames,
                                       vals) + f" {child.count}")
                else:
                    out.append(_series(fam.name, fam.labelnames, vals)
                               + f" {_fmt(child.value)}")
        return "\n".join(out) + "\n"

    def snapshot(self) -> dict:
        """Full structured dump (the /v1/stats 'metrics' block)."""
        out: dict = {}
        for fam in self.families():
            series = []
            for vals, child in fam.children():
                labels = dict(zip(fam.labelnames, vals))
                if fam.kind == "histogram":
                    series.append({
                        "labels": labels,
                        "count": child.count,
                        "sum": round(child.sum, 9),
                        "buckets": {_fmt(ub): c for ub, c in
                                    zip(fam.buckets, child.counts)},
                    })
                else:
                    series.append({"labels": labels, "value": child.value})
            out[fam.name] = {"type": fam.kind, "help": fam.help,
                             "series": series}
        return out

    def summary(self) -> dict:
        """Flat compact dump keyed by full series name — counters and
        gauges map to their value, histograms to
        {count, sum, mean, p50, p90, p99} (quantiles bucket-estimated).
        This is what the bench harnesses embed in BENCH json."""
        out: dict = {}
        for fam in self.families():
            for vals, child in fam.children():
                key = _series(fam.name, fam.labelnames, vals)
                if fam.kind == "histogram":
                    if child.count == 0:
                        continue
                    out[key] = {
                        "count": child.count,
                        "sum": round(child.sum, 9),
                        "mean": round(child.sum / child.count, 9),
                        "p50": round(child.quantile(0.5), 9),
                        "p90": round(child.quantile(0.9), 9),
                        "p99": round(child.quantile(0.99), 9),
                    }
                else:
                    out[key] = child.value
        return out


_default_registry = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-wide registry every subsystem reports into unless
    handed an explicit one (engines accept ``registry=`` for isolation,
    e.g. per-bench-run registries)."""
    return _default_registry
