"""Service-level objectives: declarative per-QoS specs, multi-window
sliding histograms, and Google-SRE-style burn-rate alerting.

The rest of the observability stack records *mechanisms* (latency
histograms, traces, roofline util); this module states what "healthy
service" MEANS and pages when the error budget is burning faster than
the service can afford.

Spec grammar (``$BIGDL_TPU_SLO_SPEC``, JSON)
--------------------------------------------
A JSON object whose QoS-class keys override individual objectives and
whose reserved keys tune the evaluator::

    {"interactive": {"ttft_p99_ms": 500, "availability": 0.999},
     "batch": {"tpot_p99_ms": 2000},
     "windows": {"fast_sec": 300, "slow_sec": 3600},
     "burn": {"fast": 14.4, "slow": 3.0},
     "eval_sec": 5.0, "recover_evals": 3, "min_events": 12}

Objectives per class (all optional; defaults below):

- ``ttft_p99_ms`` — 99% of requests must see first token within this
  (budget = 1% of requests may exceed it)
- ``tpot_p99_ms`` — 99% of decode steps within this
- ``error_rate`` — allowed fraction of finished requests with an
  engine-error finish reason
- ``availability`` — fraction of arriving requests that must be served
  (sheds and errors both spend this budget)

Burn-rate alerting (Google SRE workbook, multi-window multi-burn):
``burn = bad_fraction / budget`` per sliding window. A *fast* alert
(page-grade) fires when the 5m window burns >= 14.4x — at that rate a
30-day budget is gone in ~2 days; a *slow* alert (ticket-grade) fires
when the 1h window burns >= 3x. Alerts recover with hysteresis: the
burn must stay below its threshold for ``recover_evals`` consecutive
evaluations before the alert clears (the same dwell shape as the
brownout governor and the perf sentinel).

Every alert transition emits an ``slo_burn`` flight event, increments
``bigdl_tpu_slo_alerts_total{qos,objective,severity}``, and appends a
JSONL line to ``$BIGDL_TPU_SLO_ALERT_LOG`` (size-rotated with the
event-log knobs). Current burn rates are exported as
``bigdl_tpu_slo_burn_rate{qos,objective,window}`` gauges and served by
``GET /v1/slo`` (per replica) and the router's fleet aggregation in
``GET /v1/router/stats``.

Stdlib-only by design (see observability/metrics.py).
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from .stats import percentile
from .tracing import (
    resolve_event_log_keep,
    resolve_event_log_max_bytes,
    rotate_event_log,
    validate_event_log_path,
)

#: QoS classes (mirrors serving/overload.QOS_CLASSES; duplicated here
#: so the observability package stays import-free of the serving tier)
QOS_CLASSES = ("interactive", "standard", "batch")

#: objective names, fixed — these are metric label values, so the set
#: must stay bounded
OBJECTIVES = ("ttft_p99", "tpot_p99", "error_rate", "availability")

#: alert windows, fixed label values
WINDOWS = ("fast", "slow")

#: finish reasons that do NOT spend the error budget: client-visible
#: success ("stop"/"length"), client-initiated cancels, client-set
#: deadlines
OK_FINISH_REASONS = ("stop", "length", "abort", "deadline")

DEFAULT_OBJECTIVES: Dict[str, Dict[str, float]] = {
    "interactive": {"ttft_p99_ms": 1000.0, "tpot_p99_ms": 200.0,
                    "error_rate": 0.001, "availability": 0.999},
    "standard": {"ttft_p99_ms": 2500.0, "tpot_p99_ms": 400.0,
                 "error_rate": 0.005, "availability": 0.995},
    "batch": {"ttft_p99_ms": 10000.0, "tpot_p99_ms": 1000.0,
              "error_rate": 0.01, "availability": 0.99},
}

_DEFAULT_EVAL = {"fast_sec": 300.0, "slow_sec": 3600.0,
                 "burn_fast": 14.4, "burn_slow": 3.0,
                 "eval_sec": 5.0, "recover_evals": 3, "min_events": 12}

_OBJECTIVE_KEYS = ("ttft_p99_ms", "tpot_p99_ms", "error_rate",
                   "availability")


def resolve_slo_spec(value: Optional[str] = None) -> dict:
    """Parse + validate the SLO spec: explicit JSON string, else
    ``$BIGDL_TPU_SLO_SPEC``, else pure defaults. Returns the resolved
    spec dict ``{"qos": {...}, "windows": ..., "burn": ...,
    "eval_sec": ..., "recover_evals": ..., "min_events": ...}``.
    Raises ``ValueError`` on malformed JSON, unknown keys, or
    out-of-range values (utils/env_check.py surfaces this)."""
    if value is None:
        value = os.environ.get("BIGDL_TPU_SLO_SPEC")
    spec = {
        "qos": {q: dict(DEFAULT_OBJECTIVES[q]) for q in QOS_CLASSES},
        "windows": {"fast_sec": _DEFAULT_EVAL["fast_sec"],
                    "slow_sec": _DEFAULT_EVAL["slow_sec"]},
        "burn": {"fast": _DEFAULT_EVAL["burn_fast"],
                 "slow": _DEFAULT_EVAL["burn_slow"]},
        "eval_sec": _DEFAULT_EVAL["eval_sec"],
        "recover_evals": _DEFAULT_EVAL["recover_evals"],
        "min_events": _DEFAULT_EVAL["min_events"],
    }
    if not value:
        return spec
    try:
        doc = json.loads(value)
    except ValueError as e:
        raise ValueError(f"BIGDL_TPU_SLO_SPEC is not valid JSON: {e}")
    if not isinstance(doc, dict):
        raise ValueError("BIGDL_TPU_SLO_SPEC must be a JSON object, "
                         f"got {type(doc).__name__}")
    for key, val in doc.items():
        if key in QOS_CLASSES:
            if not isinstance(val, dict):
                raise ValueError(f"SLO spec for qos {key!r} must be an "
                                 f"object, got {type(val).__name__}")
            for ok, ov in val.items():
                if ok not in _OBJECTIVE_KEYS:
                    raise ValueError(
                        f"unknown SLO objective {ok!r} for qos {key!r} "
                        f"(choices: {', '.join(_OBJECTIVE_KEYS)})")
                if not isinstance(ov, (int, float)) \
                        or isinstance(ov, bool) or ov <= 0:
                    raise ValueError(
                        f"SLO objective {key}.{ok} must be a positive "
                        f"number, got {ov!r}")
                if ok in ("error_rate", "availability") and ov >= 1:
                    raise ValueError(
                        f"SLO objective {key}.{ok} must be in (0, 1), "
                        f"got {ov!r}")
                spec["qos"][key][ok] = float(ov)
        elif key == "windows":
            for wk in ("fast_sec", "slow_sec"):
                if wk in val:
                    wv = val[wk]
                    if not isinstance(wv, (int, float)) \
                            or isinstance(wv, bool) or wv <= 0:
                        raise ValueError(
                            f"SLO windows.{wk} must be a positive "
                            f"number, got {wv!r}")
                    spec["windows"][wk] = float(wv)
            bad = set(val) - {"fast_sec", "slow_sec"}
            if bad:
                raise ValueError(f"unknown SLO windows key(s): "
                                 f"{sorted(bad)}")
        elif key == "burn":
            for bk in ("fast", "slow"):
                if bk in val:
                    bv = val[bk]
                    if not isinstance(bv, (int, float)) \
                            or isinstance(bv, bool) or bv <= 0:
                        raise ValueError(
                            f"SLO burn.{bk} must be a positive number, "
                            f"got {bv!r}")
                    spec["burn"][bk] = float(bv)
            bad = set(val) - {"fast", "slow"}
            if bad:
                raise ValueError(f"unknown SLO burn key(s): "
                                 f"{sorted(bad)}")
        elif key in ("eval_sec",):
            if not isinstance(val, (int, float)) \
                    or isinstance(val, bool) or val <= 0:
                raise ValueError(
                    f"SLO {key} must be a positive number, got {val!r}")
            spec[key] = float(val)
        elif key in ("recover_evals", "min_events"):
            if not isinstance(val, int) or isinstance(val, bool) \
                    or val < 1:
                raise ValueError(
                    f"SLO {key} must be an integer >= 1, got {val!r}")
            spec[key] = int(val)
        else:
            raise ValueError(
                f"unknown SLO spec key {key!r} (qos classes "
                f"{', '.join(QOS_CLASSES)} or windows/burn/eval_sec/"
                f"recover_evals/min_events)")
    if spec["windows"]["fast_sec"] > spec["windows"]["slow_sec"]:
        raise ValueError(
            "SLO windows.fast_sec must be <= windows.slow_sec, got "
            f"{spec['windows']['fast_sec']} > "
            f"{spec['windows']['slow_sec']}")
    return spec


def resolve_slo_alert_log(value: Optional[str] = None) -> Optional[str]:
    """Path for the JSONL alert sink: explicit value, else
    ``$BIGDL_TPU_SLO_ALERT_LOG``, else None (no sink)."""
    if value is None:
        value = os.environ.get("BIGDL_TPU_SLO_ALERT_LOG")
    return value or None


def validate_slo_alert_log_path(path: str) -> dict:
    """Writability report for the alert sink (utils/env_check.py
    surfaces this for BIGDL_TPU_SLO_ALERT_LOG)."""
    return validate_event_log_path(path)


#: latency bucket upper edges in ms for the sliding histograms —
#: log-spaced 1ms..100s; per-qos targets are counted exactly (the
#: tracker splices each class's target into its bounds)
_MS_BOUNDS = tuple(round(10 ** (e / 4), 3) for e in range(0, 21))


class SlidingHistogram:
    """Time-sliced histogram: observations land in the current slice,
    reads aggregate the slices inside a lookback window. One ring sized
    for the longest window serves every shorter window too. Not
    thread-safe — the tracker serializes access under its lock."""

    def __init__(self, bounds: Tuple[float, ...], max_window_s: float,
                 slice_s: float):
        self.bounds = tuple(sorted(set(bounds)))
        self.slice_s = max(slice_s, 0.05)
        self.max_window_s = max_window_s
        # (slice_start, per-bucket counts [len(bounds)+1 for +Inf],
        #  total, sum)
        self._slices: "collections.deque" = collections.deque()

    def _bucket(self, v: float) -> int:
        for i, b in enumerate(self.bounds):
            if v <= b:
                return i
        return len(self.bounds)

    def _prune(self, now: float) -> None:
        horizon = now - self.max_window_s - self.slice_s
        while self._slices and self._slices[0][0] < horizon:
            self._slices.popleft()

    def observe(self, v: float, now: float) -> None:
        self._prune(now)
        t0 = now - (now % self.slice_s)
        if not self._slices or self._slices[-1][0] != t0:
            self._slices.append(
                (t0, [0] * (len(self.bounds) + 1), [0], [0.0]))
        _, counts, total, acc = self._slices[-1]
        counts[self._bucket(v)] += 1
        total[0] += 1
        acc[0] += v

    def window(self, window_s: float, now: float):
        """Aggregated (bucket_counts, total, sum) over the trailing
        ``window_s`` seconds."""
        self._prune(now)
        counts = [0] * (len(self.bounds) + 1)
        total, acc = 0, 0.0
        horizon = now - window_s
        for t0, c, t, a in self._slices:
            if t0 + self.slice_s <= horizon:
                continue
            for i, n in enumerate(c):
                counts[i] += n
            total += t[0]
            acc += a[0]
        return counts, total, acc

    def count_above(self, threshold: float, window_s: float,
                    now: float) -> Tuple[int, int]:
        """(observations strictly above ``threshold``, total) in the
        window. Exact when ``threshold`` is a bucket bound (the tracker
        splices the per-qos targets into ``bounds``)."""
        counts, total, _ = self.window(window_s, now)
        above = 0
        for i, b in enumerate(self.bounds):
            if b > threshold:
                above += counts[i]
        above += counts[-1]
        return above, total

    def quantile(self, q: float, window_s: float,
                 now: float) -> Optional[float]:
        """Bucket-interpolated quantile estimate (same scheme as the
        registry's summary())."""
        counts, total, _ = self.window(window_s, now)
        if total == 0:
            return None
        rank = q * total
        run = 0
        lo = 0.0
        for i, b in enumerate(self.bounds):
            nxt = run + counts[i]
            if nxt >= rank and counts[i] > 0:
                frac = (rank - run) / counts[i]
                return lo + (b - lo) * frac
            run = nxt
            lo = b
        return self.bounds[-1]


class SlidingCounts:
    """Time-sliced categorical counters (ok / error / shed) with the
    same windowed aggregation as SlidingHistogram."""

    def __init__(self, max_window_s: float, slice_s: float):
        self.slice_s = max(slice_s, 0.05)
        self.max_window_s = max_window_s
        self._slices: "collections.deque" = collections.deque()

    def _prune(self, now: float) -> None:
        horizon = now - self.max_window_s - self.slice_s
        while self._slices and self._slices[0][0] < horizon:
            self._slices.popleft()

    def add(self, key: str, now: float, n: int = 1) -> None:
        self._prune(now)
        t0 = now - (now % self.slice_s)
        if not self._slices or self._slices[-1][0] != t0:
            self._slices.append((t0, collections.Counter()))
        self._slices[-1][1][key] += n

    def window(self, window_s: float, now: float) -> Dict[str, int]:
        self._prune(now)
        out: collections.Counter = collections.Counter()
        horizon = now - window_s
        for t0, c in self._slices:
            if t0 + self.slice_s <= horizon:
                continue
            out.update(c)
        return dict(out)


class SLOTracker:
    """Evaluates the resolved SLO spec against live traffic.

    Feeds (engine thread): ``observe_ttft`` / ``observe_tpot`` /
    ``observe_result``. Evaluation (``maybe_evaluate``) is throttled to
    ``eval_sec`` and runs the burn-rate math, gauge export, alert state
    machine, flight events, and the JSONL alert sink. HTTP handler
    threads call ``snapshot()`` — everything mutable is guarded by one
    lock."""

    def __init__(self, spec: Optional[dict] = None, registry=None,
                 flight=None, alert_log_path: Optional[str] = None,
                 time_fn=time.time):
        if spec is None:
            try:
                spec = resolve_slo_spec()
            except ValueError:
                # env_check reports the bad spec; serve with defaults
                spec = resolve_slo_spec("")
        self.spec = spec
        self.flight = flight
        self._time = time_fn
        self._lock = threading.Lock()
        self._last_eval = 0.0
        slow = spec["windows"]["slow_sec"]
        fast = spec["windows"]["fast_sec"]
        slice_s = max(fast / 30.0, 0.05)
        self._win = {"fast": fast, "slow": slow}
        self._ttft: Dict[str, SlidingHistogram] = {}
        self._tpot: Dict[str, SlidingHistogram] = {}
        self._events: Dict[str, SlidingCounts] = {}
        for q in QOS_CLASSES:
            ob = spec["qos"][q]
            self._ttft[q] = SlidingHistogram(
                _MS_BOUNDS + (ob["ttft_p99_ms"],), slow, slice_s)
            self._tpot[q] = SlidingHistogram(
                _MS_BOUNDS + (ob["tpot_p99_ms"],), slow, slice_s)
            self._events[q] = SlidingCounts(slow, slice_s)
        # alert state per (qos, objective): None | {"severity", "since",
        # "burn", "good_evals"}
        self._alerts: Dict[Tuple[str, str], dict] = {}
        self._alerts_total = 0
        self._last_burn: Dict[Tuple[str, str, str], float] = {}
        # JSONL alert sink, size-rotated with the event-log knobs
        if alert_log_path is None:
            alert_log_path = resolve_slo_alert_log()
        self._sink_path = alert_log_path or None
        self._sink_dead = False
        try:
            self._sink_max_bytes = resolve_event_log_max_bytes()
            self._sink_keep = resolve_event_log_keep()
        except ValueError:
            self._sink_max_bytes, self._sink_keep = None, 1
        # metric families (registry may be None for bare trackers)
        self._g_burn = None
        self._c_alerts = None
        if registry is not None:
            self._g_burn = registry.gauge(
                "bigdl_tpu_slo_burn_rate",
                "Error-budget burn rate per QoS class, objective and "
                "sliding window (1.0 = burning exactly the budget).",
                labelnames=("qos", "objective", "window"))
            self._c_alerts = registry.counter(
                "bigdl_tpu_slo_alerts_total",
                "Burn-rate alerts fired, by QoS class, objective and "
                "severity (fast = page-grade, slow = ticket-grade).",
                labelnames=("qos", "objective", "severity"))
            for q in QOS_CLASSES:       # render from scrape 1
                for o in OBJECTIVES:
                    for w in WINDOWS:
                        self._g_burn.labels(q, o, w).set(0.0)

    # -- feeds (engine thread) ---------------------------------------------

    def observe_ttft(self, qos: str, seconds: float) -> None:
        h = self._ttft.get(qos)
        if h is not None and seconds >= 0:
            with self._lock:
                h.observe(seconds * 1e3, self._time())

    def observe_tpot(self, qos: str, seconds: float) -> None:
        h = self._tpot.get(qos)
        if h is not None and seconds >= 0:
            with self._lock:
                h.observe(seconds * 1e3, self._time())

    def observe_result(self, qos: str, outcome: str) -> None:
        """``outcome``: "ok" | "error" | "shed"."""
        ev = self._events.get(qos)
        if ev is not None:
            with self._lock:
                ev.add(outcome, self._time())

    def observe_finish(self, qos: str, reason: str) -> None:
        self.observe_result(
            qos, "ok" if reason in OK_FINISH_REASONS else "error")

    # -- burn math ----------------------------------------------------------

    def _burn_rates(self, qos: str, objective: str,
                    now: float) -> Dict[str, float]:
        """{window: burn} for one (qos, objective); burn is 0.0 until
        ``min_events`` observations fill the window (a cold start must
        not page)."""
        ob = self.spec["qos"][qos]
        min_ev = self.spec["min_events"]
        out = {}
        for w in WINDOWS:
            win = self._win[w]
            if objective == "ttft_p99":
                bad, total = self._ttft[qos].count_above(
                    ob["ttft_p99_ms"], win, now)
                budget = 0.01
            elif objective == "tpot_p99":
                bad, total = self._tpot[qos].count_above(
                    ob["tpot_p99_ms"], win, now)
                budget = 0.01
            elif objective == "error_rate":
                ev = self._events[qos].window(win, now)
                bad = ev.get("error", 0)
                total = bad + ev.get("ok", 0)
                budget = ob["error_rate"]
            else:                        # availability
                ev = self._events[qos].window(win, now)
                bad = ev.get("error", 0) + ev.get("shed", 0)
                total = bad + ev.get("ok", 0)
                budget = 1.0 - ob["availability"]
            if total < min_ev or budget <= 0:
                out[w] = 0.0
            else:
                out[w] = (bad / total) / budget
        return out

    def compliance(self, qos: str, kind: str,
                   window: str = "slow") -> Optional[float]:
        """Fraction of ``kind`` ("ttft"/"tpot") observations inside the
        target over the window; None with no traffic."""
        now = self._time()
        ob = self.spec["qos"][qos]
        with self._lock:
            if kind == "ttft":
                bad, total = self._ttft[qos].count_above(
                    ob["ttft_p99_ms"], self._win[window], now)
            else:
                bad, total = self._tpot[qos].count_above(
                    ob["tpot_p99_ms"], self._win[window], now)
        if total == 0:
            return None
        return 1.0 - bad / total

    # -- alert state machine ------------------------------------------------

    def maybe_evaluate(self, now: Optional[float] = None) -> None:
        """Throttled entry point — call freely from the engine step
        loop; the burn math runs at most once per ``eval_sec``."""
        if now is None:
            now = self._time()
        with self._lock:
            if now - self._last_eval < self.spec["eval_sec"]:
                return
        self.evaluate(now)

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """One full evaluation pass; returns the alert transitions it
        produced (fired / recovered), already emitted to flight,
        metrics and the sink."""
        if now is None:
            now = self._time()
        transitions: List[dict] = []
        burn_th = {"fast": self.spec["burn"]["fast"],
                   "slow": self.spec["burn"]["slow"]}
        with self._lock:
            self._last_eval = now
            for q in QOS_CLASSES:
                for o in OBJECTIVES:
                    burns = self._burn_rates(q, o, now)
                    for w in WINDOWS:
                        self._last_burn[(q, o, w)] = burns[w]
                        if self._g_burn is not None:
                            self._g_burn.labels(q, o, w).set(
                                round(burns[w], 4))
                    # fast (page) outranks slow (ticket)
                    severity = None
                    if burns["fast"] >= burn_th["fast"]:
                        severity = "fast"
                    elif burns["slow"] >= burn_th["slow"]:
                        severity = "slow"
                    st = self._alerts.get((q, o))
                    if severity is not None:
                        if st is None:
                            st = {"severity": severity, "since": now,
                                  "burn": burns, "good_evals": 0}
                            self._alerts[(q, o)] = st
                            self._alerts_total += 1
                            transitions.append({
                                "event": "slo_burn", "qos": q,
                                "objective": o, "severity": severity,
                                "burn_fast": round(burns["fast"], 3),
                                "burn_slow": round(burns["slow"], 3)})
                            if self._c_alerts is not None:
                                self._c_alerts.labels(q, o,
                                                      severity).inc()
                        else:
                            st["severity"] = max(
                                st["severity"], severity,
                                key=lambda s: s == "fast")
                            st["burn"] = burns
                            st["good_evals"] = 0
                    elif st is not None:
                        # hysteresis: recover only after recover_evals
                        # consecutive healthy evaluations
                        st["good_evals"] += 1
                        if st["good_evals"] >= self.spec["recover_evals"]:
                            del self._alerts[(q, o)]
                            transitions.append({
                                "event": "slo_recover", "qos": q,
                                "objective": o,
                                "severity": st["severity"],
                                "burn_fast": round(burns["fast"], 3),
                                "burn_slow": round(burns["slow"], 3)})
        for tr in transitions:
            if self.flight is not None:
                self.flight.record(tr["event"],
                                   **{k: v for k, v in tr.items()
                                      if k != "event"})
            self._sink_write(dict(tr, ts=round(now, 3)))
        return transitions

    # -- JSONL alert sink ---------------------------------------------------

    def _sink_write(self, doc: dict) -> None:
        if self._sink_path is None or self._sink_dead:
            return
        line = json.dumps(doc, separators=(",", ":")) + "\n"
        try:
            if (self._sink_max_bytes is not None
                    and os.path.exists(self._sink_path)
                    and os.path.getsize(self._sink_path) + len(line)
                    > self._sink_max_bytes):
                rotate_event_log(self._sink_path, self._sink_keep)
            with open(self._sink_path, "a", encoding="utf-8") as fh:
                fh.write(line)
        except OSError as e:
            self._sink_dead = True
            logging.getLogger(__name__).warning(
                "SLO alert log %s unwritable (%s); sink disabled",
                self._sink_path, e)

    # -- introspection ------------------------------------------------------

    def alerts_active(self) -> int:
        with self._lock:
            return len(self._alerts)

    def burn_rate_max(self) -> float:
        """Worst current burn across every (qos, objective, window) —
        the bench lane headline."""
        with self._lock:
            return max(self._last_burn.values(), default=0.0)

    def snapshot(self) -> dict:
        """The ``GET /v1/slo`` document."""
        now = self._time()
        out: dict = {
            "spec": {
                "qos": {q: dict(self.spec["qos"][q])
                        for q in QOS_CLASSES},
                "windows": dict(self.spec["windows"]),
                "burn": dict(self.spec["burn"]),
                "eval_sec": self.spec["eval_sec"],
                "recover_evals": self.spec["recover_evals"],
                "min_events": self.spec["min_events"],
            },
            "qos": {},
        }
        with self._lock:
            for q in QOS_CLASSES:
                ob = self.spec["qos"][q]
                qd: dict = {"objectives": {}}
                for o in OBJECTIVES:
                    st = self._alerts.get((q, o))
                    qd["objectives"][o] = {
                        "burn": {
                            w: round(self._last_burn.get((q, o, w),
                                                         0.0), 4)
                            for w in WINDOWS},
                        "alert": ({"severity": st["severity"],
                                   "since": round(st["since"], 3)}
                                  if st else None),
                    }
                for kind, hist, target in (
                        ("ttft", self._ttft[q], ob["ttft_p99_ms"]),
                        ("tpot", self._tpot[q], ob["tpot_p99_ms"])):
                    p99 = hist.quantile(0.99, self._win["fast"], now)
                    _, total, _ = hist.window(self._win["fast"], now)
                    qd[f"{kind}_p99_ms"] = (round(p99, 3)
                                            if p99 is not None else None)
                    qd[f"{kind}_target_ms"] = target
                    qd[f"{kind}_count"] = total
                ev = self._events[q].window(self._win["slow"], now)
                qd["events"] = ev
                out["qos"][q] = qd
            out["alerts_active"] = len(self._alerts)
            out["alerts_total"] = self._alerts_total
            out["burn_rate_max"] = round(
                max(self._last_burn.values(), default=0.0), 4)
        return out


__all__ = [
    "QOS_CLASSES",
    "OBJECTIVES",
    "WINDOWS",
    "OK_FINISH_REASONS",
    "DEFAULT_OBJECTIVES",
    "SlidingHistogram",
    "SlidingCounts",
    "SLOTracker",
    "resolve_slo_spec",
    "resolve_slo_alert_log",
    "validate_slo_alert_log_path",
]
