"""Analytical roofline cost model — the single source of truth for
FLOPs / HBM-bytes math shared by ``bench.py`` (offline efficiency
block), the serving engine (live ``bigdl_tpu_roofline_util{phase}`` /
``decode_ideal_ms`` gauges), compile_watch (per-jit cost annotation)
and the perf-regression sentinel.

Decode on one chip is HBM-bandwidth-bound: every token reads the whole
packed weight set plus the live KV slice, so the honest efficiency
number is bytes-moved / (latency x peak-BW). Prefill is compute-bound,
so its number is model FLOPs / (latency x peak-FLOPs) — classic MFU.
Chip peaks are v5e datasheet values, env-overridable for other chips.

Import contract: **stdlib only** (``tests/test_observability.py``
enforces that importing ``bigdl_tpu.observability`` pulls in no heavy
deps). Model configs are duck-typed: anything with ``hidden_size``,
``intermediate_size``, ``vocab_size``, ``num_attention_heads``,
``num_key_value_heads``, ``hd`` and ``num_hidden_layers`` works
(LlamaConfig does).
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

__all__ = [
    "KV_ELT_BYTES",
    "attn_flops_per_token",
    "attribution",
    "chip_peaks",
    "decode_costs",
    "efficiency",
    "jit_costs",
    "kv_bytes_per_token",
    "model_flops_per_token",
    "prefill_costs",
]

# logical storage bytes per KV element (int4 packs two codes per byte);
# scaled dtypes additionally carry fp32 scale planes, accounted in
# kv_bytes_per_token. Mirrors ops/kvcache.py KV_CACHE_DTYPES without
# importing jax.
KV_ELT_BYTES: Dict[str, float] = {
    "bf16": 2.0,
    "fp8_e5m2": 1.0,
    "int8": 1.0,
    "int4": 0.5,
}
_SCALED_KV_DTYPES = ("int8", "int4")
_SCALE_ELT_BYTES = 4.0  # fp32 scale per (token, head) plane


def chip_peaks() -> Tuple[float, float]:
    """(peak_bf16_tflops, peak_hbm_gbps) — v5e datasheet defaults,
    env-overridable for other chips. One definition for the bench
    floors, the efficiency block, bench_qlora and the live gauges."""
    return (float(os.environ.get("BIGDL_TPU_PEAK_BF16_TFLOPS", "197")),
            float(os.environ.get("BIGDL_TPU_PEAK_HBM_GBPS", "819")))


def model_flops_per_token(cfg) -> int:
    """Forward matmul FLOPs per token (qkvo + gated mlp + lm_head; no
    attention-over-cache term). Shared by the physics floors, the
    efficiency block and bench_qlora so the cost model cannot drift."""
    d, ff, v = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    h, hkv, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.hd
    proj = 2 * (d * h * hd + 2 * d * hkv * hd + h * hd * d)
    return cfg.num_hidden_layers * (proj + 2 * 3 * d * ff) + 2 * d * v


def attn_flops_per_token(cfg, seq_len: int) -> int:
    """Attention-over-cache FLOPs for one decoded token at cache length
    ``seq_len``: two matmuls (QK^T and PV) over ``seq_len`` keys."""
    h, hd = cfg.num_attention_heads, cfg.hd
    return cfg.num_hidden_layers * 2 * 2 * h * hd * seq_len


def kv_bytes_per_token(cfg, seq_len: int,
                       kv_cache_dtype: str = "bf16") -> float:
    """Live KV bytes read for one decoded token at cache length
    ``seq_len``: K and V planes across all layers, plus fp32 scale
    planes for block-scaled dtypes."""
    elt = KV_ELT_BYTES.get(kv_cache_dtype)
    if elt is None:
        raise ValueError(
            f"unknown kv_cache_dtype {kv_cache_dtype!r}; choose from "
            f"{sorted(KV_ELT_BYTES)}")
    l_, hkv, hd = (cfg.num_hidden_layers, cfg.num_key_value_heads,
                   cfg.hd)
    bytes_ = 2.0 * l_ * seq_len * hkv * hd * elt
    if kv_cache_dtype in _SCALED_KV_DTYPES:
        bytes_ += 2.0 * l_ * seq_len * hkv * _SCALE_ELT_BYTES
    return bytes_


def decode_costs(cfg, weight_bytes: int, seq_len: int,
                 kv_cache_dtype: str = "bf16",
                 batch: int = 1) -> Dict[str, float]:
    """Analytical cost of one decode step at cache length ``seq_len``:

    - ``flops``: matmul + attention-over-cache FLOPs (per batch row)
    - ``hbm_bytes``: packed weights read once for the whole batch, plus
      the live KV slice per row
    - ``ideal_ms``: bandwidth-bound floor for the step at peak HBM BW
    """
    _, peak_gbps = chip_peaks()
    flops = float(batch) * (model_flops_per_token(cfg)
                            + attn_flops_per_token(cfg, seq_len))
    hbm_bytes = float(weight_bytes) + float(batch) * kv_bytes_per_token(
        cfg, seq_len, kv_cache_dtype)
    ideal_ms = hbm_bytes / (peak_gbps * 1e9) * 1e3
    return {"flops": flops, "hbm_bytes": hbm_bytes, "ideal_ms": ideal_ms}


def prefill_costs(cfg, prompt_len: int,
                  batch: int = 1) -> Dict[str, float]:
    """Analytical cost of prefilling ``prompt_len`` tokens: per-token
    matmul FLOPs plus the causal-attention triangle (same
    ``prompt_len**2 // 2`` accounting as the bench efficiency block)."""
    l_ = cfg.num_hidden_layers
    h, hd = cfg.num_attention_heads, cfg.hd
    flops = float(batch) * (
        prompt_len * model_flops_per_token(cfg)
        + l_ * 2 * 2 * h * hd * (prompt_len * prompt_len // 2))
    return {"flops": flops}


def efficiency(cfg, weight_bytes: int, prompt_len: int, steps: int,
               first_ms: float, next_ms: float) -> dict:
    """MFU + HBM-roofline utilization (VERDICT r2 #2) — the exact
    numbers ``bench.py`` prints in every headline record (it imports
    this; ``tests/test_perf_observability.py`` asserts identity on the
    r05 fixture so bench and live gauges cannot drift).

    ``weight_bytes`` is measured from the live param pytree in the
    config subprocess and passed through. The KV term deliberately
    keeps the bench's bf16-cache accounting (the headline lane decodes
    against a bf16 cache) — kv-dtype-aware live gauges go through
    :func:`decode_costs` instead."""
    peak_tflops, peak_gbps = chip_peaks()

    l_ = cfg.num_hidden_layers
    h, hkv, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.hd
    flops_tok = model_flops_per_token(cfg)
    # attention FLOPs per token at cache length S: 2 matmuls over S keys
    s_mid = prompt_len + steps // 2
    attn_tok = l_ * 2 * 2 * h * hd * s_mid

    # bytes read per decode token: all packed weights + live KV slice
    kv_elt_bytes = 2  # bf16 cache
    kv_bytes = 2 * l_ * s_mid * hkv * hd * kv_elt_bytes
    ideal_decode_ms = (weight_bytes + kv_bytes) / (peak_gbps * 1e9) * 1e3

    # prefill MFU over the whole prompt
    prefill_flops = prompt_len * flops_tok + l_ * 2 * 2 * h * hd * (
        prompt_len * prompt_len // 2)
    prefill_mfu = prefill_flops / (first_ms / 1e3) / (peak_tflops * 1e12)

    decode_mfu = (flops_tok + attn_tok) / (next_ms / 1e3) / (
        peak_tflops * 1e12)
    return {
        "decode_hbm_roofline_util": round(ideal_decode_ms / next_ms, 4),
        "decode_ideal_ms": round(ideal_decode_ms, 6),
        "decode_mfu": round(decode_mfu, 5),
        "prefill_mfu": round(prefill_mfu, 4),
        "weight_bytes": int(weight_bytes),
        "peak_bf16_tflops": peak_tflops,
        "peak_hbm_gbps": peak_gbps,
    }


def attribution(cfg, weight_bytes: int, prompt_len: int, steps: int,
                first_ms: float, next_ms: float,
                kv_cache_dtype: str = "bf16") -> dict:
    """Per-phase roofline attribution block embedded in bench JSON:
    analytical FLOPs / HBM bytes / ideal ms next to the measured ms, so
    a bench record carries *why* a phase is slow, not just that it is."""
    peak_tflops, peak_gbps = chip_peaks()
    s_mid = prompt_len + steps // 2
    dec = decode_costs(cfg, weight_bytes, s_mid, kv_cache_dtype)
    pre = prefill_costs(cfg, prompt_len)
    prefill_ideal_ms = pre["flops"] / (peak_tflops * 1e12) * 1e3
    return {
        "prefill": {
            "flops": int(pre["flops"]),
            "ideal_ms": round(prefill_ideal_ms, 6),
            "measured_ms": round(first_ms, 3),
            "mfu": round(pre["flops"] / (first_ms / 1e3)
                         / (peak_tflops * 1e12), 4),
        },
        "decode": {
            "flops": int(dec["flops"]),
            "hbm_bytes": int(dec["hbm_bytes"]),
            "ideal_ms": round(dec["ideal_ms"], 6),
            "measured_ms": round(next_ms, 3),
            "hbm_roofline_util": round(dec["ideal_ms"] / next_ms, 4),
        },
        "kv_cache_dtype": kv_cache_dtype,
        "peak_bf16_tflops": peak_tflops,
        "peak_hbm_gbps": peak_gbps,
    }


def jit_costs(cfg, weight_bytes: int, max_batch: int, max_seq: int,
              prefill_bucket: int,
              kv_cache_dtype: str = "bf16") -> Dict[str, Dict[str, float]]:
    """Analytical {flops, hbm_bytes} per tracked_jit name, for
    compile_watch cost annotation (the "top offenders" view ranks jits
    by bytes moved). Worst-case shapes: decode at full cache, prefill
    at one bucket."""
    dec = decode_costs(cfg, weight_bytes, max_seq, kv_cache_dtype,
                       batch=max_batch)
    pre = prefill_costs(cfg, prefill_bucket)
    kv_full = float(max_batch) * kv_bytes_per_token(
        cfg, max_seq, kv_cache_dtype)
    costs: Dict[str, Dict[str, float]] = {
        "engine_decode": {"flops": dec["flops"],
                          "hbm_bytes": dec["hbm_bytes"]},
        "engine_decode_resident": {"flops": dec["flops"],
                                   "hbm_bytes": dec["hbm_bytes"]},
        "engine_prefill": {"flops": pre["flops"],
                           "hbm_bytes": float(weight_bytes)},
        # insert touches one row's KV planes; argmax/sample/health are
        # O(vocab) epsilon next to a forward pass
        "engine_insert": {"flops": 0.0,
                          "hbm_bytes": kv_full / max(max_batch, 1)},
        "engine_argmax": {
            "flops": float(max_batch * cfg.vocab_size),
            "hbm_bytes": float(2 * max_batch * cfg.vocab_size)},
        "engine_sample_device": {
            "flops": float(max_batch * cfg.vocab_size),
            "hbm_bytes": float(2 * max_batch * cfg.vocab_size)},
        "engine_health": {
            "flops": float(max_batch * cfg.vocab_size),
            "hbm_bytes": float(2 * max_batch * cfg.vocab_size)},
    }
    return costs
