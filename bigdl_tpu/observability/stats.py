"""Shared descriptive-statistics helpers for the observability stack.

One home for the percentile / median / EWMA math that used to be
hand-rolled in three places with three subtly different behaviors:

* ``utils/profiling.StepTimer.summary`` — linear-interpolation
  percentile (numpy's default method, without numpy),
* ``observability/sentinel`` baseline seeding — classic median
  (mean-of-two-middles on even length),
* ``bench_serving`` lane stats — ``np.percentile`` with the default
  (linear) interpolation.

All three are the SAME function: ``np.percentile``'s default "linear"
method reduces to mean-of-two-middles at q=0.5, so ``median(xs)``
equals ``percentile(sorted(xs), 0.5)`` for both parities and
``percentile`` is bit-compatible with ``np.percentile(v, q * 100)``
(same ``lo + (hi - lo) * frac`` evaluation order).
tests/test_slo.py asserts value-identity against pinned r05-style lane
numbers.

Stdlib-only by design (see observability/metrics.py): bench code may
have numpy, the serving engine's observability path must not need it.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence


def percentile(sorted_samples: Sequence[float], q: float) -> float:
    """Linear-interpolation percentile over PRE-SORTED samples, ``q``
    in [0, 1]. Matches ``np.percentile(samples, q * 100)`` (the default
    "linear" method) bit-for-bit: ``lo + (hi - lo) * frac``. Empty
    input returns NaN."""
    s = sorted_samples
    if not s:
        return float("nan")
    if len(s) == 1:
        return float(s[0])
    pos = q * (len(s) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(s) - 1)
    frac = pos - lo
    a, b = float(s[lo]), float(s[hi])
    # numpy's lerp flips the anchor at frac >= 0.5 so the interpolant
    # stays monotone in floating point; mirror it for bit-identity
    if frac >= 0.5:
        return b - (b - a) * (1.0 - frac)
    return a + (b - a) * frac


def median(samples: Sequence[float]) -> float:
    """Median (mean of the two middles on even length) — exactly
    ``percentile(sorted(samples), 0.5)``."""
    return percentile(sorted(samples), 0.5)


def summarize(samples: Sequence[float],
              scale: float = 1.0) -> Optional[Dict[str, float]]:
    """The StepTimer summary block: count / mean / min / max /
    p50 / p90 / p99 / total over ``samples``, with min..p99 multiplied
    by ``scale`` (1e3 turns seconds into the ``_ms`` fields). None on
    empty input (callers omit the row)."""
    if not samples:
        return None
    s = sorted(samples)
    n = len(samples)
    return {
        "count": n,
        "mean": sum(samples) / n * scale,
        "min": s[0] * scale,
        "max": s[-1] * scale,
        "p50": percentile(s, 0.50) * scale,
        "p90": percentile(s, 0.90) * scale,
        "p99": percentile(s, 0.99) * scale,
        "total": sum(samples),
    }


#: the one smoothing constant the serving EWMAs share (engine TPOT /
#: dispatch overhead, sentinel metric tracks): 0.8 carry, 0.2 sample
EWMA_DECAY = 0.8


def ewma(prev: Optional[float], sample: float,
         decay: float = EWMA_DECAY) -> float:
    """One EWMA update. ``prev`` of None or 0.0 seeds with the sample
    (the engine's ``_tpot_ewma == 0.0`` idiom and the sentinel's
    ``None`` idiom are the same rule)."""
    if prev is None or prev == 0.0:
        return float(sample)
    return decay * prev + (1.0 - decay) * sample


__all__ = ["percentile", "median", "summarize", "ewma", "EWMA_DECAY"]
