"""Dependency-free metrics, tracing, compile telemetry, memory
accounting and postmortems.

Five pieces, all stdlib-only at import time (jax is allowed elsewhere
in the package but this subpackage must import with nothing beyond the
standard library — tests/test_observability.py enforces it):

- ``metrics``: Counter / Gauge / Histogram registry with labels and
  Prometheus text exposition (``MetricsRegistry.render()``). The
  serving engine, speculative decoders, kernel probes and StepTimer all
  publish here; ``GET /metrics`` on the API server renders the
  engine's registry.
- ``tracing``: per-request lifecycle spans (queue wait, prefill, TTFT,
  decode/TPOT, preemptions) kept in a ring buffer and optionally
  appended as JSONL to ``$BIGDL_TPU_EVENT_LOG`` (size-rotated at
  ``$BIGDL_TPU_EVENT_LOG_MAX_BYTES``, keeping
  ``$BIGDL_TPU_EVENT_LOG_KEEP`` rolled files ``.1`` .. ``.N``);
  ``GET /v1/stats`` serves the snapshot.
- ``disttrace``: fleet-wide distributed tracing — W3C-style
  ``traceparent`` propagation (router -> replica -> engine -> KV-handoff
  target), a thread-safe ``SpanRecorder`` of completed spans per
  process (JSONL sink at ``$BIGDL_TPU_EVENT_LOG`` + ``.spans``, same
  rotation policy), deterministic tail sampling via
  ``$BIGDL_TPU_TRACE_SAMPLE``, and ``merge_timeline`` — the
  clock-skew-adjusted stitch behind the router's
  ``GET /v1/trace/{trace_id}``.
- ``compile_watch``: ``tracked_jit(name, fn, ...)`` — jax.jit plus
  compile accounting (count, wall time, abstract-shape signature per
  executable) feeding the jit metrics below, a process-wide
  ``compile_table()``, and a recompile-storm warning past
  ``$BIGDL_TPU_RECOMPILE_WARN`` compiles per name.
- ``memory``: ``MemoryLedger`` — exact static HBM accounting
  (packed weight / KV-cache / adapter bytes registered at build and
  allocation time) plus live ``device.memory_stats()`` telemetry
  (``bytes_in_use`` / ``peak_bytes_in_use`` / ``bytes_limit``, a no-op
  ``{}`` on CPU/interpret), a ``headroom()`` budget view driven by
  ``$BIGDL_TPU_HBM_BUDGET_FRACTION``, and ``would_fit(nbytes)`` — the
  predicate behind the serving engine's headroom-aware admission
  (deferral shows up as ``bigdl_tpu_admission_deferred_total``, an
  ``admit_deferred`` flight event, and ``GET /v1/memory``).
  ``memory_report()`` rolls the snapshot plus the compile table's peak
  temp bytes into the bench JSON records.
- ``roofline``: the analytical FLOPs / HBM-bytes cost model (single
  source for ``bench.py``'s efficiency block, the engine's live
  ``bigdl_tpu_roofline_util{phase}`` / ``decode_ideal_ms`` gauges and
  compile_watch's per-jit cost annotation). Chip peaks come from
  ``$BIGDL_TPU_PEAK_BF16_TFLOPS`` / ``$BIGDL_TPU_PEAK_HBM_GBPS``
  (v5e datasheet defaults).
- ``sentinel``: ``PerfSentinel`` — dwell-gated perf-regression
  detection over decode ms/token, roofline util and dispatch overhead
  EWMAs vs a rolling baseline persisted at ``$BIGDL_TPU_PERF_HISTORY``
  (size-rotated like the event log); trips emit ``perf_regression``
  flight events + postmortems + a bounded profiler auto-capture, then
  recover with hysteresis.
- ``stats``: the shared percentile / median / EWMA math (single source
  for StepTimer summaries, sentinel baseline seeding and bench lane
  stats; ``percentile`` is bit-compatible with ``np.percentile``'s
  default linear method).
- ``slo``: declarative per-QoS service-level objectives (TTFT p99,
  TPOT p99, error rate, availability; defaults overridden by JSON in
  ``$BIGDL_TPU_SLO_SPEC``) evaluated against multi-window sliding
  histograms with Google-SRE fast/slow burn-rate alerting — alerts
  emit ``slo_burn`` flight events,
  ``bigdl_tpu_slo_burn_rate{qos,objective,window}`` gauges,
  ``bigdl_tpu_slo_alerts_total`` and a size-rotated JSONL sink at
  ``$BIGDL_TPU_SLO_ALERT_LOG``; ``GET /v1/slo`` serves the snapshot
  and the router aggregates it fleet-wide.
- ``usage``: per-tenant usage metering — one append-only JSONL record
  per finished/shed request (``$BIGDL_TPU_USAGE_LOG``, written off the
  engine thread) plus the live rollup behind ``GET /v1/usage``,
  reconciled exactly against the tenant counters.
- ``flight``: ``FlightRecorder`` ring buffer of per-step engine events
  plus postmortem dumps — on engine-step exception, stall-guard trip,
  or SIGTERM/SIGINT a single JSON (flight tail, span tail, metrics
  snapshot, compile table, config + env fingerprint) is written to
  ``$BIGDL_TPU_POSTMORTEM_DIR``; ``GET /v1/debug/dump`` serves the
  same dict on demand.

Metric name -> engine field map (see also serving/engine.py):

==========================================  ===============================
metric                                      source
==========================================  ===============================
bigdl_tpu_request_phase_seconds{phase=...}  RequestSpan queue/prefill/decode
bigdl_tpu_ttft_seconds                      RequestSpan.ttft_s
bigdl_tpu_tpot_seconds                      LLMEngine.step() decode timing
bigdl_tpu_slot_occupancy                    len(LLMEngine._slots)
bigdl_tpu_queue_depth                       len(LLMEngine._queue)
bigdl_tpu_admissions_total                  LLMEngine._admission_step
bigdl_tpu_preemptions_total                 LLMEngine._preempt
bigdl_tpu_stall_guard_trips_total           LLMEngine._stall_steps trip
bigdl_tpu_requests_finished_total{reason}   LLMEngine._finish
bigdl_tpu_engine_steps_total                LLMEngine.step
bigdl_tpu_tokens_generated_total            LLMEngine._emit
bigdl_tpu_kernel_probe_total{kernel,...}    ops/probing.record_probe_result
bigdl_tpu_spec_accept_ratio{mode}           speculative._spec_observe
bigdl_tpu_spec_round_seconds{mode}          speculative._spec_observe
bigdl_tpu_spec_tokens_total{mode,kind}      speculative._spec_observe
bigdl_tpu_kv_cache_bytes{dtype,component}   ops/kvcache.publish_kv_cache_bytes
bigdl_tpu_kv_dequant_path_total{dtype,path} ops/attention._note_dequant_path
bigdl_tpu_jit_compiles_total{fn}            compile_watch.TrackedJit
bigdl_tpu_jit_compile_seconds{fn}           compile_watch.TrackedJit
bigdl_tpu_hbm_bytes{kind}                   memory.MemoryLedger.publish
bigdl_tpu_hbm_headroom_bytes                memory.MemoryLedger.publish
bigdl_tpu_admission_deferred_total{reason}  LLMEngine._admission_step
bigdl_tpu_requests_quarantined_total{reason} LLMEngine._quarantine_slot
bigdl_tpu_step_retries_total                LLMEngine._on_step_failure
bigdl_tpu_faults_injected_total{kind}       robustness.FaultInjector
bigdl_tpu_engine_draining                   LLMEngine.begin_drain
==========================================  ===============================

``bigdl_tpu_kv_cache_bytes`` reports the batched KV cache's logical
storage footprint split by component ("codes", "scales", "total" — int4
counts two codes per byte). ``bigdl_tpu_kv_dequant_path_total`` counts
how quantized attention dequantized: "fused" (inside the Pallas kernel)
vs "xla" (upcast fallback); increments happen at trace time, so read it
as "which path compiled", not a per-token rate.

``bigdl_tpu_jit_compiles_total{fn}`` counts jax.jit compiles per
tracked executable name (one per new abstract shape signature — e.g.
one per (prefill bucket, kv dtype) pair for ``engine_prefill``);
``bigdl_tpu_jit_compile_seconds{fn}`` holds the first-call wall time
of each. A steadily incrementing compile counter in steady state IS the
recompile-storm signature these exist to catch. Each first compile
also captures ``compiled.memory_analysis()`` (temp/argument/output
bytes) via an AOT lower+compile of the same signature; set
``BIGDL_TPU_COMPILE_MEMORY=0`` to skip that extra compile.

``bigdl_tpu_hbm_bytes{kind}`` carries both the ledger's static sums
per kind ("weights", "kv_cache", ...) and the device telemetry rows
("device_in_use", "device_peak", "device_limit" — absent without a
real accelerator). ``bigdl_tpu_hbm_headroom_bytes`` is
``budget_fraction * bytes_limit - bytes_in_use``; when an admission's
KV-cache cost exceeds it the request stays queued and
``bigdl_tpu_admission_deferred_total{reason="memory"}`` increments.

Environment knobs: ``BIGDL_TPU_EVENT_LOG`` (span JSONL sink) +
``BIGDL_TPU_EVENT_LOG_MAX_BYTES`` (rotate past this size) +
``BIGDL_TPU_EVENT_LOG_KEEP`` (rotated files retained, default 1),
``BIGDL_TPU_TRACE_SAMPLE`` (distributed-trace tail-sampling fraction,
default 1.0),
``BIGDL_TPU_POSTMORTEM_DIR`` (where crash/stall/signal dumps land),
``BIGDL_TPU_RECOMPILE_WARN`` (compiles-per-name warning threshold,
default 8), ``BIGDL_TPU_HBM_BUDGET_FRACTION`` (admission budget as a
fraction of ``bytes_limit``, float in (0, 1], default 0.9),
``BIGDL_TPU_MEMORY_POLL_SEC`` (min seconds between live
``memory_stats()`` reads, default 1.0), ``BIGDL_TPU_COMPILE_MEMORY``
(set 0 to skip per-compile memory analysis),
``BIGDL_TPU_SLO_SPEC`` (JSON SLO spec override),
``BIGDL_TPU_SLO_ALERT_LOG`` (burn-alert JSONL sink),
``BIGDL_TPU_USAGE_LOG`` (per-request usage ledger). All are validated
by ``python -m bigdl_tpu.utils.env_check``.
"""

from bigdl_tpu.observability.compile_watch import (
    TrackedJit,
    annotate_costs,
    compile_table,
    reset_compile_table,
    resolve_recompile_threshold,
    top_offenders,
    tracked_jit,
)
from bigdl_tpu.observability.flight import (
    FlightRecorder,
    build_postmortem,
    env_fingerprint,
    install_signal_dumps,
    validate_postmortem_dir,
    write_postmortem,
)
from bigdl_tpu.observability.memory import (
    MemoryLedger,
    default_ledger,
    device_memory_stats,
    memory_report,
    reset_default_ledger,
    resolve_hbm_budget_fraction,
    resolve_memory_poll_sec,
    tree_nbytes,
)
from bigdl_tpu.observability.metrics import (
    LATENCY_BUCKETS_S,
    RATIO_BUCKETS,
    MetricFamily,
    MetricsRegistry,
    default_registry,
)
from bigdl_tpu.observability.disttrace import (
    SpanRecorder,
    make_traceparent,
    merge_timeline,
    new_span_id,
    new_trace_id,
    parse_traceparent,
    resolve_trace_sample,
    trace_sampled,
)
from bigdl_tpu.observability.tracing import (
    RequestSpan,
    RequestTracer,
    resolve_event_log_keep,
    resolve_event_log_max_bytes,
    rotate_event_log,
    validate_event_log_path,
)
from bigdl_tpu.observability.roofline import (
    attn_flops_per_token,
    chip_peaks,
    decode_costs,
    jit_costs,
    kv_bytes_per_token,
    model_flops_per_token,
    prefill_costs,
)
from bigdl_tpu.observability.roofline import (
    attribution as roofline_attribution,
    efficiency as roofline_efficiency,
)
from bigdl_tpu.observability.slo import (
    DEFAULT_OBJECTIVES,
    OBJECTIVES,
    SLOTracker,
    SlidingHistogram,
    resolve_slo_alert_log,
    resolve_slo_spec,
    validate_slo_alert_log_path,
)
from bigdl_tpu.observability.stats import (
    EWMA_DECAY,
    ewma,
    median,
    percentile,
    summarize,
)
from bigdl_tpu.observability.usage import (
    UsageLedger,
    resolve_usage_log,
    validate_usage_log_path,
)
from bigdl_tpu.observability.sentinel import (
    PerfSentinel,
    resolve_perf_history,
    resolve_sentinel_recover_steps,
    resolve_sentinel_threshold,
    resolve_sentinel_trip_steps,
    validate_perf_history_path,
)

__all__ = [
    "LATENCY_BUCKETS_S",
    "RATIO_BUCKETS",
    "MetricFamily",
    "MetricsRegistry",
    "default_registry",
    "RequestSpan",
    "RequestTracer",
    "resolve_event_log_keep",
    "resolve_event_log_max_bytes",
    "rotate_event_log",
    "validate_event_log_path",
    "SpanRecorder",
    "make_traceparent",
    "merge_timeline",
    "new_span_id",
    "new_trace_id",
    "parse_traceparent",
    "resolve_trace_sample",
    "trace_sampled",
    "TrackedJit",
    "tracked_jit",
    "annotate_costs",
    "top_offenders",
    "compile_table",
    "reset_compile_table",
    "resolve_recompile_threshold",
    "MemoryLedger",
    "default_ledger",
    "device_memory_stats",
    "memory_report",
    "reset_default_ledger",
    "resolve_hbm_budget_fraction",
    "resolve_memory_poll_sec",
    "tree_nbytes",
    "FlightRecorder",
    "build_postmortem",
    "env_fingerprint",
    "install_signal_dumps",
    "validate_postmortem_dir",
    "write_postmortem",
    "attn_flops_per_token",
    "chip_peaks",
    "decode_costs",
    "jit_costs",
    "kv_bytes_per_token",
    "model_flops_per_token",
    "prefill_costs",
    "roofline_attribution",
    "roofline_efficiency",
    "DEFAULT_OBJECTIVES",
    "OBJECTIVES",
    "SLOTracker",
    "SlidingHistogram",
    "resolve_slo_alert_log",
    "resolve_slo_spec",
    "validate_slo_alert_log_path",
    "EWMA_DECAY",
    "ewma",
    "median",
    "percentile",
    "summarize",
    "UsageLedger",
    "resolve_usage_log",
    "validate_usage_log_path",
    "PerfSentinel",
    "resolve_perf_history",
    "resolve_sentinel_recover_steps",
    "resolve_sentinel_threshold",
    "resolve_sentinel_trip_steps",
    "validate_perf_history_path",
]
