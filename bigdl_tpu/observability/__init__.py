"""Dependency-free metrics + request tracing for bigdl_tpu.

Two pieces, both stdlib-only (jax is allowed elsewhere in the package
but this subpackage must import with nothing beyond the standard
library — tests/test_observability.py enforces it):

- ``metrics``: Counter / Gauge / Histogram registry with labels and
  Prometheus text exposition (``MetricsRegistry.render()``). The
  serving engine, speculative decoders, kernel probes and StepTimer all
  publish here; ``GET /metrics`` on the API server renders the
  engine's registry.
- ``tracing``: per-request lifecycle spans (queue wait, prefill, TTFT,
  decode/TPOT, preemptions) kept in a ring buffer and optionally
  appended as JSONL to ``$BIGDL_TPU_EVENT_LOG``; ``GET /v1/stats``
  serves the snapshot.

Metric name -> engine field map (see also serving/engine.py):

==========================================  ===============================
metric                                      source
==========================================  ===============================
bigdl_tpu_request_phase_seconds{phase=...}  RequestSpan queue/prefill/decode
bigdl_tpu_ttft_seconds                      RequestSpan.ttft_s
bigdl_tpu_tpot_seconds                      LLMEngine.step() decode timing
bigdl_tpu_slot_occupancy                    len(LLMEngine._slots)
bigdl_tpu_queue_depth                       len(LLMEngine._queue)
bigdl_tpu_admissions_total                  LLMEngine._admission_step
bigdl_tpu_preemptions_total                 LLMEngine._preempt
bigdl_tpu_stall_guard_trips_total           LLMEngine._stall_steps trip
bigdl_tpu_requests_finished_total{reason}   LLMEngine._finish
bigdl_tpu_engine_steps_total                LLMEngine.step
bigdl_tpu_tokens_generated_total            LLMEngine._emit
bigdl_tpu_kernel_probe_total{kernel,...}    ops/probing.record_probe_result
bigdl_tpu_spec_accept_ratio{mode}           speculative._spec_observe
bigdl_tpu_spec_round_seconds{mode}          speculative._spec_observe
bigdl_tpu_spec_tokens_total{mode,kind}      speculative._spec_observe
bigdl_tpu_kv_cache_bytes{dtype,component}   ops/kvcache.publish_kv_cache_bytes
bigdl_tpu_kv_dequant_path_total{dtype,path} ops/attention._note_dequant_path
==========================================  ===============================

``bigdl_tpu_kv_cache_bytes`` reports the batched KV cache's logical
storage footprint split by component ("codes", "scales", "total" — int4
counts two codes per byte). ``bigdl_tpu_kv_dequant_path_total`` counts
how quantized attention dequantized: "fused" (inside the Pallas kernel)
vs "xla" (upcast fallback); increments happen at trace time, so read it
as "which path compiled", not a per-token rate.
"""

from bigdl_tpu.observability.metrics import (
    LATENCY_BUCKETS_S,
    RATIO_BUCKETS,
    MetricFamily,
    MetricsRegistry,
    default_registry,
)
from bigdl_tpu.observability.tracing import (
    RequestSpan,
    RequestTracer,
    validate_event_log_path,
)

__all__ = [
    "LATENCY_BUCKETS_S",
    "RATIO_BUCKETS",
    "MetricFamily",
    "MetricsRegistry",
    "default_registry",
    "RequestSpan",
    "RequestTracer",
    "validate_event_log_path",
]
