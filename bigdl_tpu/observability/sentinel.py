"""Perf-regression sentinel: EWMAs of the serving hot-path health
numbers (decode ms/token, HBM-roofline utilization, dispatch overhead)
checked each working step against a persisted rolling baseline.

The engine feeds :meth:`PerfSentinel.observe` once per decoding step;
the sentinel keeps 0.8/0.2 EWMAs, compares them against a baseline
loaded from the perf-history JSONL (``BIGDL_TPU_PERF_HISTORY``,
size-rotated exactly like the event log) or — on a fresh deploy —
established from the first ``warmup_steps`` steps, and reports:

- ``"trip"`` after ``trip_steps`` *consecutive* steps past threshold
  (the engine then emits the ``perf_regression`` flight event +
  postmortem + counter and starts the bounded profiler auto-capture)
- ``"recover"`` after ``recover_steps`` consecutive healthy steps
  while tripped — the same dwell/hysteresis shape as the overload
  brownout governor, so a boundary-hugging workload cannot flap.

Stdlib-only (imports only sibling ``tracing`` rotation helpers);
``tests/test_observability.py`` enforces the package contract.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from . import stats
from .tracing import (
    resolve_event_log_keep,
    resolve_event_log_max_bytes,
    rotate_event_log,
)

__all__ = [
    "PerfSentinel",
    "resolve_perf_history",
    "resolve_sentinel_recover_steps",
    "resolve_sentinel_threshold",
    "resolve_sentinel_trip_steps",
    "validate_perf_history_path",
]

# the three watched signals. Direction: for decode/dispatch ms a value
# ABOVE baseline*(1+threshold) is bad; for roofline util a value BELOW
# baseline*(1-threshold) is bad (util falling = drifting off the roof).
METRICS = ("decode_ms", "roofline_util", "dispatch_ms")
_HIGHER_IS_BAD = {"decode_ms": True, "roofline_util": False,
                  "dispatch_ms": True}

#: same 0.8/0.2 blend as the engine's tpot/dispatch EWMAs — single
#: constant in observability/stats.py
_EWMA_DECAY = stats.EWMA_DECAY
_HISTORY_EVERY = 64        # append a baseline sample every N healthy steps
_HISTORY_TAIL = 32         # baseline = median over the last N records


def resolve_sentinel_threshold(value=None) -> float:
    """Relative drift that counts as "past threshold": explicit value,
    else ``$BIGDL_TPU_SENTINEL_THRESHOLD``, else 0.5 (a metric must be
    50% worse than baseline). ValueError on a non-positive or
    non-numeric setting (utils/env_check.py surfaces this)."""
    if value is None:
        value = os.environ.get("BIGDL_TPU_SENTINEL_THRESHOLD")
    if value is None or value == "":
        return 0.5
    try:
        f = float(value)
    except (TypeError, ValueError):
        raise ValueError(
            f"sentinel threshold must be a positive number, got "
            f"{value!r}")
    if f <= 0:
        raise ValueError(
            f"sentinel threshold must be a positive number, got {f}")
    return f


def _resolve_steps(value, env_name: str, default: int, what: str) -> int:
    if value is None:
        value = os.environ.get(env_name)
    if value is None or value == "":
        return default
    try:
        n = int(value)
    except (TypeError, ValueError):
        raise ValueError(
            f"{what} must be a positive integer, got {value!r}")
    if n <= 0:
        raise ValueError(f"{what} must be a positive integer, got {n}")
    return n


def resolve_sentinel_trip_steps(value=None) -> int:
    """Consecutive past-threshold steps before the sentinel trips:
    explicit value, else ``$BIGDL_TPU_SENTINEL_TRIP_STEPS``, else 5."""
    return _resolve_steps(value, "BIGDL_TPU_SENTINEL_TRIP_STEPS", 5,
                          "sentinel trip steps")


def resolve_sentinel_recover_steps(value=None) -> int:
    """Consecutive healthy steps before a tripped sentinel recovers
    (hysteresis dwell): explicit value, else
    ``$BIGDL_TPU_SENTINEL_RECOVER_STEPS``, else 10."""
    return _resolve_steps(value, "BIGDL_TPU_SENTINEL_RECOVER_STEPS", 10,
                          "sentinel recover steps")


def resolve_perf_history(value=None) -> Optional[str]:
    """Perf-history JSONL path: explicit value, else
    ``$BIGDL_TPU_PERF_HISTORY``, else None (in-memory baseline only)."""
    if value is None:
        value = os.environ.get("BIGDL_TPU_PERF_HISTORY")
    if value is None or value == "":
        return None
    return value


def validate_perf_history_path(path: str) -> dict:
    """Report whether `path` is usable as the perf-history sink
    (utils/env_check.py surfaces this for BIGDL_TPU_PERF_HISTORY).
    Same shape as tracing.validate_event_log_path."""
    out = {"path": path}
    d = os.path.dirname(os.path.abspath(path)) or "."
    if not os.path.isdir(d):
        out["writable"] = False
        out["error"] = f"directory {d!r} does not exist"
    elif os.path.exists(path) and not os.access(path, os.W_OK):
        out["writable"] = False
        out["error"] = f"{path!r} exists and is not writable"
    elif not os.path.exists(path) and not os.access(d, os.W_OK):
        out["writable"] = False
        out["error"] = f"directory {d!r} is not writable"
    else:
        out["writable"] = True
    return out


def _median(xs: List[float]) -> float:
    # single-sourced in observability/stats.py (same math as the
    # StepTimer p50 and the bench lane percentiles)
    return stats.median(xs)


class PerfSentinel:
    """Dwell-gated regression detector over the serving perf EWMAs.

    Thread-safety: ``observe``/``snapshot`` take an internal lock (the
    engine calls observe from its worker thread; HTTP handler threads
    snapshot it for ``/v1/perf``). Trip/recover callbacks run inline in
    the observing thread and must not raise (the engine's handlers are
    postmortem-grade: they swallow their own errors)."""

    def __init__(self,
                 threshold: Optional[float] = None,
                 trip_steps: Optional[int] = None,
                 recover_steps: Optional[int] = None,
                 history_path: Optional[str] = None,
                 warmup_steps: int = 16,
                 on_trip: Optional[Callable[[dict], None]] = None,
                 on_recover: Optional[Callable[[dict], None]] = None,
                 metrics: Optional[Tuple[str, ...]] = None,
                 higher_is_bad: Optional[Dict[str, bool]] = None):
        # the machinery is metric-agnostic: a subclass (the quality
        # sentinel in observability/quality.py) supplies its own watched
        # signals + directions and everything else — EWMAs, warmup
        # baseline, JSONL history seeding, dwell hysteresis — is shared
        self.metrics: Tuple[str, ...] = (tuple(metrics) if metrics
                                         else METRICS)
        self.higher_is_bad: Dict[str, bool] = (
            dict(higher_is_bad) if higher_is_bad else dict(_HIGHER_IS_BAD))
        self.threshold = resolve_sentinel_threshold(threshold)
        self.trip_steps = resolve_sentinel_trip_steps(trip_steps)
        self.recover_steps = resolve_sentinel_recover_steps(recover_steps)
        self.history_path = (history_path if history_path is not None
                             else resolve_perf_history())
        self.warmup_steps = max(1, int(warmup_steps))
        self.on_trip = on_trip
        self.on_recover = on_recover
        self._lock = threading.Lock()
        self._ewma: Dict[str, Optional[float]] = {m: None
                                                  for m in self.metrics}
        self._baseline: Dict[str, float] = {}
        self._steps = 0
        self._bad_streak = 0
        self._good_streak = 0
        self._tripped = False
        self._tripped_metrics: List[str] = []
        self._trips = 0
        self._recoveries = 0
        self._last_trip_ts: Optional[float] = None
        self._since_history = 0
        self._history_error: Optional[str] = None
        if self.history_path:
            self._baseline = self._load_baseline(self.history_path)

    # -- baseline persistence ---------------------------------------------

    def _load_baseline(self, path: str) -> Dict[str, float]:
        """Median over the history tail — robust to the occasional
        recorded outlier. Unreadable/corrupt history degrades to an
        empty baseline (established live after warmup) rather than
        failing engine construction."""
        records: List[dict] = []
        try:
            with open(path, "r", encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        doc = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(doc, dict):
                        records.append(doc)
        except FileNotFoundError:
            return {}  # first run: no history yet is the normal state
        except OSError as e:
            self._history_error = str(e)
            return {}
        records = records[-_HISTORY_TAIL:]
        base: Dict[str, float] = {}
        for m in self.metrics:
            vals = [float(r[m]) for r in records
                    if isinstance(r.get(m), (int, float))
                    and float(r[m]) > 0]
            if vals:
                base[m] = _median(vals)
        return base

    def _append_history(self) -> None:
        """One JSONL baseline sample, size-rotated like the event log.
        Best-effort: a full disk must never take down the decode loop."""
        path = self.history_path
        if not path:
            return
        doc = {"ts": time.time()}
        for m in self.metrics:
            # called with _lock held (observe's locked section)
            if self._ewma[m] is not None:  # graftlint: disable=lock-guarded-unlocked
                doc[m] = round(self._ewma[m], 6)  # graftlint: disable=lock-guarded-unlocked
        line = json.dumps(doc, separators=(",", ":")) + "\n"
        try:
            max_bytes = resolve_event_log_max_bytes()
            keep = resolve_event_log_keep()
        except ValueError:
            max_bytes, keep = None, 1
        try:
            if (max_bytes is not None and os.path.exists(path)
                    and os.path.getsize(path) + len(line) > max_bytes):
                rotate_event_log(path, keep)
            with open(path, "a", encoding="utf-8") as fh:
                fh.write(line)
            self._history_error = None
        except OSError as e:
            self._history_error = str(e)

    # -- the step hook ----------------------------------------------------

    def observe(self, decode_ms: Optional[float] = None,
                roofline_util: Optional[float] = None,
                dispatch_ms: Optional[float] = None) -> Optional[str]:
        """Fold one step's numbers in; returns ``"trip"`` /
        ``"recover"`` on a state transition, else None."""
        return self.observe_sample(
            {"decode_ms": decode_ms, "roofline_util": roofline_util,
             "dispatch_ms": dispatch_ms})

    def observe_sample(self, sample: Dict[str, Optional[float]]
                       ) -> Optional[str]:
        """Metric-agnostic observe: fold ``{metric: value-or-None}``
        in; unknown keys are ignored, None values skip that metric this
        step. Subclasses wrap this with their own named signature."""
        transition = None
        info = None
        with self._lock:
            self._steps += 1
            for m, v in sample.items():
                if v is None or m not in self._ewma:
                    continue
                self._ewma[m] = stats.ewma(self._ewma[m], v,
                                           decay=_EWMA_DECAY)
            if not self._baseline and self._steps >= self.warmup_steps:
                self._baseline = {m: v for m, v in self._ewma.items()
                                  if v is not None and v > 0}
            bad = self._bad_metrics()
            if bad:
                self._bad_streak += 1
                self._good_streak = 0
            else:
                self._good_streak += 1
                self._bad_streak = 0
            if (not self._tripped and bad
                    and self._bad_streak >= self.trip_steps):
                self._tripped = True
                self._tripped_metrics = bad
                self._trips += 1
                self._last_trip_ts = time.time()
                transition = "trip"
                info = self._info_locked(bad)
            elif (self._tripped
                    and self._good_streak >= self.recover_steps):
                self._tripped = False
                recovered = self._tripped_metrics
                self._tripped_metrics = []
                self._recoveries += 1
                transition = "recover"
                info = self._info_locked(recovered)
            if not self._tripped and not bad:
                self._since_history += 1
                if self._since_history >= _HISTORY_EVERY:
                    self._since_history = 0
                    self._append_history()
        if transition == "trip" and self.on_trip is not None:
            self.on_trip(info)
        elif transition == "recover" and self.on_recover is not None:
            self.on_recover(info)
        return transition

    def _bad_metrics(self) -> List[str]:
        # called with _lock held (observe's locked section)
        bad = []
        for m in self.metrics:
            cur, base = self._ewma[m], self._baseline.get(m)  # graftlint: disable=lock-guarded-unlocked
            if cur is None or base is None or base <= 0:
                continue
            if self.higher_is_bad.get(m, True):
                if cur > base * (1.0 + self.threshold):
                    bad.append(m)
            elif cur < base * (1.0 - self.threshold):
                bad.append(m)
        return bad

    def _info_locked(self, metrics: List[str]) -> dict:
        # "_locked" suffix = caller holds _lock (observe / snapshot)
        return {
            "metrics": list(metrics),
            "ewma": {m: (round(v, 6) if v is not None else None)
                     for m, v in self._ewma.items()},  # graftlint: disable=lock-guarded-unlocked
            "baseline": {m: round(v, 6)
                         for m, v in self._baseline.items()},  # graftlint: disable=lock-guarded-unlocked
            "threshold": self.threshold,
            "steps": self._steps,  # graftlint: disable=lock-guarded-unlocked
        }

    # -- introspection ----------------------------------------------------

    @property
    def tripped(self) -> bool:
        with self._lock:
            return self._tripped

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "tripped": self._tripped,
                "tripped_metrics": list(self._tripped_metrics),
                "trips": self._trips,
                "recoveries": self._recoveries,
                "bad_streak": self._bad_streak,
                "good_streak": self._good_streak,
                "threshold": self.threshold,
                "trip_steps": self.trip_steps,
                "recover_steps": self.recover_steps,
                "steps": self._steps,
                "ewma": {m: (round(v, 6) if v is not None else None)
                         for m, v in self._ewma.items()},
                "baseline": {m: round(v, 6)
                             for m, v in self._baseline.items()},
                "history_path": self.history_path,
            }
            if self._last_trip_ts is not None:
                out["last_trip_ts"] = self._last_trip_ts
            if self._history_error is not None:
                out["history_error"] = self._history_error
            return out
