"""Per-tenant usage metering: a durable ledger + live rollup.

Answers the question the tenant counters (serving/overload.py) can't:
"what did tenant X actually consume, and what did each request cost?"
One append-only JSONL record per finished (or shed) request — tenant,
QoS class, prompt/generated tokens, queue-wait, TTFT/TPOT, finish
reason, preemption count — written OFF the engine thread (a
SimpleQueue feeds a daemon writer, so a slow disk can never stall a
decode step). The in-memory rollup behind ``GET /v1/usage`` carries
per-tenant totals plus a current token-burn rate, and reconciles
exactly against ``bigdl_tpu_tenant_requests_total`` and the overload
governor's per-tenant generated totals (tests/test_slo.py asserts
this).

Knobs: ``$BIGDL_TPU_USAGE_LOG`` (ledger path; unset = metering stays
in-memory only), rotation via the shared event-log size knobs.

Stdlib-only by design (see observability/metrics.py).
"""

from __future__ import annotations

import collections
import json
import logging
import os
import queue
import threading
import time
from typing import Dict, Optional

from .tracing import (
    resolve_event_log_keep,
    resolve_event_log_max_bytes,
    rotate_event_log,
    validate_event_log_path,
)

#: trailing window for the per-tenant burn rate in the rollup
_BURN_WINDOW_S = 60.0


def resolve_usage_log(value: Optional[str] = None) -> Optional[str]:
    """Ledger path: explicit value, else ``$BIGDL_TPU_USAGE_LOG``, else
    None (rollup only, no file)."""
    if value is None:
        value = os.environ.get("BIGDL_TPU_USAGE_LOG")
    return value or None


def validate_usage_log_path(path: str) -> dict:
    """Writability report for the ledger path (utils/env_check.py
    surfaces this for BIGDL_TPU_USAGE_LOG)."""
    return validate_event_log_path(path)


class _TenantUsage:
    __slots__ = ("requests", "shed", "errors", "prompt_tokens",
                 "generated_tokens", "queue_wait_s", "ttft_s_sum",
                 "ttft_n", "preemptions", "burn")

    def __init__(self):
        self.requests = 0          # finished (any reason except shed)
        self.shed = 0
        self.errors = 0
        self.prompt_tokens = 0
        self.generated_tokens = 0
        self.queue_wait_s = 0.0
        self.ttft_s_sum = 0.0
        self.ttft_n = 0
        self.preemptions = 0
        # (ts, generated_tokens) samples for the burn window
        self.burn = collections.deque()


class UsageLedger:
    """Durable per-request usage records + live per-tenant rollup.

    ``record_finish`` / ``record_shed`` are called from the engine
    thread and must stay cheap: they update the rollup under a lock and
    enqueue the JSONL doc for the writer thread. ``snapshot()`` is the
    ``GET /v1/usage`` document; ``drain()`` blocks until every queued
    record hit the file (tests and graceful shutdown)."""

    def __init__(self, path: Optional[str] = None, time_fn=time.time):
        if path is None:
            path = resolve_usage_log()
        self.path = path or None
        self._time = time_fn
        self._lock = threading.Lock()
        self._tenants: Dict[str, _TenantUsage] = {}
        self._records_total = 0
        self._dropped = 0
        self._sink_dead = False
        try:
            self._max_bytes = resolve_event_log_max_bytes()
            self._keep = resolve_event_log_keep()
        except ValueError:
            self._max_bytes, self._keep = None, 1
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._writer: Optional[threading.Thread] = None
        if self.path is not None:
            self._writer = threading.Thread(
                target=self._writer_loop, name="usage-ledger",
                daemon=True)
            self._writer.start()

    # -- engine-thread feeds ------------------------------------------------

    def record_finish(self, rid: str, tenant: str, qos: str,
                      prompt_tokens: int, generated_tokens: int,
                      finish_reason: str,
                      queue_wait_s: Optional[float] = None,
                      ttft_s: Optional[float] = None,
                      tpot_s: Optional[float] = None,
                      preemptions: int = 0) -> None:
        now = self._time()
        tenant = tenant or "default"
        with self._lock:
            t = self._tenants.setdefault(tenant, _TenantUsage())
            t.requests += 1
            if finish_reason == "error" or finish_reason not in (
                    "stop", "length", "abort", "deadline"):
                t.errors += 1
            t.prompt_tokens += int(prompt_tokens)
            t.generated_tokens += int(generated_tokens)
            if queue_wait_s is not None:
                t.queue_wait_s += queue_wait_s
            if ttft_s is not None:
                t.ttft_s_sum += ttft_s
                t.ttft_n += 1
            t.preemptions += int(preemptions)
            t.burn.append((now, int(generated_tokens)))
            self._trim_burn(t, now)
            self._records_total += 1
        doc = {"ts": round(now, 3), "rid": rid, "tenant": tenant,
               "qos": qos, "outcome": "finish",
               "finish_reason": finish_reason,
               "prompt_tokens": int(prompt_tokens),
               "generated_tokens": int(generated_tokens)}
        if queue_wait_s is not None:
            doc["queue_wait_s"] = round(queue_wait_s, 4)
        if ttft_s is not None:
            doc["ttft_s"] = round(ttft_s, 4)
        if tpot_s is not None:
            doc["tpot_s"] = round(tpot_s, 5)
        if preemptions:
            doc["preemptions"] = int(preemptions)
        self._enqueue(doc)

    def record_shed(self, rid: str, tenant: str, qos: str,
                    reason: str) -> None:
        now = self._time()
        tenant = tenant or "default"
        with self._lock:
            t = self._tenants.setdefault(tenant, _TenantUsage())
            t.shed += 1
            self._records_total += 1
        self._enqueue({"ts": round(now, 3), "rid": rid,
                       "tenant": tenant, "qos": qos, "outcome": "shed",
                       "reason": reason})

    @staticmethod
    def _trim_burn(t: _TenantUsage, now: float) -> None:
        horizon = now - _BURN_WINDOW_S
        while t.burn and t.burn[0][0] < horizon:
            t.burn.popleft()

    # -- writer thread ------------------------------------------------------

    def _enqueue(self, doc: dict) -> None:
        if self.path is not None and not self._sink_dead:
            self._q.put(doc)

    def _writer_loop(self) -> None:
        while True:
            doc = self._q.get()
            if doc is None:            # drain barrier
                continue
            if isinstance(doc, threading.Event):
                doc.set()
                continue
            self._write(doc)

    def _write(self, doc: dict) -> None:
        if self._sink_dead:
            return
        line = json.dumps(doc, separators=(",", ":")) + "\n"
        try:
            if (self._max_bytes is not None
                    and os.path.exists(self.path)
                    and os.path.getsize(self.path) + len(line)
                    > self._max_bytes):
                rotate_event_log(self.path, self._keep)
            with open(self.path, "a", encoding="utf-8") as fh:
                fh.write(line)
        except OSError as e:
            self._sink_dead = True
            with self._lock:
                self._dropped += 1
            logging.getLogger(__name__).warning(
                "usage ledger %s unwritable (%s); ledger disabled "
                "(rollup keeps running)", self.path, e)

    def drain(self, timeout: float = 5.0) -> bool:
        """Block until every record enqueued so far is on disk. True on
        success, False on timeout or when no file is configured."""
        if self.path is None or self._writer is None:
            return False
        ev = threading.Event()
        self._q.put(ev)
        return ev.wait(timeout)

    # -- rollup -------------------------------------------------------------

    def snapshot(self) -> dict:
        """The ``GET /v1/usage`` document: per-tenant totals + current
        burn (tokens/s over the last minute)."""
        now = self._time()
        tenants = {}
        with self._lock:
            for name, t in sorted(self._tenants.items()):
                self._trim_burn(t, now)
                burn_tokens = sum(n for _, n in t.burn)
                tenants[name] = {
                    "requests": t.requests,
                    "shed": t.shed,
                    "errors": t.errors,
                    "prompt_tokens": t.prompt_tokens,
                    "generated_tokens": t.generated_tokens,
                    "queue_wait_s": round(t.queue_wait_s, 3),
                    "mean_ttft_s": (round(t.ttft_s_sum / t.ttft_n, 4)
                                    if t.ttft_n else None),
                    "preemptions": t.preemptions,
                    "burn_tokens_per_s": round(
                        burn_tokens / _BURN_WINDOW_S, 3),
                }
            out = {"tenants": tenants,
                   "records_total": self._records_total,
                   "ledger_path": self.path,
                   "ledger_dropped": self._dropped}
        return out

    def totals(self) -> Dict[str, Dict[str, int]]:
        """Bare per-tenant counters for reconciliation tests:
        ``{tenant: {"requests", "shed", "generated_tokens"}}``."""
        with self._lock:
            return {name: {"requests": t.requests, "shed": t.shed,
                           "generated_tokens": t.generated_tokens}
                    for name, t in self._tenants.items()}


__all__ = [
    "UsageLedger",
    "resolve_usage_log",
    "validate_usage_log_path",
]
