"""Compile telemetry: ``tracked_jit`` wrappers around every ``jax.jit``.

The hot paths in this stack are configuration-sensitive by design —
prefill compiles per (prompt-length bucket, kv dtype), the engine keeps
per-shape sampler executables, speculative rounds compile per gamma.
That is the intended cost model ("compile few, reuse forever"), but it
also means a mis-bucketed client or a dtype knob flipped mid-flight can
silently recompile every step and nothing in steady-state latency
metrics says why. ``tracked_jit(name, fn, ...)`` is ``jax.jit`` plus an
accounting layer:

- a per-wrapper signature set (pytree structure + abstract shape/dtype
  of every leaf) detects first-call-for-a-signature, i.e. a compile;
- each compile increments ``bigdl_tpu_jit_compiles_total{fn=name}`` and
  observes the first-call wall time (trace + lower + compile + first
  dispatch) into ``bigdl_tpu_jit_compile_seconds{fn=name}``;
- the process-wide compile table (``compile_table()``) keeps per-name
  counts, cumulative seconds, and the most recent signatures — embedded
  in postmortem dumps (observability/flight.py) and BENCH json;
- crossing the recompile-storm threshold (``warn_threshold=`` or
  ``$BIGDL_TPU_RECOMPILE_WARN``, default 8 compiles per name) logs one
  warning and flags the table entry;
- each compile also captures the executable's
  ``compiled.memory_analysis()`` (temp / argument / output /
  generated-code bytes) next to its seconds, and the table keeps the
  per-name ``peak_temp_bytes`` — the scratch HBM a jitted fn needs on
  top of its operands. Capture goes through jax's AOT path
  (``lower(...).compile()`` on abstract placeholder shapes), whose
  executable cache is SEPARATE from the traced-call cache: the first
  capture per signature pays one extra XLA compile. Set
  ``$BIGDL_TPU_COMPILE_MEMORY=0`` to skip capture when compile wall
  time matters more than memory attribution.

Detection is signature-based rather than hooking XLA: it is exact for
the wrappers' own cache (jax.jit keys its trace cache on the same
abstract signature) and costs one tree_flatten per call.

Stdlib-only at import time (tests/test_observability.py enforces it):
jax is imported lazily inside ``tracked_jit``, which only ever runs from
modules that already depend on jax.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

DEFAULT_RECOMPILE_WARN = 8
# signatures kept per name in the compile table (newest last); the
# counters keep counting past this bound
MAX_SIGNATURES_PER_NAME = 32
COMPILE_MEMORY_ENV = "BIGDL_TPU_COMPILE_MEMORY"


def memory_capture_enabled() -> bool:
    """Whether per-compile memory_analysis capture is on (default yes;
    ``$BIGDL_TPU_COMPILE_MEMORY`` in {0, false, off, no} disables)."""
    return os.environ.get(COMPILE_MEMORY_ENV, "1").strip().lower() \
        not in ("0", "false", "off", "no")

_lock = threading.Lock()
_table: Dict[str, Dict[str, Any]] = {}

# per-name DISPATCH counts (every call of a tracked executable, compile
# or cache hit). The resident-decode work (ISSUE 14) is measured in
# host dispatches per engine step; this table is how tests assert
# "exactly one" without profiling the runtime.
_dispatch_lock = threading.Lock()
_dispatches: Dict[str, int] = {}


def _count_dispatch(name: str) -> None:
    with _dispatch_lock:
        _dispatches[name] = _dispatches.get(name, 0) + 1


def dispatch_table() -> Dict[str, int]:
    """Snapshot of per-name tracked-jit dispatch counts since process
    start (or the last ``reset_dispatch_table()``). One entry per
    tracked executable name; every __call__ counts, compiles included."""
    with _dispatch_lock:
        return dict(_dispatches)


def reset_dispatch_table() -> None:
    """Zero the per-name dispatch counters (tests bracket an engine
    step with reset + dispatch_table() to count its host dispatches)."""
    with _dispatch_lock:
        _dispatches.clear()

# first-call-for-a-signature compiles currently executing, process-wide.
# A compile blocks the engine's step loop for seconds-to-minutes (real
# TPU lowerings far exceed any sane wedge threshold), during which the
# step heartbeat goes stale exactly like a genuine hang — liveness
# checks (api_server /health) consult this to tell the two apart.
_inflight_lock = threading.Lock()
_compiles_inflight = 0


def compiles_in_progress() -> int:
    """Number of tracked first-call compiles executing right now.
    Nonzero means a stale step heartbeat is the compiler working, not a
    wedged replica. (A compile that itself hangs forever is reported as
    'busy' rather than 'wedged' — the supervisor's spawn timeout is the
    backstop for that.)"""
    with _inflight_lock:
        return _compiles_inflight


class _CompileInFlight:
    """Context manager bracketing one first-call compile."""

    def __enter__(self):
        global _compiles_inflight
        with _inflight_lock:
            _compiles_inflight += 1
        return self

    def __exit__(self, *exc):
        global _compiles_inflight
        with _inflight_lock:
            _compiles_inflight -= 1
        return False


def resolve_recompile_threshold(value: Optional[object] = None) -> int:
    """The recompile-storm warning threshold: explicit value, else
    ``$BIGDL_TPU_RECOMPILE_WARN``, else the default. Raises ValueError
    on a non-positive or non-integer setting (utils/env_check.py
    surfaces this for the env var)."""
    if value is None:
        value = os.environ.get("BIGDL_TPU_RECOMPILE_WARN")
    if value is None or value == "":
        return DEFAULT_RECOMPILE_WARN
    try:
        n = int(value)
    except (TypeError, ValueError):
        raise ValueError(
            f"recompile threshold must be a positive integer, got "
            f"{value!r}")
    if n <= 0:
        raise ValueError(
            f"recompile threshold must be a positive integer, got {n}")
    return n


def _leaf_sig(x: Any) -> Tuple:
    """Hashable abstract signature of one DYNAMIC (traced) pytree leaf,
    matching how jax's trace cache keys it: arrays by (dtype, shape);
    python bool/int/float/complex by TYPE ONLY (jax traces them as
    weak-typed 0-d arrays, so the value does not recompile — a beam
    step counter t=0,1,2,... reuses one executable); anything else by
    type (+hash when it has one)."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return (str(dtype), tuple(shape))
    if isinstance(x, (bool, int, float, complex)):
        return (type(x).__name__,)
    try:
        return (type(x).__name__, hash(x))
    except TypeError:
        return (type(x).__name__,)


def _static_sig(x: Any) -> Tuple:
    """Signature of a static_argnums/static_argnames argument: keyed by
    VALUE — that is what jax keys compiles on for statics."""
    try:
        hash(x)
        return (type(x).__name__, x)
    except TypeError:
        return (type(x).__name__, repr(x))


def _sig_str(sig: Tuple) -> str:
    """Compact human-readable form for the compile table (arrays as
    'f32[2,8]'-style, statics as key=value)."""
    _treedef, leaves, statics = sig
    parts: List[str] = []
    for leaf in leaves:
        if (len(leaf) == 2 and isinstance(leaf[1], tuple)
                and all(isinstance(d, int) for d in leaf[1])):
            parts.append(f"{leaf[0]}[{','.join(map(str, leaf[1]))}]")
        else:
            parts.append(repr(leaf[1]) if len(leaf) > 1 else leaf[0])
    for key, val in statics:
        parts.append(f"{key}={val[1]!r}")
    return "(" + ", ".join(parts) + ")"


class TrackedJit:
    """A jax.jit-compiled callable with compile accounting.

    Calls pass straight through to the jitted function; the only
    per-call overhead on the cache-hit path is one tree_flatten of the
    arguments. Unknown attributes (``lower``, ``clear_cache``, ...)
    forward to the underlying jitted callable.
    """

    def __init__(self, name: str, fn, registry=None,
                 warn_threshold: Optional[int] = None, **jit_kwargs):
        import jax

        self.name = name
        self._fn = fn
        self._jitted = jax.jit(fn, **jit_kwargs)
        self._flatten = jax.tree_util.tree_flatten
        self._registry = registry
        try:
            self._warn_threshold = resolve_recompile_threshold(
                warn_threshold)
        except ValueError:
            logger.warning(
                "invalid BIGDL_TPU_RECOMPILE_WARN=%r; using default %d",
                os.environ.get("BIGDL_TPU_RECOMPILE_WARN"),
                DEFAULT_RECOMPILE_WARN)
            self._warn_threshold = DEFAULT_RECOMPILE_WARN
        sa = jit_kwargs.get("static_argnums", ())
        self._static_argnums = (sa,) if isinstance(sa, int) else tuple(sa)
        sn = jit_kwargs.get("static_argnames", ())
        self._static_argnames = (sn,) if isinstance(sn, str) else tuple(sn)
        self._seen: set = set()
        self._seen_lock = threading.Lock()

    # -- call path -----------------------------------------------------------

    def _signature(self, args, kwargs) -> Tuple:
        """Mirror jax's compile key: static args by value, everything
        else by pytree structure + abstract leaf signature."""
        statics: List[Tuple] = []
        dyn_args = []
        for i, a in enumerate(args):
            if i in self._static_argnums:
                statics.append((i, _static_sig(a)))
            else:
                dyn_args.append(a)
        dyn_kwargs = {}
        for k, v in kwargs.items():
            if k in self._static_argnames:
                statics.append((k, _static_sig(v)))
            else:
                dyn_kwargs[k] = v
        leaves, treedef = self._flatten((dyn_args, dyn_kwargs))
        return (treedef, tuple(_leaf_sig(x) for x in leaves),
                tuple(statics))

    def __call__(self, *args, **kwargs):
        _count_dispatch(self.name)
        try:
            sig = self._signature(args, kwargs)
            with self._seen_lock:
                hit = sig in self._seen
        except Exception:
            # unhashable exotic leaf: telemetry must never break the
            # compiled path — run untracked
            return self._jitted(*args, **kwargs)
        if hit:
            return self._jitted(*args, **kwargs)
        # placeholders must be built BEFORE the call: donate_argnums
        # deletes input buffers during it
        placeholders = self._placeholders(args, kwargs)
        with _CompileInFlight():
            t0 = time.perf_counter()
            out = self._jitted(*args, **kwargs)
            # dispatch-return time on a FIRST call is dominated by the
            # synchronous trace+compile — that is exactly what the
            # compile table records, so no device fence here
            dt = time.perf_counter() - t0  # graftlint: disable=jax-unsynced-timing
            with self._seen_lock:
                self._seen.add(sig)
            # the AOT memory_analysis below compiles the signature a
            # second time — keep it inside the in-flight bracket so the
            # heartbeat stays excused for its duration too
            self._record_compile(sig, dt,
                                 self._memory_analysis(placeholders))
        return out

    def __getattr__(self, item):
        return getattr(self._jitted, item)

    # -- memory analysis -----------------------------------------------------

    def _placeholders(self, args, kwargs):
        """(args, kwargs) with every dynamic array leaf replaced by a
        ShapeDtypeStruct — abstract inputs for the AOT lowering, safe
        against donated buffers. Statics keep their real values (jax
        keys compiles on them); non-array dynamic leaves (python
        scalars) pass through, matching how the traced call saw them.
        None when capture is disabled."""
        if not memory_capture_enabled():
            return None
        try:
            import jax

            def abstract(x):
                shape = getattr(x, "shape", None)
                dtype = getattr(x, "dtype", None)
                if shape is not None and dtype is not None:
                    return jax.ShapeDtypeStruct(tuple(shape), dtype)
                return x

            ph_args = tuple(
                a if i in self._static_argnums
                else jax.tree_util.tree_map(abstract, a)
                for i, a in enumerate(args))
            ph_kwargs = {
                k: v if k in self._static_argnames
                else jax.tree_util.tree_map(abstract, v)
                for k, v in kwargs.items()}
            return (ph_args, ph_kwargs)
        except Exception:
            return None

    def _memory_analysis(self, placeholders) -> Optional[Dict[str, int]]:
        """Best-effort CompiledMemoryStats for one signature via the
        AOT path (its executable cache is separate from the traced
        call's, so the first capture per signature pays one extra XLA
        compile — see module docstring). Never raises."""
        if placeholders is None:
            return None
        try:
            ph_args, ph_kwargs = placeholders
            stats = self._jitted.lower(
                *ph_args, **ph_kwargs).compile().memory_analysis()
            if stats is None:
                return None
            return {
                "temp_bytes": int(stats.temp_size_in_bytes),
                "argument_bytes": int(stats.argument_size_in_bytes),
                "output_bytes": int(stats.output_size_in_bytes),
                "alias_bytes": int(stats.alias_size_in_bytes),
                "generated_code_bytes": int(
                    stats.generated_code_size_in_bytes),
            }
        except Exception:
            return None

    # -- accounting ----------------------------------------------------------

    @property
    def compiles(self) -> int:
        with self._seen_lock:
            return len(self._seen)

    def _record_compile(self, sig: Tuple, seconds: float,
                        memory: Optional[Dict[str, int]] = None) -> None:
        try:
            self._observe_metrics(seconds)
        except Exception:
            pass
        storm = False
        with _lock:
            ent = _table.setdefault(self.name, {
                "compiles": 0, "total_s": 0.0, "signatures": [],
                "last_compile_ts": 0.0, "storm": False,
                "peak_temp_bytes": 0})
            ent.setdefault("peak_temp_bytes", 0)
            ent["compiles"] += 1
            ent["total_s"] += seconds
            ent["last_compile_ts"] = time.time()
            sigs = ent["signatures"]
            row = {"signature": _sig_str(sig),
                   "seconds": round(seconds, 6)}
            if memory is not None:
                row["memory"] = dict(memory)
                ent["peak_temp_bytes"] = max(
                    ent["peak_temp_bytes"], memory.get("temp_bytes", 0))
            sigs.append(row)
            del sigs[:-MAX_SIGNATURES_PER_NAME]
            if ent["compiles"] >= self._warn_threshold \
                    and not ent["storm"]:
                ent["storm"] = True
                storm = True
        if storm:
            logger.warning(
                "recompile storm: %r compiled %d times (threshold %d) — "
                "check for unbucketed shapes or per-call dtype churn",
                self.name, self._warn_threshold, self._warn_threshold)

    def _observe_metrics(self, seconds: float) -> None:
        from bigdl_tpu.observability.metrics import default_registry

        regs = [default_registry()]
        if self._registry is not None and self._registry is not regs[0]:
            regs.append(self._registry)
        for reg in regs:
            reg.counter(
                "bigdl_tpu_jit_compiles_total",
                "jax.jit compiles per tracked executable "
                "(one per new abstract shape signature).",
                labelnames=("fn",)).labels(self.name).inc()
            reg.histogram(
                "bigdl_tpu_jit_compile_seconds",
                "First-call wall time per new signature "
                "(trace + lower + compile + first dispatch).",
                labelnames=("fn",)).labels(self.name).observe(seconds)


def tracked_jit(name: str, fn=None, *, registry=None,
                warn_threshold: Optional[int] = None, **jit_kwargs):
    """jax.jit with compile telemetry (see module docstring).

    ``tracked_jit("decode", fn, donate_argnums=(2,))`` or as a
    decorator factory: ``@tracked_jit("decode", donate_argnums=(2,))``.
    ``registry`` additionally mirrors the compile metrics into a
    non-default registry (e.g. the engine's)."""
    if fn is None:
        def deco(f):
            return TrackedJit(name, f, registry=registry,
                              warn_threshold=warn_threshold, **jit_kwargs)
        return deco
    return TrackedJit(name, fn, registry=registry,
                      warn_threshold=warn_threshold, **jit_kwargs)


def compile_table() -> Dict[str, Dict[str, Any]]:
    """JSON-ready snapshot of the process-wide compile table:
    {name: {compiles, total_s, peak_temp_bytes, signatures[...],
    last_compile_ts, storm}}. Signature rows carry a "memory" dict
    (temp/argument/output/alias/generated-code bytes) when capture was
    on and the AOT analysis succeeded."""
    with _lock:
        out: Dict[str, Dict[str, Any]] = {}
        for name, ent in sorted(_table.items()):
            out[name] = {
                "compiles": ent["compiles"],
                "total_s": round(ent["total_s"], 6),
                "last_compile_ts": round(ent["last_compile_ts"], 6),
                "storm": ent["storm"],
                "peak_temp_bytes": ent.get("peak_temp_bytes", 0),
                "signatures": [
                    {k: (dict(v) if isinstance(v, dict) else v)
                     for k, v in s.items()} for s in ent["signatures"]],
            }
            for key in ("analytical_flops", "analytical_hbm_bytes"):
                if key in ent:
                    out[name][key] = ent[key]
        return out


def annotate_costs(name: str, flops: Optional[float] = None,
                   hbm_bytes: Optional[float] = None) -> None:
    """Attach analytical roofline costs (observability/roofline.py
    ``jit_costs``) to a tracked_jit's table entry, so the compile table
    carries bytes-moved/FLOPs next to compile counts. Creates the entry
    when the jit has not compiled yet (costs are known at engine build,
    compiles happen lazily)."""
    with _lock:
        ent = _table.setdefault(name, {
            "compiles": 0, "total_s": 0.0, "signatures": [],
            "last_compile_ts": 0.0, "storm": False,
            "peak_temp_bytes": 0})
        if flops is not None:
            ent["analytical_flops"] = float(flops)
        if hbm_bytes is not None:
            ent["analytical_hbm_bytes"] = float(hbm_bytes)


def top_offenders(limit: int = 8) -> list:
    """Tracked jits ranked by analytical HBM bytes moved (descending) —
    the roofline view of "which executable is the bandwidth bill".
    Entries without cost annotation rank last (by compile time)."""
    table = compile_table()
    rows = []
    for name, ent in table.items():
        rows.append({
            "name": name,
            "analytical_hbm_bytes": ent.get("analytical_hbm_bytes", 0.0),
            "analytical_flops": ent.get("analytical_flops", 0.0),
            "compiles": ent["compiles"],
            "total_s": ent["total_s"],
        })
    rows.sort(key=lambda r: (-r["analytical_hbm_bytes"], -r["total_s"]))
    return rows[:max(0, int(limit))]


def reset_compile_table() -> None:
    """Drop the process-wide table (tests / fresh bench runs). Does NOT
    reset per-wrapper signature sets — already-compiled executables stay
    uncounted, which is the truthful reading."""
    with _lock:
        _table.clear()
