"""Flight recorder + postmortem dumps for the serving engine.

When a serving process dies mid-step, stalls, or is SIGTERMed during a
deploy, steady-state metrics say nothing about what it was *doing*. Two
pieces fix that:

- ``FlightRecorder``: a bounded ring buffer of structured engine events
  (per-step occupancy/queue depth, admission starts/completions,
  preemptions, stall-guard trips, finishes, exceptions). Appending is a
  lock + deque append — safe inside the hot step loop. The engine owns
  one (``LLMEngine.flight``).

- postmortems: ``build_postmortem()`` assembles one JSON-ready dict —
  flight-recorder tail, recent request spans, full metrics snapshot,
  the jit compile table (compile_watch), config + environment
  fingerprint, and the active exception when there is one.
  ``write_postmortem()`` writes it to ``$BIGDL_TPU_POSTMORTEM_DIR``
  (atomically, via tmp + rename) and NEVER raises — a failing dump must
  not mask the original failure. The engine writes one on step
  exceptions and stall-guard trips; ``install_signal_dumps()`` hooks
  SIGTERM/SIGINT for operator kills; ``GET /v1/debug/dump`` serves the
  same dict from a live server.

Stdlib-only (tests/test_observability.py enforces it for this
subpackage).
"""

from __future__ import annotations

import collections
import json
import logging
import os
import signal
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

POSTMORTEM_DIR_ENV = "BIGDL_TPU_POSTMORTEM_DIR"


class FlightRecorder:
    """Thread-safe bounded ring buffer of structured engine events.

    Each event is a flat dict ``{"ts": ..., "event": ..., **fields}``;
    the buffer holds the most recent ``capacity`` of them. Recording
    never raises and never blocks beyond a lock."""

    def __init__(self, capacity: int = 1024):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self._lock = threading.Lock()
        self._events: "collections.deque[dict]" = \
            collections.deque(maxlen=capacity)
        self._total = 0

    def record(self, event: str, **fields) -> None:
        entry = {"ts": round(time.time(), 6), "event": event}
        entry.update(fields)
        with self._lock:
            self._events.append(entry)
            self._total += 1

    def snapshot(self, last: Optional[int] = None) -> List[dict]:
        """Most recent events, oldest first (all when ``last`` is
        None)."""
        with self._lock:
            ev = list(self._events)
        if last is not None and last >= 0:
            ev = ev[-last:]
        return ev

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    @property
    def total_recorded(self) -> int:
        """Events recorded over the recorder's lifetime (>= len when
        the ring has wrapped)."""
        with self._lock:
            return self._total

    def clear(self) -> None:
        with self._lock:
            self._events.clear()


def exception_fields(error: BaseException, max_len: int = 200) -> dict:
    """Flat ``{"error_type", "error_msg"}`` fields for a flight event:
    the exception's type name and its truncated message, so events like
    ``step_exception`` / ``quarantined`` are debuggable straight from
    the ring buffer without chasing the postmortem file (which carries
    the full traceback)."""
    msg = str(error)
    if len(msg) > max_len:
        msg = msg[: max_len - 1] + "…"
    return {"error_type": type(error).__name__, "error_msg": msg}


def env_fingerprint() -> dict:
    """Process + environment identity for a postmortem: interpreter,
    pid, argv, accelerator-relevant env flags, and library versions for
    whatever is ALREADY imported (no new imports — a dump must work
    from a dying process)."""
    out: dict = {
        "python": sys.version.split()[0],
        "pid": os.getpid(),
        "argv": list(sys.argv),
        "env": {k: v for k, v in os.environ.items()
                if k.startswith(("JAX_", "XLA_", "BIGDL_", "LIBTPU"))},
    }
    for mod in ("jax", "numpy", "bigdl_tpu"):
        m = sys.modules.get(mod)
        ver = getattr(m, "__version__", None) if m is not None else None
        if ver is not None:
            out[mod] = ver
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None:
        try:
            out["backend"] = jax_mod.default_backend()
        except Exception:
            pass
    return out


def build_postmortem(reason: str, *, flight: Optional[FlightRecorder] = None,
                     tracer=None, registry=None,
                     config: Optional[dict] = None,
                     memory: Optional[dict] = None,
                     error: Optional[BaseException] = None,
                     events_tail: int = 256,
                     spans_tail: int = 32) -> dict:
    """Assemble the postmortem dict. Every section degrades to a
    partial record rather than failing the dump. ``memory`` is a
    ready-made snapshot (the engine passes its ledger view); when
    omitted, the process-default MemoryLedger's snapshot is used so
    even bare dumps answer "where was HBM when it died"."""
    out: dict = {"reason": reason, "ts": round(time.time(), 6)}
    if error is not None:
        out["error"] = {
            "type": type(error).__name__,
            "message": str(error),
            "traceback": traceback.format_exception(
                type(error), error, error.__traceback__),
        }
    try:
        out["fingerprint"] = env_fingerprint()
    except Exception as e:
        out["fingerprint"] = {"error": repr(e)}
    if config is not None:
        out["config"] = config
    if flight is not None:
        try:
            out["flight"] = flight.snapshot(last=events_tail)
            out["flight_total_events"] = flight.total_recorded
        except Exception as e:
            out["flight"] = [{"event": "snapshot_error", "error": repr(e)}]
    if tracer is not None:
        try:
            out["spans"] = tracer.snapshot(recent=spans_tail)
        except Exception as e:
            out["spans"] = {"error": repr(e)}
    if registry is not None:
        try:
            out["metrics"] = registry.snapshot()
        except Exception as e:
            out["metrics"] = {"error": repr(e)}
    try:
        from bigdl_tpu.observability.compile_watch import compile_table

        out["compile_table"] = compile_table()
    except Exception as e:
        out["compile_table"] = {"error": repr(e)}
    if memory is not None:
        out["memory"] = memory
    else:
        try:
            from bigdl_tpu.observability.memory import default_ledger

            out["memory"] = default_ledger().snapshot()
        except Exception as e:
            out["memory"] = {"error": repr(e)}
    return out


def postmortem_dir() -> Optional[str]:
    return os.environ.get(POSTMORTEM_DIR_ENV) or None


def validate_postmortem_dir(path: str) -> dict:
    """Report whether `path` can receive postmortem dumps
    (utils/env_check.py surfaces this for BIGDL_TPU_POSTMORTEM_DIR).
    A missing directory is fine — it is created at dump time — as long
    as some existing ancestor is writable."""
    out = {"path": path, "exists": os.path.isdir(path)}
    if os.path.isdir(path):
        out["writable"] = os.access(path, os.W_OK)
        if not out["writable"]:
            out["error"] = f"directory {path!r} is not writable"
        return out
    if os.path.exists(path):
        out["writable"] = False
        out["error"] = f"{path!r} exists and is not a directory"
        return out
    parent = os.path.abspath(path)
    while parent and not os.path.isdir(parent):
        nxt = os.path.dirname(parent)
        if nxt == parent:
            break
        parent = nxt
    out["writable"] = bool(parent) and os.access(parent, os.W_OK)
    if not out["writable"]:
        out["error"] = f"no writable ancestor for {path!r}"
    return out


def write_postmortem(reason: str, *, directory: Optional[str] = None,
                     **build_kwargs) -> Optional[str]:
    """Write one postmortem JSON; returns its path, or None when no
    directory is configured (``directory=`` or
    ``$BIGDL_TPU_POSTMORTEM_DIR``). Never raises: dump failures are
    logged and swallowed so they cannot mask the original failure."""
    try:
        d = directory or postmortem_dir()
        if not d:
            return None
        dump = build_postmortem(reason, **build_kwargs)
        os.makedirs(d, exist_ok=True)
        safe = "".join(c if c.isalnum() or c in "-_" else "-"
                       for c in reason) or "dump"
        path = os.path.join(
            d, f"postmortem-{int(time.time() * 1000)}-{os.getpid()}"
               f"-{safe}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(dump, f, default=repr)
        os.replace(tmp, path)
        return path
    except Exception:
        logger.warning("postmortem dump failed", exc_info=True)
        return None


def install_signal_dumps(write_fn, signals=(signal.SIGTERM, signal.SIGINT)):
    """Install handlers that call ``write_fn(reason)`` (e.g. the
    engine's postmortem writer) on SIGTERM/SIGINT, then chain to the
    previous handler so default termination semantics are preserved.
    Main-thread only (CPython restriction); returns {signum: previous
    handler}."""
    previous: Dict[int, Any] = {}

    def handler(signum, frame):
        try:
            write_fn(f"signal_{signal.Signals(signum).name}")
        except Exception:
            logger.warning("signal postmortem failed", exc_info=True)
        prev = previous.get(signum)
        if callable(prev):
            prev(signum, frame)
        elif prev == signal.SIG_DFL:
            signal.signal(signum, signal.SIG_DFL)
            signal.raise_signal(signum)
        # SIG_IGN / None: swallow, matching the prior disposition

    for s in signals:
        previous[s] = signal.signal(s, handler)
    return previous
