"""HBM memory ledger: static byte accounting + live device telemetry.

The whole stack exists to fit models into scarce accelerator memory
(low-bit weights, block-scaled KV caches), yet nothing at runtime could
answer "where did HBM go?" — the footprint claims lived in one unit
test. The ``MemoryLedger`` closes that gap with two complementary
views:

- **static**: exact packed bytes per registered allocation, grouped by
  kind ("weights", "kv_cache", "lora", "optimizer", ...). Producers
  register at build/allocation time (the serving engine registers its
  params and batched KV cache; ``Generator``/``tpu_onchip`` register
  theirs) with the same byte conventions the allocators use — int4 at
  two codes per byte, scale planes counted separately — so
  ``static_report()`` matches allocated ``nbytes`` exactly.
- **live**: ``device.memory_stats()`` (``bytes_in_use``,
  ``peak_bytes_in_use``, ``bytes_limit``) polled at most once per
  ``$BIGDL_TPU_MEMORY_POLL_SEC`` (default 1.0s). CPU/interpret backends
  return no stats; every consumer degrades to "no telemetry" rather
  than failing — admission control admits, gauges stay unset.

``headroom()`` combines the two into budget math: the serving engine
defers admissions whose projected usage exceeds
``$BIGDL_TPU_HBM_BUDGET_FRACTION`` (a float in (0, 1], default 0.9) of
``bytes_limit``. Tests inject a deterministic ``stats_provider``
callable instead of a real device.

``publish()`` exports ``bigdl_tpu_hbm_bytes{kind=...}`` (static kinds
plus ``device_in_use`` / ``device_peak`` / ``device_limit``) and
``bigdl_tpu_hbm_headroom_bytes`` (budget minus in-use; negative means
overdraft) to a metrics registry.

Stdlib-only at import time (tests/test_observability.py enforces it):
jax is imported lazily inside ``device_memory_stats``/``tree_nbytes``.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Callable, Dict, Optional

logger = logging.getLogger(__name__)

HBM_BUDGET_FRACTION_ENV = "BIGDL_TPU_HBM_BUDGET_FRACTION"
MEMORY_POLL_SEC_ENV = "BIGDL_TPU_MEMORY_POLL_SEC"
DEFAULT_HBM_BUDGET_FRACTION = 0.9
DEFAULT_MEMORY_POLL_SEC = 1.0

# device.memory_stats() keys the ledger snapshots/headroom math read
_STATS_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")


def resolve_hbm_budget_fraction(value: Optional[object] = None) -> float:
    """The admission HBM budget as a fraction of ``bytes_limit``:
    explicit value, else ``$BIGDL_TPU_HBM_BUDGET_FRACTION``, else the
    default. Raises ValueError outside (0, 1] (utils/env_check.py
    surfaces this for the env var)."""
    if value is None:
        value = os.environ.get(HBM_BUDGET_FRACTION_ENV)
    if value is None or value == "":
        return DEFAULT_HBM_BUDGET_FRACTION
    try:
        f = float(value)
    except (TypeError, ValueError):
        raise ValueError(
            f"HBM budget fraction must be a float in (0, 1], got "
            f"{value!r}")
    if not (0.0 < f <= 1.0):
        raise ValueError(
            f"HBM budget fraction must be in (0, 1], got {f}")
    return f


def resolve_memory_poll_sec(value: Optional[object] = None) -> float:
    """Minimum seconds between live ``memory_stats()`` polls: explicit
    value, else ``$BIGDL_TPU_MEMORY_POLL_SEC``, else the default.
    Raises ValueError on a negative or non-numeric setting (0 disables
    throttling — every read polls)."""
    if value is None:
        value = os.environ.get(MEMORY_POLL_SEC_ENV)
    if value is None or value == "":
        return DEFAULT_MEMORY_POLL_SEC
    try:
        f = float(value)
    except (TypeError, ValueError):
        raise ValueError(
            f"memory poll interval must be a non-negative float, got "
            f"{value!r}")
    if f < 0.0:
        raise ValueError(
            f"memory poll interval must be a non-negative float, got {f}")
    return f


def device_memory_stats(device: Any = None) -> Dict[str, int]:
    """Best-effort ``device.memory_stats()`` as a plain dict of numeric
    fields. Returns ``{}`` whenever telemetry is unavailable — CPU and
    interpret backends return None, some plugins raise — so callers
    can treat falsy as "no live view" without try/except."""
    try:
        if device is None:
            import jax

            devs = jax.local_devices()
            if not devs:
                return {}
            device = devs[0]
        stats = device.memory_stats()
        if not stats:
            return {}
        return {k: int(v) for k, v in stats.items()
                if isinstance(v, (int, float)) and not isinstance(v, bool)}
    except Exception:
        return {}


def tree_nbytes(tree: Any) -> int:
    """Packed storage bytes of an array pytree, with the allocators'
    byte conventions: jnp.int4 counts two codes per byte (QTensor
    flattens into its raw component planes, so this reproduces
    ``QTensor.nbytes`` exactly); everything else size * itemsize.
    Non-array leaves (python scalars, None) count zero."""
    import jax
    import jax.numpy as jnp

    int4 = jnp.dtype(jnp.int4)

    def leaf_bytes(a: Any) -> int:
        dt = getattr(a, "dtype", None)
        size = getattr(a, "size", None)
        if dt is None or size is None:
            return 0
        if jnp.dtype(dt) == int4:
            return -(-int(size) // 2)
        return int(size) * jnp.dtype(dt).itemsize

    return sum(leaf_bytes(a) for a in jax.tree_util.tree_leaves(tree))


class MemoryLedger:
    """Static allocation ledger + throttled live device telemetry.

    ``stats_provider`` is any zero-arg callable returning a
    ``memory_stats()``-shaped dict (or ``{}``/None for "no telemetry");
    the default polls the first local jax device. Tests inject a fake
    provider for deterministic headroom behaviour. All methods are
    thread-safe and never raise out of telemetry paths.
    """

    def __init__(self, stats_provider: Optional[Callable[[], dict]] = None,
                 budget_fraction: Optional[float] = None,
                 poll_sec: Optional[float] = None):
        self._lock = threading.Lock()
        self._static: Dict[str, Dict[str, Dict[str, Any]]] = {}
        self._stats_provider = stats_provider or device_memory_stats
        try:
            self.budget_fraction = resolve_hbm_budget_fraction(
                budget_fraction)
        except ValueError:
            logger.warning(
                "invalid %s=%r; using default %g", HBM_BUDGET_FRACTION_ENV,
                os.environ.get(HBM_BUDGET_FRACTION_ENV),
                DEFAULT_HBM_BUDGET_FRACTION)
            self.budget_fraction = DEFAULT_HBM_BUDGET_FRACTION
        try:
            self.poll_sec = resolve_memory_poll_sec(poll_sec)
        except ValueError:
            logger.warning(
                "invalid %s=%r; using default %g", MEMORY_POLL_SEC_ENV,
                os.environ.get(MEMORY_POLL_SEC_ENV),
                DEFAULT_MEMORY_POLL_SEC)
            self.poll_sec = DEFAULT_MEMORY_POLL_SEC
        self._last_poll = 0.0
        self._last_stats: Dict[str, int] = {}

    # -- static accounting ---------------------------------------------------

    def register(self, kind: str, name: str, nbytes: int,
                 **meta: Any) -> int:
        """Record (or replace) one named allocation under ``kind``.
        ``meta`` (dtype, shape, components, ...) rides along into
        ``static_report()``. Returns ``nbytes`` for chaining."""
        entry = {"bytes": int(nbytes)}
        entry.update(meta)
        with self._lock:
            self._static.setdefault(kind, {})[name] = entry
        return int(nbytes)

    def unregister(self, kind: str, name: str) -> None:
        with self._lock:
            self._static.get(kind, {}).pop(name, None)

    def static_report(self) -> dict:
        """JSON-ready static view: every registered entry, per-kind
        subtotals, and the grand total."""
        with self._lock:
            entries = {kind: {name: dict(ent)
                              for name, ent in sorted(named.items())}
                       for kind, named in sorted(self._static.items())}
        by_kind = {kind: sum(e["bytes"] for e in named.values())
                   for kind, named in entries.items()}
        return {"entries": entries, "by_kind": by_kind,
                "total_bytes": sum(by_kind.values())}

    def static_bytes(self, kind: Optional[str] = None) -> int:
        """Total registered bytes, optionally restricted to one kind."""
        with self._lock:
            if kind is not None:
                return sum(e["bytes"]
                           for e in self._static.get(kind, {}).values())
            return sum(e["bytes"] for named in self._static.values()
                       for e in named.values())

    # -- live telemetry ------------------------------------------------------

    def device_stats(self, refresh: bool = False) -> Dict[str, int]:
        """Most recent device stats dict (``{}`` when the backend has
        none). Polls the provider at most once per ``poll_sec`` unless
        ``refresh=True`` forces it. Never raises."""
        now = time.monotonic()
        with self._lock:
            fresh = (now - self._last_poll) < self.poll_sec \
                and self._last_poll > 0.0
            if fresh and not refresh:
                return dict(self._last_stats)
        try:
            stats = self._stats_provider() or {}
        except Exception:
            stats = {}
        stats = {k: int(v) for k, v in stats.items()
                 if isinstance(v, (int, float)) and not isinstance(v, bool)}
        with self._lock:
            self._last_poll = now
            self._last_stats = stats
            return dict(stats)

    def headroom(self, refresh: bool = False) -> dict:
        """Budget math from the live view: ``{}`` without telemetry,
        else bytes_limit/bytes_in_use/budget_bytes/headroom_bytes
        (budget minus in-use; negative = overdraft) + the fraction."""
        stats = self.device_stats(refresh=refresh)
        limit = stats.get("bytes_limit")
        in_use = stats.get("bytes_in_use")
        if not limit or in_use is None:
            return {}
        budget = int(limit * self.budget_fraction)
        return {
            "bytes_limit": int(limit),
            "bytes_in_use": int(in_use),
            "peak_bytes_in_use": int(stats.get("peak_bytes_in_use",
                                               in_use)),
            "budget_fraction": self.budget_fraction,
            "budget_bytes": budget,
            "headroom_bytes": budget - int(in_use),
        }

    def would_fit(self, nbytes: int,
                  refresh: bool = False) -> Optional[bool]:
        """Whether an extra allocation of ``nbytes`` stays within the
        budget. ``None`` means "no telemetry" — the caller decides
        (admission control admits, matching the CPU/interpret no-op
        contract)."""
        hr = self.headroom(refresh=refresh)
        if not hr:
            return None
        return int(nbytes) <= hr["headroom_bytes"]

    # -- export --------------------------------------------------------------

    def snapshot(self, refresh: bool = False) -> dict:
        """The one-call JSON view served by ``GET /v1/memory`` and
        embedded in postmortems/bench records: static report + live
        stats + budget math."""
        return {
            "static": self.static_report(),
            "device": self.device_stats(refresh=refresh),
            "headroom": self.headroom(),
        }

    def publish(self, registry: Any = None) -> None:
        """Set the HBM gauges on ``registry`` (default process
        registry). Best-effort: metric export never gates the caller."""
        try:
            if registry is None:
                from bigdl_tpu.observability.metrics import default_registry

                registry = default_registry()
            g = registry.gauge(
                "bigdl_tpu_hbm_bytes",
                "HBM bytes by kind: statically registered allocations "
                "(weights, kv_cache, ...) plus live device_in_use / "
                "device_peak / device_limit when the backend reports "
                "memory_stats().", labelnames=("kind",))
            report = self.static_report()
            for kind, total in report["by_kind"].items():
                g.labels(kind).set(float(total))
            stats = self.device_stats()
            for key, label in (("bytes_in_use", "device_in_use"),
                               ("peak_bytes_in_use", "device_peak"),
                               ("bytes_limit", "device_limit")):
                if key in stats:
                    g.labels(label).set(float(stats[key]))
            hr = self.headroom()
            if hr:
                registry.gauge(
                    "bigdl_tpu_hbm_headroom_bytes",
                    "HBM budget (budget_fraction * bytes_limit) minus "
                    "bytes_in_use; negative means overdraft.").set(
                        float(hr["headroom_bytes"]))
        except Exception:
            pass


_default_ledger: Optional[MemoryLedger] = None
_default_lock = threading.Lock()


def default_ledger() -> MemoryLedger:
    """The process-wide ledger (bench tooling, generation, postmortem
    fallbacks). The serving engine keeps its own when handed one."""
    global _default_ledger
    with _default_lock:
        if _default_ledger is None:
            _default_ledger = MemoryLedger()
        return _default_ledger


def reset_default_ledger() -> None:
    """Drop the process-wide ledger (tests)."""
    global _default_ledger
    with _default_lock:
        _default_ledger = None


def memory_report(ledger: Optional[MemoryLedger] = None) -> dict:
    """The bench-embeddable memory report: a ledger snapshot plus flat
    headline scalars tools/bench_diff.py can compare across runs —
    ``hbm_static_total_bytes`` (registered allocations),
    ``hbm_device_peak_bytes`` (live peak, absent on CPU), and
    ``jit_peak_temp_bytes`` (largest per-executable scratch from the
    compile table's memory analysis)."""
    led = ledger if ledger is not None else default_ledger()
    out = led.snapshot()
    out["hbm_static_total_bytes"] = out["static"]["total_bytes"]
    dev = out.get("device", {})
    if "peak_bytes_in_use" in dev:
        out["hbm_device_peak_bytes"] = dev["peak_bytes_in_use"]
    try:
        from bigdl_tpu.observability.compile_watch import compile_table

        out["jit_peak_temp_bytes"] = max(
            (ent.get("peak_temp_bytes", 0)
             for ent in compile_table().values()), default=0)
    except Exception:
        pass
    return out
