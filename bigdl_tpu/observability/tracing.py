"""Per-request lifecycle tracing for the serving path.

Each request the engine touches gets a ``RequestSpan`` recording the
timestamps the serving metrics are computed from:

    t_enqueued  -> t_admitted          queue wait
                   (bigdl_tpu_request_phase_seconds{phase="queue"})
    t_admitted  -> t_first_token       prefill latency ({phase="prefill"})
    t_arrival   -> t_first_token       TTFT (bigdl_tpu_ttft_seconds)
    t_first_token -> t_finished        decode phase ({phase="decode"})
    decode phase / tokens              TPOT (engine observes per step
                                       into bigdl_tpu_tpot_seconds)

plus discrete events (``preempt``, ``resume``, ``finish``) with their
own timestamps. Spans live in the tracer's in-memory ring buffer
(``GET /v1/stats`` serves them) and, when an event-log path is
configured — explicitly or via ``BIGDL_TPU_EVENT_LOG`` — every event is
appended to a JSONL file for offline analysis.

Stdlib-only by design (see observability/metrics.py).
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple


def resolve_event_log_max_bytes(value=None):
    """Size bound for the JSONL sink: explicit value, else
    ``$BIGDL_TPU_EVENT_LOG_MAX_BYTES``, else None (unbounded). Raises
    ValueError on a non-positive or non-integer setting
    (utils/env_check.py surfaces this for the env var)."""
    if value is None:
        value = os.environ.get("BIGDL_TPU_EVENT_LOG_MAX_BYTES")
    if value is None or value == "":
        return None
    try:
        n = int(value)
    except (TypeError, ValueError):
        raise ValueError(
            f"event log size limit must be a positive integer, got "
            f"{value!r}")
    if n <= 0:
        raise ValueError(
            f"event log size limit must be a positive integer, got {n}")
    return n


def resolve_event_log_keep(value=None) -> int:
    """How many rotated event-log files to keep: explicit value, else
    ``$BIGDL_TPU_EVENT_LOG_KEEP``, else 1 (the pre-existing single
    ``.1`` rollover). Raises ValueError on a non-positive or
    non-integer setting (utils/env_check.py surfaces this for the env
    var; the tracer itself degrades to the default)."""
    if value is None:
        value = os.environ.get("BIGDL_TPU_EVENT_LOG_KEEP")
    if value is None or value == "":
        return 1
    try:
        n = int(value)
    except (TypeError, ValueError):
        raise ValueError(
            f"event log keep count must be a positive integer, got "
            f"{value!r}")
    if n <= 0:
        raise ValueError(
            f"event log keep count must be a positive integer, got {n}")
    return n


def rotate_event_log(path: str, keep: int) -> None:
    """Cascade ``path.{keep-1}`` -> ``path.{keep}``, ...,
    ``path`` -> ``path.1``. With ``keep`` files retained plus the live
    one, total disk footprint stays bounded at ~``(keep + 1)`` x the
    rotation limit. Missing intermediates are skipped (a fresh deploy
    with keep=5 has no ``.3`` yet)."""
    for i in range(keep - 1, 0, -1):
        src = f"{path}.{i}"
        if os.path.exists(src):
            os.replace(src, f"{path}.{i + 1}")
    os.replace(path, path + ".1")


def validate_event_log_path(path: str) -> dict:
    """Report whether `path` is usable as a JSONL event-log sink
    (utils/env_check.py surfaces this for BIGDL_TPU_EVENT_LOG)."""
    out = {"path": path}
    d = os.path.dirname(os.path.abspath(path)) or "."
    if not os.path.isdir(d):
        out["writable"] = False
        out["error"] = f"directory {d!r} does not exist"
    elif os.path.exists(path) and not os.access(path, os.W_OK):
        out["writable"] = False
        out["error"] = f"{path!r} exists and is not writable"
    elif not os.path.exists(path) and not os.access(d, os.W_OK):
        out["writable"] = False
        out["error"] = f"directory {d!r} is not writable"
    else:
        out["writable"] = True
    return out


@dataclasses.dataclass
class RequestSpan:
    """Lifecycle timestamps for one engine-level request (n/best_of
    fan-out children are separate sequences and get separate spans)."""
    request_id: str
    prompt_len: int = 0
    t_arrival: float = 0.0
    t_enqueued: float = 0.0          # re-set on preemption (re-queue)
    t_admitted: Optional[float] = None
    t_first_token: Optional[float] = None
    t_finished: Optional[float] = None
    finish_reason: Optional[str] = None
    n_generated: int = 0
    n_preemptions: int = 0
    events: List[Tuple[float, str]] = dataclasses.field(
        default_factory=list)
    # distributed-trace context (observability/disttrace.py): the fleet
    # trace id, the upstream parent span id, and this request's own
    # engine-side span id — None for untraced/unsampled requests
    trace_id: Optional[str] = None
    trace_parent: Optional[str] = None
    trace_span: Optional[str] = None

    # -- derived durations (None until the span reaches that point) --------

    @property
    def queue_wait_s(self) -> Optional[float]:
        if self.t_admitted is None:
            return None
        return self.t_admitted - self.t_enqueued

    @property
    def prefill_s(self) -> Optional[float]:
        if self.t_admitted is None or self.t_first_token is None:
            return None
        return self.t_first_token - self.t_admitted

    @property
    def ttft_s(self) -> Optional[float]:
        if self.t_first_token is None:
            return None
        return self.t_first_token - self.t_arrival

    @property
    def decode_s(self) -> Optional[float]:
        if self.t_first_token is None or self.t_finished is None:
            return None
        return self.t_finished - self.t_first_token

    @property
    def tpot_s(self) -> Optional[float]:
        d = self.decode_s
        if d is None or self.n_generated <= 1:
            return None
        return d / (self.n_generated - 1)

    def to_dict(self) -> dict:
        out = {
            "request_id": self.request_id,
            "prompt_len": self.prompt_len,
            "t_arrival": self.t_arrival,
            "n_generated": self.n_generated,
            "n_preemptions": self.n_preemptions,
            "finish_reason": self.finish_reason,
        }
        if self.trace_id is not None:
            out["trace_id"] = self.trace_id
        for k in ("queue_wait_s", "prefill_s", "ttft_s", "decode_s",
                  "tpot_s"):
            v = getattr(self, k)
            if v is not None:
                out[k] = round(v, 6)
        out["events"] = [(round(t, 6), kind) for t, kind in self.events]
        return out


class RequestTracer:
    """Thread-safe span store: active spans by request id plus a ring
    buffer of finished spans; optional JSONL event sink."""

    def __init__(self, capacity: int = 256,
                 event_log_path: Optional[str] = None,
                 event_log_max_bytes: Optional[int] = None,
                 event_log_keep: Optional[int] = None):
        if event_log_path is None:
            event_log_path = os.environ.get("BIGDL_TPU_EVENT_LOG")
        self._lock = threading.Lock()
        self._active: Dict[str, RequestSpan] = {}
        self._finished: "collections.deque[RequestSpan]" = \
            collections.deque(maxlen=capacity)
        self._sink_path = event_log_path or None
        self._sink = None
        self._sink_dead = False
        # size-bounded rotation: when the sink would grow past the
        # limit the rotated files cascade (`.1` -> `.2` -> ... up to
        # $BIGDL_TPU_EVENT_LOG_KEEP files) and a fresh file is started
        # — total disk footprint is bounded at ~(keep + 1)x the limit
        if event_log_max_bytes is None:
            try:
                event_log_max_bytes = resolve_event_log_max_bytes()
            except ValueError:
                # env_check reports the bad value; the tracer itself
                # degrades to an unbounded sink rather than dying
                event_log_max_bytes = None
        if event_log_keep is None:
            try:
                event_log_keep = resolve_event_log_keep()
            except ValueError:
                event_log_keep = 1     # env_check reports the bad value
        self._sink_max_bytes = event_log_max_bytes
        self._sink_keep = event_log_keep
        self._sink_bytes = 0

    # -- JSONL sink ---------------------------------------------------------

    def _log(self, request_id: str, event: str, **data) -> None:
        if self._sink_path is None or self._sink_dead:
            return
        line = {"ts": round(time.time(), 6), "request_id": request_id,
                "event": event}
        line.update(data)
        try:
            # handler threads and the engine thread both log: the
            # open/rotate/write sequence must be atomic or a rotation
            # can race a write into a closed file
            with self._lock:
                if self._sink is None:
                    self._sink = open(self._sink_path, "a", buffering=1)
                    try:
                        self._sink_bytes = os.path.getsize(
                            self._sink_path)
                    except OSError:
                        self._sink_bytes = 0
                payload = json.dumps(line) + "\n"
                if (self._sink_max_bytes is not None and self._sink_bytes
                        and self._sink_bytes + len(payload)
                        > self._sink_max_bytes):
                    self._sink.close()
                    rotate_event_log(self._sink_path, self._sink_keep)
                    self._sink = open(self._sink_path, "a", buffering=1)
                    self._sink_bytes = 0
                self._sink.write(payload)
                self._sink_bytes += len(payload)
        except OSError as e:
            # one warning, then the sink stays off — tracing must never
            # take the serving loop down
            self._sink_dead = True
            import logging

            logging.getLogger(__name__).warning(
                "event log %s unwritable (%s); JSONL tracing disabled",
                self._sink_path, e)

    def close(self) -> None:
        with self._lock:
            if self._sink is not None:
                try:
                    self._sink.close()
                except OSError:
                    pass
                self._sink = None

    # -- lifecycle ----------------------------------------------------------

    def start(self, request_id: str, prompt_len: int = 0,
              t_arrival: Optional[float] = None,
              trace: Optional[Tuple[str, str, str]] = None) -> RequestSpan:
        """``trace`` is the distributed-trace context
        ``(trace_id, parent_span_id, own_span_id)`` threaded in by the
        engine for requests that arrived with a ``traceparent``."""
        now = time.time()
        span = RequestSpan(request_id, prompt_len,
                           t_arrival=t_arrival or now,
                           t_enqueued=t_arrival or now)
        if trace is not None:
            span.trace_id, span.trace_parent, span.trace_span = trace
        span.events.append((span.t_arrival, "enqueue"))
        with self._lock:
            self._active[request_id] = span
        self._log(request_id, "enqueue", prompt_len=prompt_len,
                  **self._trace_fields(span))
        return span

    @staticmethod
    def _trace_fields(span: Optional["RequestSpan"]) -> dict:
        if span is None or span.trace_id is None:
            return {}
        return {"trace_id": span.trace_id}

    def get(self, request_id: str) -> Optional[RequestSpan]:
        with self._lock:
            return self._active.get(request_id)

    def admitted(self, request_id: str) -> Optional[RequestSpan]:
        now = time.time()
        span = self.get(request_id)
        if span is not None:
            span.t_admitted = now
            span.events.append((now, "admit"))
            self._log(request_id, "admit",
                      queue_wait_s=round(now - span.t_enqueued, 6),
                      **self._trace_fields(span))
        return span

    def first_token(self, request_id: str) -> Optional[RequestSpan]:
        now = time.time()
        span = self.get(request_id)
        if span is not None and span.t_first_token is None:
            span.t_first_token = now
            span.events.append((now, "first_token"))
            self._log(request_id, "first_token",
                      ttft_s=round(now - span.t_arrival, 6),
                      **self._trace_fields(span))
        return span

    def preempted(self, request_id: str) -> Optional[RequestSpan]:
        """Victim evicted back to the queue: the next admit's queue wait
        counts from NOW, not from arrival."""
        now = time.time()
        span = self.get(request_id)
        if span is not None:
            span.n_preemptions += 1
            span.t_enqueued = now
            span.t_admitted = None
            span.events.append((now, "preempt"))
            self._log(request_id, "preempt",
                      **self._trace_fields(span))
        return span

    def finish(self, request_id: str, reason: str,
               n_generated: int = 0) -> Optional[RequestSpan]:
        now = time.time()
        with self._lock:
            span = self._active.pop(request_id, None)
        if span is not None:
            span.t_finished = now
            span.finish_reason = reason
            span.n_generated = n_generated
            span.events.append((now, "finish"))
            with self._lock:
                self._finished.append(span)
            self._log(request_id, "finish", reason=reason,
                      n_generated=n_generated,
                      **self._trace_fields(span))
        return span

    # -- introspection ------------------------------------------------------

    def snapshot(self, recent: int = 32) -> dict:
        with self._lock:
            active = [s.to_dict() for s in self._active.values()]
            done = [s.to_dict() for s in
                    list(self._finished)[-max(recent, 0):]]
        return {"active": active, "recent": done}
