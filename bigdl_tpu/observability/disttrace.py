"""Fleet-wide distributed tracing: cross-process span propagation.

PR 1's ``RequestTracer`` is strictly in-process; since the fleet grew a
router, replica subprocesses, KV handoff, and an autoscaler, no single
tool could answer "where did request X spend its 400 ms" once a request
crossed the router. This module closes that gap with three pieces:

- **Trace context** — a W3C-style ``traceparent`` header
  (``00-<32 hex trace-id>-<16 hex span-id>-<2 hex flags>``) generated
  at the router (or accepted from the client) and propagated on every
  fleet-internal hop: router -> replica ``api_server`` -> engine ->
  ``/v1/internal/kv_handoff`` decode target. ``parse_traceparent`` is
  strict — a malformed header is *ignored* (a fresh trace starts), it
  never faults a request.
- **SpanRecorder** — a thread-safe per-process store of *completed*
  spans (name, service, trace/span/parent ids, wall-clock start/end,
  attrs). Spans are recorded post-hoc with explicit timestamps, so the
  hot path never holds an open span object. Completed spans are also
  appended to a JSONL sink next to ``$BIGDL_TPU_EVENT_LOG``
  (``<path>.spans``) with the same size rotation + keep-N policy as
  the request tracer's event log.
- **Timeline merge** — the router's ``GET /v1/trace/{trace_id}`` fans
  out to each replica's ``GET /v1/internal/spans?trace_id=`` and calls
  ``merge_timeline`` to stitch one clock-skew-adjusted timeline (each
  replica reports its own wall clock; the router shifts spans by the
  midpoint-RTT offset). ``GET /v1/traces`` lists recent slow traces
  (top-k by duration).

Tail sampling: ``$BIGDL_TPU_TRACE_SAMPLE`` (0..1, default 1.0) decides
which traces record spans. The decision is a *deterministic* hash of
the trace id, so every process in the fleet keeps or drops the same
traces without coordination.

Stdlib-only by design (see observability/metrics.py).
"""

from __future__ import annotations

import collections
import json
import os
import re
import threading
import time
import uuid
from typing import Any, Dict, Iterable, List, Optional, Tuple

from bigdl_tpu.observability.tracing import (
    resolve_event_log_keep,
    resolve_event_log_max_bytes,
    rotate_event_log,
)

TRACE_SAMPLE_ENV = "BIGDL_TPU_TRACE_SAMPLE"

#: strict W3C traceparent shape: version "00", lowercase hex only
_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")


def resolve_trace_sample(value=None) -> float:
    """Tail-sampling fraction in [0, 1]: explicit value, else
    ``$BIGDL_TPU_TRACE_SAMPLE``, else 1.0 (record every trace). Raises
    ValueError outside [0, 1] (utils/env_check.py surfaces this for
    the env var; the recorder itself degrades to 1.0)."""
    raw = value if value is not None else os.environ.get(
        TRACE_SAMPLE_ENV, "")
    if raw is None or raw == "":
        return 1.0
    f = float(raw)                     # ValueError propagates
    if not 0.0 <= f <= 1.0:
        raise ValueError(
            f"{TRACE_SAMPLE_ENV} must be in [0, 1], got {raw!r}")
    return f


def new_trace_id() -> str:
    return uuid.uuid4().hex


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


def make_traceparent(trace_id: str, span_id: str,
                     flags: str = "01") -> str:
    return f"00-{trace_id}-{span_id}-{flags}"


def parse_traceparent(header) -> Optional[Tuple[str, str]]:
    """``(trace_id, parent_span_id)`` from a traceparent header, or
    None for anything malformed: wrong field count/width, uppercase or
    non-hex digits, the forbidden ``ff`` version, or all-zero ids. A
    rejected header means a fresh trace starts — it never errors the
    request that carried it."""
    if not isinstance(header, str):
        return None
    m = _TRACEPARENT_RE.match(header.strip())
    if m is None:
        return None
    version, trace_id, span_id, _flags = m.groups()
    if version == "ff" or trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id


def trace_sampled(trace_id: str, sample: Optional[float] = None) -> bool:
    """Deterministic tail-sampling: a pure function of the trace id, so
    the router and every replica agree on which traces record without
    coordination."""
    if sample is None:
        try:
            sample = resolve_trace_sample()
        except ValueError:
            sample = 1.0               # env_check reports the bad value
    if sample >= 1.0:
        return True
    if sample <= 0.0:
        return False
    return int(trace_id[:8], 16) / float(0xFFFFFFFF) < sample


class SpanRecorder:
    """Thread-safe store of completed spans, grouped by trace id.

    ``record`` takes explicit wall-clock start/end timestamps — spans
    are closed facts, not live objects, so the engine's step loop never
    carries open-span state across iterations. Every span mutation and
    read goes through ``_lock`` (handler threads query while the engine
    thread records)."""

    def __init__(self, service: str = "process", capacity: int = 1024,
                 sink_path: Optional[str] = None,
                 sink_max_bytes: Optional[int] = None,
                 sink_keep: Optional[int] = None,
                 sample: Optional[float] = None):
        self.service = service
        if sink_path is None:
            base = os.environ.get("BIGDL_TPU_EVENT_LOG")
            sink_path = (base + ".spans") if base else None
        if sink_max_bytes is None:
            try:
                sink_max_bytes = resolve_event_log_max_bytes()
            except ValueError:
                sink_max_bytes = None  # env_check reports it
        if sink_keep is None:
            try:
                sink_keep = resolve_event_log_keep()
            except ValueError:
                sink_keep = 1          # env_check reports it
        if sample is None:
            try:
                sample = resolve_trace_sample()
            except ValueError:
                sample = 1.0           # env_check reports it
        self.sample = sample
        self._lock = threading.Lock()
        self._spans: "collections.deque[dict]" = collections.deque(
            maxlen=capacity)
        # trace id -> its spans, insertion-ordered so eviction drops the
        # oldest trace and annotate_recent sees the newest
        self._by_trace: "collections.OrderedDict[str, List[dict]]" = \
            collections.OrderedDict()
        self._trace_cap = max(16, capacity // 8)
        self._sink_path = sink_path or None
        self._sink = None
        self._sink_dead = False
        self._sink_max_bytes = sink_max_bytes
        self._sink_keep = sink_keep
        self._sink_bytes = 0

    # -- recording ----------------------------------------------------------

    def record(self, name: str, trace_id: Optional[str],
               span_id: Optional[str] = None,
               parent_id: Optional[str] = None,
               t_start: Optional[float] = None,
               t_end: Optional[float] = None,
               **attrs) -> Optional[dict]:
        """Record one completed span; returns its dict, or None when the
        trace is absent or tail-sampled out."""
        if not trace_id or not trace_sampled(trace_id, self.sample):
            return None
        now = time.time()
        t0 = now if t_start is None else t_start
        t1 = now if t_end is None else t_end
        span = {
            "name": name,
            "service": self.service,
            "trace_id": trace_id,
            "span_id": span_id or new_span_id(),
            "parent_id": parent_id or None,
            "t_start": round(t0, 6),
            "t_end": round(t1, 6),
            "duration_s": round(max(t1 - t0, 0.0), 6),
        }
        if attrs:
            span["attrs"] = attrs
        with self._lock:
            self._spans.append(span)
            group = self._by_trace.get(trace_id)
            if group is None:
                group = self._by_trace[trace_id] = []
                while len(self._by_trace) > self._trace_cap:
                    self._by_trace.popitem(last=False)
            else:
                self._by_trace.move_to_end(trace_id)
            group.append(span)
            self._sink_write(span)
        return span

    def annotate(self, trace_id: Optional[str], name: str,
                 parent_id: Optional[str] = None,
                 **attrs) -> Optional[dict]:
        """Zero-duration event span: how fleet decisions (failover,
        shed, brownout, autoscale) pin themselves to a timeline."""
        return self.record(name, trace_id, parent_id=parent_id,
                           event=True, **attrs)

    def annotate_recent(self, name: str, limit: int = 8,
                        **attrs) -> int:
        """Attach a zero-duration event to the ``limit`` most recent
        traces — fleet-scoped decisions (brownout level change,
        autoscale action) land on the timeline of every request that
        was in flight around them."""
        with self._lock:
            tids = list(self._by_trace)[-max(limit, 0):]
        n = 0
        for tid in tids:
            if self.annotate(tid, name, **attrs) is not None:
                n += 1
        return n

    # -- JSONL sink (same format + rotation policy as the request
    # tracer's $BIGDL_TPU_EVENT_LOG sink) -----------------------------------

    def _sink_write(self, span: dict) -> None:
        # caller holds _lock: open/rotate/write must be atomic against
        # concurrent recorders (engine thread + HTTP handler threads)
        if self._sink_path is None or self._sink_dead:
            return
        try:
            sink = self._sink  # graftlint: disable=lock-guarded-unlocked
            if sink is None:
                sink = open(self._sink_path, "a", buffering=1)
                try:
                    self._sink_bytes = os.path.getsize(self._sink_path)
                except OSError:
                    self._sink_bytes = 0
            payload = json.dumps(span) + "\n"
            if (self._sink_max_bytes is not None and self._sink_bytes
                    and self._sink_bytes + len(payload)
                    > self._sink_max_bytes):
                sink.close()
                rotate_event_log(self._sink_path, self._sink_keep)
                sink = open(self._sink_path, "a", buffering=1)
                self._sink_bytes = 0
            sink.write(payload)
            self._sink_bytes += len(payload)
            self._sink = sink  # graftlint: disable=lock-guarded-unlocked
        except OSError as e:
            self._sink_dead = True
            import logging

            logging.getLogger(__name__).warning(
                "span log %s unwritable (%s); span JSONL disabled",
                self._sink_path, e)

    def close(self) -> None:
        with self._lock:
            if self._sink is not None:
                try:
                    self._sink.close()
                except OSError:
                    pass
                self._sink = None

    # -- queries ------------------------------------------------------------

    def spans_for(self, trace_id: str) -> List[dict]:
        with self._lock:
            return [dict(s) for s in self._by_trace.get(trace_id, ())]

    def recent_traces(self, k: int = 16) -> List[dict]:
        """Top-k *slowest* recorded traces (wall duration across all
        spans), newest data included — the ``GET /v1/traces`` payload."""
        with self._lock:
            items = [(tid, list(spans))
                     for tid, spans in self._by_trace.items()]
        out = []
        for tid, spans in items:
            if not spans:
                continue
            t0 = min(s["t_start"] for s in spans)
            t1 = max(s["t_end"] for s in spans)
            root = next((s for s in spans if not s.get("parent_id")),
                        spans[0])
            out.append({
                "trace_id": tid,
                "t_start": t0,
                "duration_s": round(t1 - t0, 6),
                "n_spans": len(spans),
                "root": root["name"],
                "services": sorted({s["service"] for s in spans}),
            })
        out.sort(key=lambda d: -d["duration_s"])
        return out[:max(k, 0)]

    def snapshot(self) -> dict:
        with self._lock:
            return {"service": self.service,
                    "spans": len(self._spans),
                    "traces": len(self._by_trace),
                    "sample": self.sample,
                    "sink": self._sink_path,
                    "sink_dead": self._sink_dead}


def merge_timeline(trace_id: str,
                   span_groups: Iterable[Tuple[float, List[dict]]],
                   external_parents: Iterable[str] = ()) -> Dict[str, Any]:
    """Stitch span groups from several processes into one timeline.

    ``span_groups`` is ``[(skew_s, spans)]`` — each group's timestamps
    are shifted by its clock-skew estimate (the router computes
    ``skew = local_midpoint - remote_now`` per replica fan-out call).
    ``external_parents`` are span ids known to live outside the fleet
    (the client's own parent span): spans pointing at them are not
    orphans. Any other span whose parent never reported is — the
    ``bigdl_tpu_handoff_span_orphans_total`` condition, surfaced here
    as ``orphan_spans``."""
    spans: List[dict] = []
    for skew, group in span_groups:
        for s in group:
            s = dict(s)
            if skew:
                s["t_start"] = round(s["t_start"] + skew, 6)
                s["t_end"] = round(s["t_end"] + skew, 6)
                s["skew_adjust_s"] = round(skew, 6)
            spans.append(s)
    spans.sort(key=lambda s: (s.get("t_start", 0.0),
                              s.get("t_end", 0.0)))
    known = {s.get("span_id") for s in spans}
    known.update(external_parents)
    orphans = sorted(s["span_id"] for s in spans
                     if s.get("parent_id") and s["parent_id"] not in known)
    doc: Dict[str, Any] = {
        "trace_id": trace_id,
        "n_spans": len(spans),
        "services": sorted({s.get("service", "?") for s in spans}),
        "orphan_spans": orphans,
        "spans": spans,
    }
    if spans:
        t0 = min(s["t_start"] for s in spans)
        t1 = max(s["t_end"] for s in spans)
        doc["t_start"] = t0
        doc["duration_s"] = round(t1 - t0, 6)
    return doc
