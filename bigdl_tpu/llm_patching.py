"""One-line patch of Hugging Face transformers onto bigdl-tpu.

Equivalent of the reference's `llm_patch`/`llm_unpatch`
(reference llm_patching.py:48: swaps transformers Auto* classes for the
ipex-llm ones so third-party code gains low-bit loading unmodified).

    import bigdl_tpu
    bigdl_tpu.llm_patch()          # transformers.AutoModelForCausalLM is ours
    ...
    bigdl_tpu.llm_unpatch()
"""

from __future__ import annotations

_saved = {}


def llm_patch(load_in_4bit_default: bool = True) -> None:
    """Replace transformers.AutoModelForCausalLM/AutoModel with the
    bigdl-tpu facades (4-bit by default, like the reference's patch)."""
    import transformers

    from bigdl_tpu.transformers import model as _m

    if _saved:
        return

    class _PatchedCausalLM(_m.AutoModelForCausalLM):
        @classmethod
        def from_pretrained(cls, *args, **kw):
            kw.setdefault("load_in_4bit", load_in_4bit_default)
            return super().from_pretrained(*args, **kw)

    _saved["AutoModelForCausalLM"] = transformers.AutoModelForCausalLM
    _saved["AutoModel"] = transformers.AutoModel
    transformers.AutoModelForCausalLM = _PatchedCausalLM
    transformers.AutoModel = _m.AutoModel


def llm_unpatch() -> None:
    import transformers

    if not _saved:
        return
    transformers.AutoModelForCausalLM = _saved.pop("AutoModelForCausalLM")
    transformers.AutoModel = _saved.pop("AutoModel")
