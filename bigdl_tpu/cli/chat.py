"""CLI: one-shot generation and interactive chat.

Equivalent of the reference's `llm-cli` / `llm-chat` scripts (reference
cli/llm-cli:25-57 picks a per-family native binary; portable-zip/chat.py is
the interactive loop). Here one CLI drives every family through the
framework; `-x/--model-family` is accepted for command-line compatibility
but the architecture is auto-detected from the checkpoint.
"""

from __future__ import annotations

import argparse
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="bigdl-tpu-cli",
        description="Low-bit LLM generation on TPU (llm-cli equivalent)")
    ap.add_argument("-m", "--model", required=True,
                    help="HF checkpoint dir, save_low_bit dir, or .gguf")
    ap.add_argument("-x", "--model-family", default=None,
                    help="accepted for llm-cli compatibility (auto-detected)")
    ap.add_argument("-p", "--prompt", default=None,
                    help="one-shot prompt (omit for interactive chat)")
    ap.add_argument("-n", "--n-predict", type=int, default=128)
    ap.add_argument("--low-bit", default="sym_int4")
    ap.add_argument("-t", "--temperature", type=float, default=0.0)
    ap.add_argument("--top-k", type=int, default=0)
    ap.add_argument("--top-p", type=float, default=1.0)
    ap.add_argument("--max-seq", type=int, default=2048)
    ap.add_argument("--speculative", action="store_true")
    ap.add_argument("--stats", action="store_true",
                    help="print first/next token latency after each turn")
    return ap


def _load(args):
    from bigdl_tpu.transformers.model import AutoModelForCausalLM

    model = AutoModelForCausalLM.from_pretrained(
        args.model, load_in_low_bit=args.low_bit, max_seq=args.max_seq,
        speculative=args.speculative)
    tokenizer = None
    try:
        from transformers import AutoTokenizer

        tokenizer = AutoTokenizer.from_pretrained(args.model)
    except Exception:
        tok_info = getattr(model, "gguf_tokenizer_info", None)
        if tok_info:
            # reconstruct from the GGUF vocabulary already parsed at load
            # (reference gguf/api.py)
            try:
                from bigdl_tpu.gguf_tokenizer import GGUFTokenizer

                tokenizer = GGUFTokenizer.from_tokenizer_info(tok_info)
                print("using tokenizer reconstructed from GGUF vocab",
                      file=sys.stderr)
            except ValueError as e:
                print(f"gguf tokenizer unusable ({e})", file=sys.stderr)
        if tokenizer is None:
            print("warning: no tokenizer found; token-id mode",
                  file=sys.stderr)
    return model, tokenizer


def _generate(model, tokenizer, text, args, history=None):
    from bigdl_tpu.generation import GenerationStats

    if tokenizer is None:
        ids = [int(x) for x in text.split()]
    elif history is not None and hasattr(tokenizer, "apply_chat_template"):
        history.append({"role": "user", "content": text})
        ids = tokenizer.apply_chat_template(history, tokenize=True,
                                            add_generation_prompt=True)
    else:
        ids = tokenizer(text)["input_ids"]

    stats = GenerationStats()
    t0 = time.perf_counter()
    out = model.generate(
        ids, max_new_tokens=args.n_predict,
        do_sample=args.temperature > 0, temperature=args.temperature,
        top_k=args.top_k, top_p=args.top_p, stats=stats)
    wall = time.perf_counter() - t0
    new = list(out[0][len(ids):])
    text_out = (" ".join(map(str, new)) if tokenizer is None
                else tokenizer.decode(new, skip_special_tokens=True))
    if history is not None:
        history.append({"role": "assistant", "content": text_out})
    if args.stats:
        n = max(len(new) - 1, 1)
        print(f"[first {stats.first_token_s*1e3:.0f} ms | "
              f"rest {stats.rest_cost_mean*1e3:.1f} ms/tok | "
              f"{len(new)} tokens in {wall:.1f}s]", file=sys.stderr)
    return text_out


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    model, tokenizer = _load(args)

    if args.prompt is not None:
        print(_generate(model, tokenizer, args.prompt, args))
        return 0

    print("interactive chat — empty line or /exit to quit")
    history = []
    while True:
        try:
            line = input("user> ").strip()
        except (EOFError, KeyboardInterrupt):
            break
        if not line or line == "/exit":
            break
        if line == "/clear":
            history = []
            print("(history cleared)")
            continue
        print("assistant>", _generate(model, tokenizer, line, args,
                                      history=history))
    return 0


if __name__ == "__main__":
    sys.exit(main())
