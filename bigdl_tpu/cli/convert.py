"""CLI: one-shot checkpoint converter.

Equivalent of the reference's `llm_convert` CLI (reference
convert_model.py:31-144: pth/HF -> ggml int4/int8 .bin, gptq -> ggml).
Here: HF dir or .gguf -> quantized save_low_bit directory, or -> GGUF
export (q4_0/q4_1/q5_0/q5_1/q8_0) for llama.cpp interop.
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="llm-convert-tpu",
        description="Convert a model to low-bit (llm_convert equivalent)")
    ap.add_argument("model", help="HF checkpoint dir or .gguf file")
    ap.add_argument("-o", "--outfile", required=True,
                    help="output directory (or .gguf path with -f gguf)")
    ap.add_argument("-t", "--outtype", default="sym_int4",
                    help="qtype: sym_int4/asym_int4/nf4/fp8_e4m3/... ")
    ap.add_argument("-f", "--format", default="lowbit",
                    choices=["lowbit", "gguf"])
    ap.add_argument("--imatrix", default=None,
                    help="llama.cpp-format importance matrix file for "
                         "weighted quantization (ultra-low-bit qtypes)")
    args = ap.parse_args(argv)

    from bigdl_tpu.transformers.model import AutoModelForCausalLM

    model = AutoModelForCausalLM.from_pretrained(
        args.model, load_in_low_bit=args.outtype, imatrix=args.imatrix)

    if args.format == "lowbit":
        model.save_low_bit(args.outfile)
        print(f"saved low-bit checkpoint to {args.outfile}")
        return 0

    # GGUF export: dequantize leaves back to f32, re-encode as ggml blocks
    import numpy as np

    from bigdl_tpu import gguf as G
    from bigdl_tpu.ops.quant import QTensor, dequantize

    cfg = model.config
    # outtype was validated by from_pretrained above; qtypes without a
    # matching ggml block format (nf4, fp4, iq*, ...) re-encode at the
    # nearest width: 8-bit kinds as q8_0, everything else as q4_0
    exact = {
        "fp32": G.GGML_F32, "f32": G.GGML_F32,
        "fp16": G.GGML_F16, "f16": G.GGML_F16,
        "bf16": G.GGML_BF16,
        "sym_int4": G.GGML_Q4_0, "int4": G.GGML_Q4_0, "q4_0": G.GGML_Q4_0,
        "sym_int8": G.GGML_Q8_0, "int8": G.GGML_Q8_0, "q8_0": G.GGML_Q8_0,
        "fp8": G.GGML_Q8_0, "fp8_e4m3": G.GGML_Q8_0,
        "fp8_e5m2": G.GGML_Q8_0,
        "asym_int4": G.GGML_Q4_1, "q4_1": G.GGML_Q4_1,
        "sym_int5": G.GGML_Q5_0, "q5_0": G.GGML_Q5_0,
        "asym_int5": G.GGML_Q5_1, "q5_1": G.GGML_Q5_1,
    }
    gt = exact.get(args.outtype)
    if gt is None:
        gt = G.GGML_Q4_0
        print(f"warning: qtype '{args.outtype}' has no matching ggml "
              "block format; the GGUF will be re-encoded as q4_0 "
              "(different size and quantization than the in-memory "
              "model)", file=sys.stderr)

    def dense_oi(leaf, idx=None):
        """Leaf -> dense HF-orientation [out, in] f32."""
        if isinstance(leaf, QTensor):
            if idx is not None:
                import jax

                leaf = jax.tree.map(lambda x: x[idx], leaf)
            return np.asarray(dequantize(leaf), np.float32).T
        arr = np.asarray(leaf, np.float32)
        if idx is not None:
            arr = arr[idx]
        return arr.T

    from bigdl_tpu.models.llama import unmerge_projections

    # from_pretrained merges qkv/gate-up by default; GGUF tensor names
    # are per-projection, so restore the split layout (exact slicing)
    p = unmerge_projections(model.params, cfg)
    tensors = {"token_embd.weight":
               (np.asarray(p["embed_tokens"], np.float32), G.GGML_F16),
               "output_norm.weight":
               (np.asarray(p["norm"], np.float32), G.GGML_F32)}
    if "lm_head" in p:
        tensors["output.weight"] = (dense_oi(p["lm_head"]), gt)
    name_map = {"q_proj": "attn_q", "k_proj": "attn_k", "v_proj": "attn_v",
                "o_proj": "attn_output", "gate_proj": "ffn_gate",
                "up_proj": "ffn_up", "down_proj": "ffn_down"}
    for i in range(cfg.num_hidden_layers):
        for ours, theirs in name_map.items():
            if ours in p["layers"]:
                tensors[f"blk.{i}.{theirs}.weight"] = (
                    dense_oi(p["layers"][ours], i), gt)
        tensors[f"blk.{i}.attn_norm.weight"] = (
            np.asarray(p["layers"]["input_layernorm"][i], np.float32),
            G.GGML_F32)
        tensors[f"blk.{i}.ffn_norm.weight"] = (
            np.asarray(p["layers"]["post_attention_layernorm"][i],
                       np.float32), G.GGML_F32)

    kv = {
        "general.architecture": "llama",
        "llama.block_count": cfg.num_hidden_layers,
        "llama.embedding_length": cfg.hidden_size,
        "llama.feed_forward_length": cfg.intermediate_size,
        "llama.attention.head_count": cfg.num_attention_heads,
        "llama.attention.head_count_kv": cfg.num_key_value_heads,
        "llama.attention.layer_norm_rms_epsilon": cfg.rms_norm_eps,
        "llama.rope.freq_base": cfg.rope_theta,
        "llama.context_length": cfg.max_position_embeddings,
    }
    G.write_gguf(args.outfile, kv, tensors)
    print(f"wrote GGUF to {args.outfile}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
