"""Token generation: jit prefill + jit decode step, sampling on device.

The reference rides HF `GenerationMixin.generate` (patched at
transformers/speculative.py:42-103); here generation is a first-class loop
built for XLA: one compiled prefill executable per prompt-length bucket and
ONE compiled decode executable reused for every token (static shapes, cache
carried as donated state). Sampling (temperature / top-k / top-p, greedy)
runs on device; only the emitted token returns to host each step.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.models import llama as llama_mod
from bigdl_tpu.observability.compile_watch import tracked_jit
from bigdl_tpu.ops.kvcache import KVCache


@dataclasses.dataclass
class GenerationConfig:
    max_new_tokens: int = 32
    temperature: float = 1.0
    top_k: int = 0            # 0 = disabled
    top_p: float = 1.0        # 1.0 = disabled
    do_sample: bool = False
    eos_token_id: Optional[int] = None
    seed: int = 0
    # llama.cpp-style repetition penalty (reference native sampler,
    # ggml/model/llama/llama.py:566-620): logits of already-seen tokens
    # divide (if >0) / multiply (if <0) by this. 1.0 = off.
    repetition_penalty: float = 1.0
    # OpenAI-style count penalties (reference vllm/sampling_params.py):
    # logit -= count * frequency_penalty + (count > 0) * presence_penalty
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    # raise FloatingPointError on NaN/Inf logits instead of silently
    # sampling garbage (off by default: it forces a per-step host check;
    # the serving engine has its own always-on batched health check)
    check_logits: bool = False

    @property
    def needs_token_counts(self) -> bool:
        return (self.repetition_penalty != 1.0
                or self.presence_penalty != 0.0
                or self.frequency_penalty != 0.0)


def token_counts(tokens: jax.Array, vocab_size: int,
                 length: Optional[jax.Array] = None) -> jax.Array:
    """Per-row token occurrence counts [B, V] int32 for tokens [B, S].

    `length` ([B] or scalar) masks right padding: positions >= length do
    not count. The counts tensor is the jit-compatible stand-in for the
    reference sampler's `last_n_tokens` python list scan
    (ggml/model/llama/llama.py:566-620) — static shape, scatter-add
    updates, lives in the decode carry.
    """
    b, s = tokens.shape
    if length is None:
        add = jnp.ones((b, s), jnp.int32)
    else:
        idx = jnp.arange(s, dtype=jnp.int32)
        add = (idx[None, :] < jnp.broadcast_to(
            jnp.asarray(length, jnp.int32).reshape(-1, 1),
            (b, 1))).astype(jnp.int32)
    rows = jnp.broadcast_to(jnp.arange(b, dtype=jnp.int32)[:, None], (b, s))
    return jnp.zeros((b, vocab_size), jnp.int32).at[rows, tokens].add(add)


def apply_penalties(
    logits: jax.Array,            # [B, V] f32
    rep_counts: jax.Array,        # [B, V] int32: prompt + output counts
    out_counts: jax.Array,        # [B, V] int32: OUTPUT-only counts
    repetition_penalty: float = 1.0,
    presence_penalty: float = 0.0,
    frequency_penalty: float = 0.0,
) -> jax.Array:
    """Repetition (llama.cpp form, over prompt + output) + presence/
    frequency (OpenAI/vllm form, over OUTPUT tokens only — vllm applies
    count penalties to generated tokens, not the prompt), pure
    gather-free tensor ops — safe inside jit/scan."""
    if repetition_penalty != 1.0:
        seen = rep_counts > 0
        penalized = jnp.where(logits > 0, logits / repetition_penalty,
                              logits * repetition_penalty)
        logits = jnp.where(seen, penalized, logits)
    if presence_penalty != 0.0 or frequency_penalty != 0.0:
        logits = (logits
                  - out_counts.astype(logits.dtype) * frequency_penalty
                  - (out_counts > 0).astype(logits.dtype)
                  * presence_penalty)
    return logits


def filter_logits(logits: jax.Array, top_k: int = 0,
                  top_p: float = 1.0) -> jax.Array:
    """top-k / top-p filtering over the last axis (-inf outside the set)."""
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.flip(jnp.sort(logits, axis=-1), axis=-1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep smallest set with cumulative prob >= top_p (always keep top-1)
        cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return logits


def sample_token(
    logits: jax.Array,        # [B, V] f32
    key: jax.Array,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jax.Array:
    """Temperature / top-k / top-p sampling on device. Returns [B] int32."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = filter_logits(logits / temperature, top_k, top_p)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


@dataclasses.dataclass
class GenerationStats:
    """BenchmarkWrapper-compatible timing (reference
    dev/benchmark/benchmark_util.py:2447-2476: first_cost / rest_cost_mean)."""
    first_token_s: float = 0.0
    rest_token_s: List[float] = dataclasses.field(default_factory=list)

    @property
    def rest_cost_mean(self) -> float:
        return float(np.mean(self.rest_token_s)) if self.rest_token_s else 0.0


def generate_on_device(
    params: Dict[str, Any],
    cfg,
    forward_fn,
    input_ids: jax.Array,     # [B, S] int32 (right-padded ok if pos handled)
    cache: KVCache,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    eos_token_id: Optional[int] = None,
    seed: int = 0,
    repetition_penalty: float = 1.0,
    presence_penalty: float = 0.0,
    frequency_penalty: float = 0.0,
) -> Tuple[jax.Array, KVCache]:
    """Whole-generation-on-device loop: prefill + `lax.scan` over decode
    steps inside ONE jittable function. No host sync per token — the
    TPU-idiomatic replacement for HF's Python generate loop, and the only
    shape that hits real next-token latency on remote/tunneled devices.

    Returns (generated [B, max_new_tokens], cache). After EOS, emits
    pad (0) tokens (masked continuation keeps shapes static).
    """
    b, s = input_ids.shape
    if s + max_new_tokens > cache.max_seq:
        raise ValueError(
            f"prompt ({s}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"cache max_seq {cache.max_seq}")

    penal = (repetition_penalty != 1.0 or presence_penalty != 0.0
             or frequency_penalty != 0.0)

    logits, cache = forward_fn(params, cfg, input_ids, cache)
    last = logits[:, -1, :]
    key = jax.random.PRNGKey(seed)
    v = last.shape[-1]
    # rep counts include the prompt; out counts are generation-only
    # (vllm count-penalty semantics)
    rep0 = (token_counts(input_ids, v) if penal
            else jnp.zeros((b, 1), jnp.int32))      # dummy when off
    out0 = (jnp.zeros((b, v), jnp.int32) if penal
            else jnp.zeros((b, 1), jnp.int32))

    def pick(lg, k, rep, outc):
        if penal:
            lg = apply_penalties(lg, rep, outc, repetition_penalty,
                                 presence_penalty, frequency_penalty)
        return sample_token(lg, k, temperature=temperature, top_k=top_k,
                            top_p=top_p)

    def bump(counts, tok, done):
        if not penal:
            return counts
        rows = jnp.arange(counts.shape[0], dtype=jnp.int32)
        return counts.at[rows, tok].add((~done).astype(jnp.int32))

    key, sk = jax.random.split(key)
    tok0 = pick(last, sk, rep0, out0)
    done0 = (jnp.zeros((b,), jnp.bool_) if eos_token_id is None
             else tok0 == eos_token_id)
    never = jnp.zeros((b,), jnp.bool_)
    rep0 = bump(rep0, tok0, never)
    out0 = bump(out0, tok0, never)

    def step(carry, _):
        tok, done, cache, key, rep, outc = carry
        lg, cache = forward_fn(params, cfg, tok[:, None], cache)
        key, sk = jax.random.split(key)
        nxt = pick(lg[:, -1, :], sk, rep, outc)
        nxt = jnp.where(done, 0, nxt)
        rep = bump(rep, nxt, done)
        outc = bump(outc, nxt, done)
        if eos_token_id is not None:
            done = done | (nxt == eos_token_id)
        return (nxt, done, cache, key, rep, outc), nxt

    (_, _, cache, _, _, _), rest = lax.scan(
        step, (tok0, done0, cache, key, rep0, out0), None,
        length=max_new_tokens - 1)
    out = jnp.concatenate([tok0[:, None], rest.T], axis=1)
    return out, cache


def beam_search(
    params: Dict[str, Any],
    cfg,
    forward_fn,
    input_ids,                # [B, S] or [S] ints
    new_cache_fn,
    num_beams: int = 4,
    max_new_tokens: int = 32,
    max_seq: int = 2048,
    length_penalty: float = 1.0,
    eos_token_id: Optional[int] = None,
    prefill_fn=None,          # last-token-logits prefill variant, if any
) -> np.ndarray:
    """Greedy beam search -> best sequences [B, max_new_tokens].

    The HF-generate parity piece the reference gets for free from
    transformers (its native pipeline has no beams). Static-shape,
    TPU-first formulation: the batch expands to B*W rows sharing ONE
    compiled decode executable; each step is one jitted function that
    scores W*V continuations, selects the top W, and GATHERS the KV
    cache rows of the surviving parents (index bookkeeping — no
    reallocation). EOS beams freeze (their only continuation is pad at
    frozen score); the best beam by length-penalized score wins.
    Matches HF beam_search with early_stopping for the common cases;
    it does not keep a per-batch heap of >W finished hypotheses.
    """
    ids = np.asarray(input_ids, np.int32)
    if ids.ndim == 1:
        ids = ids[None]
    b, s = ids.shape
    w = num_beams
    if s + max_new_tokens > max_seq:
        raise ValueError("prompt + max_new_tokens exceeds max_seq")

    prefill_j, expand_j, select_j, reorder_decode_j = _beam_fns(
        cfg, forward_fn, prefill_fn, b, w, eos_token_id)

    # prefill at batch B, then REPEAT the cache rows per beam — all W
    # beams share the prompt KV, so prefilling B*W rows would waste
    # (W-1)/W of the dominant long-prompt cost; with a last-token
    # prefill_fn the [B, S, V] logits tensor is never materialized
    # either. (One executable per prompt LENGTH — warm common lengths
    # or go through Generator for bucketing.)
    cache1 = new_cache_fn(cfg, b, max_seq)
    lp_b, cache1 = prefill_j(params, jnp.asarray(ids), cache1)
    cache, gathered = _beam_expand_cache(cache1, expand_j, b, w)
    if not gathered:
        raise NotImplementedError(
            "beam search requires a cache with [.., batch, ..] leaves at "
            f"axis 1 (got {type(cache1).__name__} with none)")
    lp0 = jnp.repeat(lp_b, w, axis=0)                         # [B*W, V]

    # all beams identical after prefill: only beam 0 may seed candidates
    init_bias = jnp.full((w,), -jnp.inf).at[0].set(0.0)
    scores = jnp.tile(init_bias, (b,)).reshape(b, w)          # [B, W]
    done = jnp.zeros((b, w), jnp.bool_)
    toks = jnp.zeros((b, w, max_new_tokens), jnp.int32)
    lengths = jnp.zeros((b, w), jnp.int32)

    tok_flat, scores, done, lengths, toks, parent_flat = select_j(
        lp0, scores, done, lengths, toks, 0)
    for t in range(1, max_new_tokens):
        if bool(jnp.all(done)):
            break
        lp, cache = reorder_decode_j(params, parent_flat, cache, tok_flat)
        tok_flat, scores, done, lengths, toks, parent_flat = select_j(
            lp, scores, done, lengths, toks, t)

    final = scores / jnp.maximum(
        lengths.astype(jnp.float32), 1.0) ** length_penalty
    best = jnp.argmax(final, axis=1)                          # [B]
    out = jnp.take_along_axis(
        toks, best[:, None, None], axis=1)[:, 0]
    return np.asarray(out)


def _beam_expand_cache(cache1, expand_j, b: int, w: int):
    """Repeat batch-axis-1 cache leaves per beam. Returns (cache, n
    leaves expanded). Batch-axis CONTRACT: beam state must live on axis
    1 of >=2-D leaves (true of KVCache and every family cache built on
    it); other leaves must be beam-invariant (e.g. scalar positions,
    per-prompt anchors) — they are left untouched."""
    n_hit = 0

    def rep(x):
        nonlocal n_hit
        if getattr(x, "ndim", 0) >= 2 and x.shape[1] == b:
            n_hit += 1
            return expand_j(x)
        return x

    return jax.tree.map(rep, cache1), n_hit


@functools.lru_cache(maxsize=32)
def _beam_fns(cfg, forward_fn, prefill_fn, b: int, w: int, eos_token_id):
    """Jitted beam-search step functions, cached per geometry so repeated
    beam_search calls reuse the compiled executables (the free-function
    analog of Generator's cached prefill/decode)."""

    pre = prefill_fn or forward_fn
    prefill = tracked_jit("beam_prefill", lambda p, i, c: pre(p, cfg, i, c))

    def prefill_lp(p, i, c):
        lg, c = prefill(p, i, c)
        return jax.nn.log_softmax(
            lg[:, -1, :].astype(jnp.float32), -1), c

    expand = tracked_jit("beam_expand", lambda x: jnp.repeat(x, w, axis=1))

    @functools.partial(tracked_jit, "beam_select")
    def select(lp, scores, done, lengths, toks, t):
        """lp [B*W, V] log-probs -> (next_tok [B*W], new state)."""
        v = lp.shape[-1]
        lp = lp.reshape(b, w, v)
        # finished beams: only pad continues, at unchanged score
        pad_only = jnp.full((v,), -jnp.inf).at[0].set(0.0)
        lp = jnp.where(done[..., None], pad_only[None, None, :], lp)
        cand = scores[..., None] + lp                         # [B, W, V]
        flat = cand.reshape(b, w * v)
        top_sc, top_ix = jax.lax.top_k(flat, w)               # [B, W]
        parent = top_ix // v
        tok = (top_ix % v).astype(jnp.int32)
        # reorder per-beam state to the surviving parents
        gather = lambda x: jnp.take_along_axis(               # noqa: E731
            x, parent.reshape(b, w, *([1] * (x.ndim - 2))), axis=1)
        done_n = gather(done[..., None])[..., 0]
        lengths_n = gather(lengths[..., None])[..., 0]
        toks_n = gather(toks)
        toks_n = toks_n.at[:, :, t].set(jnp.where(done_n, 0, tok))
        lengths_n = jnp.where(done_n, lengths_n, lengths_n + 1)
        if eos_token_id is not None:
            done_n = done_n | (tok == eos_token_id)
        flat_parent = (jnp.arange(b, dtype=jnp.int32)[:, None] * w
                       + parent).reshape(-1)                  # [B*W]
        return (tok.reshape(-1), top_sc, done_n, lengths_n, toks_n,
                flat_parent)

    @functools.partial(tracked_jit, "beam_reorder_decode",
                       donate_argnums=(2,))
    def reorder_decode(params, parent_flat, cache, tok_flat):
        cache = jax.tree.map(
            lambda x: jnp.take(x, parent_flat, axis=1)
            if getattr(x, "ndim", 0) >= 2 and x.shape[1] == b * w else x,
            cache)
        lg, cache = forward_fn(params, cfg, tok_flat[:, None], cache)
        return jax.nn.log_softmax(
            lg[:, -1, :].astype(jnp.float32), -1), cache

    return prefill_lp, expand, select, reorder_decode


class Generator:
    """Compiled generate loop for a (params, config) pair.

    forward_fn(params, cfg, tokens, cache) -> (logits, cache); defaults to
    the llama forward. Prefill compiles per prompt-length bucket; decode
    compiles once. The KV cache buffer is donated between steps so XLA
    updates it in place.
    """

    def __init__(self, params: Dict[str, Any], cfg,
                 forward_fn=None, prefill_fn=None, max_seq: int = 2048,
                 kv_quantized=False, new_cache_fn=None,
                 recurrent: Optional[bool] = None,
                 kv_cache_dtype: Optional[str] = None,
                 faults=None):
        from bigdl_tpu.ops.kvcache import resolve_kv_cache_dtype
        from bigdl_tpu.robustness.faults import NULL as _no_faults

        # same fault-injection surface the serving engine exposes
        # (robustness/faults.py): chaos tests drive the offline decode
        # loop through identical step/logits hooks. Default: no-op.
        self.faults = faults if faults is not None else _no_faults
        self.params = params
        self.cfg = cfg
        self.max_seq = max_seq
        # canonical storage name; kv_quantized is the deprecated alias
        # (True -> fp8_e5m2) and also accepts a dtype name directly
        self.kv_cache_dtype = resolve_kv_cache_dtype(
            kv_cache_dtype if kv_cache_dtype is not None else kv_quantized)
        self.kv_quantized = self.kv_cache_dtype != "bf16"   # legacy mirror
        self.new_cache = new_cache_fn or llama_mod.new_cache
        self.recurrent = recurrent      # None: sniff from the cache type
        fwd = forward_fn or llama_mod.forward
        pre = prefill_fn or llama_mod.forward_last_token

        self._decode = tracked_jit(
            "generate_decode",
            lambda p, c, t, kv: fwd(p, c, t, kv), static_argnums=(1,),
            donate_argnums=(3,))
        self._prefill = tracked_jit(
            "generate_prefill",
            lambda p, c, t, kv: pre(p, c, t, kv), static_argnums=(1,),
            donate_argnums=(3,))
        # multimodal prefill (families whose prefill takes visual=):
        # built lazily so text-only models never trace it
        self._prefill_raw = pre
        self._prefill_vis = None
        self._sample = tracked_jit(
            "generate_sample", sample_token,
            static_argnames=("temperature", "top_k", "top_p"))

        def sample_pen(lg, k, rep_counts, out_counts, *, temperature,
                       top_k, top_p, rep, pres, freq):
            lg = apply_penalties(lg, rep_counts, out_counts, rep, pres,
                                 freq)
            tok = sample_token(lg, k, temperature=temperature, top_k=top_k,
                               top_p=top_p)
            rows = jnp.arange(rep_counts.shape[0], dtype=jnp.int32)
            rep_counts = rep_counts.at[rows, tok].add(1)
            out_counts = out_counts.at[rows, tok].add(1)
            return tok, rep_counts, out_counts

        self._sample_pen = tracked_jit(
            "generate_sample_pen", sample_pen,
            static_argnames=("temperature", "top_k", "top_p",
                             "rep", "pres", "freq"))

        def step_resident(p, c, tok, kv, key, finished, *, temperature,
                          top_k, top_p, eos):
            """ONE-dispatch decode step: layer-scanned forward + PRNG
            split + sampling + EOS masking fused into a single
            executable. Keeps the legacy step's exact op order (split
            THEN sample THEN mask) so greedy output is byte-identical
            and sampled output reuses the same key chain."""
            lg, kv = fwd(p, c, tok[:, None], kv)
            key, sk = jax.random.split(key)
            nxt = sample_token(lg[:, -1, :], sk, temperature=temperature,
                               top_k=top_k, top_p=top_p)
            if eos is not None:
                nxt = jnp.where(finished, 0, nxt)
                finished = finished | (nxt == eos)
            return nxt, kv, key, finished

        self._decode_resident = tracked_jit(
            "generate_decode_resident", step_resident,
            static_argnums=(1,), donate_argnums=(3,),
            static_argnames=("temperature", "top_k", "top_p", "eos"))
        self._counts = tracked_jit("generate_token_counts", token_counts,
                                   static_argnums=(1,))
        # phase timing published as bigdl_tpu_generate_{prefill,decode}
        # _seconds histograms (observability registry); .summary() gives
        # the host-side view
        from bigdl_tpu.utils.profiling import StepTimer

        self.step_timer = StepTimer(metrics_prefix="bigdl_tpu_generate")

    def _bucket(self, n: int) -> int:
        """Round prompt length up to a power-of-two bucket to bound the
        number of compiled prefill executables."""
        b = 16
        while b < n:
            b *= 2
        return min(b, self.max_seq)

    def _register_memory(self, cache, batch: int) -> None:
        """Record this generation's weights + cache footprint in the
        process memory ledger (observability/memory.py) — postmortems
        and bench memory reports read it. Best-effort."""
        try:
            from bigdl_tpu.observability.memory import (default_ledger,
                                                        tree_nbytes)

            led = default_ledger()
            led.register("weights", "generator_params",
                         tree_nbytes(self.params))
            led.register("kv_cache", "generator_cache",
                         tree_nbytes(cache),
                         dtype=self.kv_cache_dtype, batch=batch)
        except Exception:
            pass

    def generate(
        self,
        input_ids,                       # [B, S] or [S] ints
        gen: Optional[GenerationConfig] = None,
        stats: Optional[GenerationStats] = None,
        visual: Optional[Tuple[Any, Any]] = None,  # (vidx [B,S], vemb [Nv,D])
    ) -> np.ndarray:
        """Returns generated ids [B, <=max_new_tokens] (prompt excluded)."""
        return np.stack(list(self.stream(input_ids, gen, stats, visual)),
                        axis=1)

    def stream(
        self,
        input_ids,
        gen: Optional[GenerationConfig] = None,
        stats: Optional[GenerationStats] = None,
        visual: Optional[Tuple[Any, Any]] = None,
    ):
        """Token-by-token generation: yields [B] int32 per step — the
        streaming-callback surface the reference gets from FastChat's
        TextIteratorStreamer (serving/fastchat/ipex_llm_worker.py)."""
        gen = gen or GenerationConfig()
        ids = np.asarray(input_ids, np.int32)
        if ids.ndim == 1:
            ids = ids[None]
        b, s = ids.shape
        if s + gen.max_new_tokens > self.max_seq:
            raise ValueError(
                f"prompt ({s}) + max_new_tokens ({gen.max_new_tokens}) "
                f"exceeds max_seq {self.max_seq}")

        cache = self.new_cache(self.cfg, b, self.max_seq,
                               self.kv_cache_dtype)
        recurrent = (not isinstance(cache, KVCache)
                     if self.recurrent is None else self.recurrent)
        self._register_memory(cache, b)
        if recurrent:
            # recurrent families (RWKV): the state absorbs every token it
            # sees, so pad tokens cannot be masked retroactively — prefill
            # at the exact prompt length (one executable per length).
            bucket = s
        else:
            bucket = self._bucket(s)
        # right-pad into the bucket: positions stay correct for RoPE, the
        # garbage keys the pad writes are overwritten/masked (see below)
        pad = bucket - s
        padded = np.zeros((b, bucket), np.int32)
        padded[:, :s] = ids

        key = jax.random.PRNGKey(gen.seed)
        t0 = time.perf_counter()
        if visual is not None:
            vidx, vemb = visual
            vidx = np.asarray(vidx, np.int32)
            if pad > 0 and (vidx[:, s - 1] > 0).any():
                raise ValueError(
                    "prompt must end with at least one text token after "
                    "the final image span (the padded-prefill repair step "
                    "re-runs the last token without injection)")
            vpad = np.zeros((b, bucket), np.int32)
            vpad[:, :s] = vidx
            # bucket the embedding-row count too (power of two) so a
            # varying image count reuses one compiled prefill — padding
            # rows are never gathered (vidx only references real rows)
            vemb = np.asarray(vemb)
            rows = max(16, 1 << (int(vemb.shape[0]) - 1).bit_length())
            if rows != vemb.shape[0]:
                vemb = np.concatenate(
                    [vemb, np.zeros((rows - vemb.shape[0],) +
                                    vemb.shape[1:], vemb.dtype)])
            if self._prefill_vis is None:
                self._prefill_vis = tracked_jit(
                    "generate_prefill_vis",
                    lambda p, c, t, kv, vi, ve: self._prefill_raw(
                        p, c, t, kv, visual=(vi, ve)),
                    static_argnums=(1,), donate_argnums=(3,))
            logits, cache = self._prefill_vis(
                self.params, self.cfg, jnp.asarray(padded), cache,
                jnp.asarray(vpad), jnp.asarray(vemb))
        else:
            logits, cache = self._prefill(
                self.params, self.cfg, jnp.asarray(padded), cache)
        # logits from forward_last_token are for the LAST cache position
        # (bucket-1); when padded, recompute pointer: forward_last_token
        # returns position bucket-1 which may be padding. Use full-forward
        # logits gather instead when pad > 0.
        if pad > 0:
            # cheap fix: decode path needs logits at position s-1; rerun the
            # last real token through decode after trimming cache.pos
            # (reset_pos keeps non-KVCache cache types' extra state)
            cache = cache.reset_pos(jnp.asarray(s - 1, jnp.int32))
            logits, cache = self._decode(
                self.params, self.cfg, jnp.asarray(ids[:, -1:]), cache)
        else:
            logits = logits[:, -1:, :]

        temp = gen.temperature if gen.do_sample else 0.0

        penal = gen.needs_token_counts
        if penal:
            v = logits.shape[-1]
            counts = self._counts(jnp.asarray(padded), v,
                                  jnp.full((b,), s, jnp.int32))
            out_counts = jnp.zeros((b, v), jnp.int32)

        def sample(lg, k):
            nonlocal counts, out_counts
            if penal:
                t, counts, out_counts = self._sample_pen(
                    lg, k, counts, out_counts, temperature=temp,
                    top_k=gen.top_k, top_p=gen.top_p,
                    rep=gen.repetition_penalty,
                    pres=gen.presence_penalty, freq=gen.frequency_penalty)
                return t
            return self._sample(lg, k, temperature=temp, top_k=gen.top_k,
                                top_p=gen.top_p)

        if gen.check_logits and not np.isfinite(
                np.asarray(logits[:, -1, :])).all():
            raise FloatingPointError("non-finite logits after prefill")

        key, sk = jax.random.split(key)
        tok = sample(logits[:, -1, :], sk)
        tok_host = np.asarray(tok)
        self.step_timer.record("prefill", time.perf_counter() - t0)
        if stats is not None:
            stats.first_token_s = time.perf_counter() - t0

        yield tok_host
        finished = np.zeros((b,), bool)
        finished_dev = jnp.zeros((b,), jnp.bool_)
        if gen.eos_token_id is not None:
            finished |= tok_host == gen.eos_token_id
            finished_dev = jnp.asarray(finished)

        # resident single-dispatch decode (ISSUE 14b): forward + PRNG
        # split + sampling + EOS masking run as ONE executable per token,
        # so the tunnel/dispatch overhead is paid once per step instead
        # of once per phase. Host-side per-step work (penalty counters
        # via _sample_pen's nonlocals, fault hooks, check_logits pulls)
        # keeps the legacy multi-dispatch loop.
        from bigdl_tpu.config import decode_resident_enabled
        from bigdl_tpu.robustness.faults import NULL as _no_faults

        resident = (decode_resident_enabled() and not penal
                    and not gen.check_logits
                    and self.faults is _no_faults)

        for step_i in range(1, gen.max_new_tokens):
            if finished.all():
                break
            t1 = time.perf_counter()
            if resident:
                tok, cache, key, finished_dev = self._decode_resident(
                    self.params, self.cfg, tok, cache, key, finished_dev,
                    temperature=temp, top_k=gen.top_k, top_p=gen.top_p,
                    eos=gen.eos_token_id)
                tok_host = np.asarray(tok)
                self.step_timer.record("decode", time.perf_counter() - t1)
                if stats is not None:
                    stats.rest_token_s.append(time.perf_counter() - t1)
                yield tok_host
                if gen.eos_token_id is not None:
                    finished |= tok_host == gen.eos_token_id
                continue
            # fault hooks mirror the serving engine's step points
            self.faults.raise_point("step", step_i)
            ms = self.faults.sleep_ms("step", step_i)
            if ms > 0:
                time.sleep(ms / 1000.0)
            logits, cache = self._decode(
                self.params, self.cfg, tok[:, None], cache)
            bad = self.faults.poison_rows(step_i, list(range(b)))
            if bad:
                logits = logits.at[jnp.asarray(bad)].set(jnp.nan)
            if gen.check_logits and not np.isfinite(
                    np.asarray(logits[:, -1, :])).all():
                raise FloatingPointError(
                    f"non-finite logits at decode step {step_i}")
            key, sk = jax.random.split(key)
            tok = sample(logits[:, -1, :], sk)
            if gen.eos_token_id is not None:
                # post-EOS rows emit pad (0): parity with generate_on_device.
                # Mask and track EOS on device; nothing is uploaded per step.
                tok = jnp.where(finished_dev, 0, tok)
                finished_dev = finished_dev | (tok == gen.eos_token_id)
            tok_host = np.asarray(tok)
            self.step_timer.record("decode", time.perf_counter() - t1)
            if stats is not None:
                stats.rest_token_s.append(time.perf_counter() - t1)
            yield tok_host
            if gen.eos_token_id is not None:
                finished |= tok_host == gen.eos_token_id
