"""Training core: loss, train-step factory, sharded optimizer state.

The reference finetunes through HF Trainer + DeepSpeed ZeRO-2 over
MPI/oneCCL (SURVEY.md §3.5, transformers/training_patch.py). Here a train
step is a pure function jitted over a mesh: params carry their shardings
(bigdl_tpu.parallel), the batch is dp-sharded, and XLA emits the gradient
all-reduce over ICI — the `mpirun + ccl` stack collapses into GSPMD.

Works over dense (full finetune) and mixed dense/QTensor+LoRA pytrees
(QLoRA: frozen quantized base + trainable adapters, bigdl_tpu/qlora.py).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from bigdl_tpu.observability.compile_watch import tracked_jit


def next_token_loss(logits: jax.Array, tokens: jax.Array,
                    mask: Optional[jax.Array] = None) -> jax.Array:
    """Mean next-token cross-entropy. logits [B,S,V] f32, tokens [B,S].

    mask [B,S] marks *target* validity (loss over positions 1..S-1 uses
    mask[:, 1:]); pad targets contribute zero.
    """
    targets = tokens[:, 1:]
    lg = logits[:, :-1, :]
    ll = jax.nn.log_softmax(lg, axis=-1)
    nll = -jnp.take_along_axis(ll, targets[..., None], axis=-1)[..., 0]
    if mask is not None:
        m = mask[:, 1:].astype(jnp.float32)
        return jnp.sum(nll * m) / jnp.maximum(jnp.sum(m), 1.0)
    return jnp.mean(nll)


def make_train_step(
    forward_train: Callable,   # (params, cfg, tokens) -> logits
    cfg: Any,
    optimizer: optax.GradientTransformation,
    trainable_filter: Optional[Callable[[Any], Any]] = None,
    timer: Optional[Any] = None,
) -> Callable:
    """Build a jittable `step(params, opt_state, batch) -> (params,
    opt_state, loss)`.

    `trainable_filter(params) -> pytree of bool` freezes leaves (QLoRA:
    only adapters train). Gradients for frozen leaves are zeroed before the
    optimizer, so optimizer state for them stays inert.

    `timer` (a utils/profiling.StepTimer) wraps each call in
    `timed("train_step", ...)` — blocking wall time per step, published
    to the observability registry when the timer has a metrics prefix.
    """

    def loss_fn(params, batch):
        logits = forward_train(params, cfg, batch["input_ids"])
        return next_token_loss(logits, batch["input_ids"],
                               batch.get("attention_mask"))

    # audited no-donate: relora-style callers snapshot the pre-step
    # params tree (merge/reset cycles) after the call returns, so
    # donating position 0 would hand them invalidated buffers
    @functools.partial(tracked_jit, "train_step")
    def step(params, opt_state, batch):  # graftlint: disable=jax-missing-donate
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        if trainable_filter is not None:
            tmask = trainable_filter(params)
            grads = jax.tree.map(
                lambda g, t: g if t else jnp.zeros_like(g), grads, tmask)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    if timer is None:
        return step

    def timed_step(params, opt_state, batch):
        return timer.timed("train_step", step, params, opt_state, batch)

    return timed_step


# ---------------------------------------------------------------------------
# Partitioned training (QLoRA): differentiate ONLY trainable leaves
# ---------------------------------------------------------------------------

def partition(params: Any, mask: Any) -> Tuple[Any, Any]:
    """Split a pytree into (trainable, frozen) by a bool mask pytree.

    Frozen positions become None in the trainable tree and vice versa
    (recombined with `combine`). This is how QLoRA avoids both AD through
    int-packed QTensor leaves and optimizer state for the frozen base —
    the reference instead freezes modules and relies on requires_grad
    (qlora.py:294-342).
    """
    train = jax.tree.map(lambda p, m: p if m else None, params, mask)
    frozen = jax.tree.map(lambda p, m: None if m else p, params, mask)
    return train, frozen


def combine(train: Any, frozen: Any) -> Any:
    """Inverse of `partition`."""
    return jax.tree.map(
        lambda a, b: b if a is None else a, train, frozen,
        is_leaf=lambda x: x is None)


def make_lora_train_step(
    forward_train: Callable,   # (params, cfg, tokens) -> logits
    cfg: Any,
    optimizer: optax.GradientTransformation,
    timer: Optional[Any] = None,
) -> Callable:
    """Build `step(train, opt_state, frozen, batch)` for adapter training.
    `timer` as in make_train_step.

    Usage:
        train, frozen = partition(params, lora_trainable_mask(params))
        opt_state = optimizer.init(train)
        step = make_lora_train_step(fwd, cfg, opt)
        train, opt_state, loss = step(train, opt_state, frozen, batch)
    """

    def loss_fn(train, frozen, batch):
        params = combine(train, frozen)
        logits = forward_train(params, cfg, batch["input_ids"])
        return next_token_loss(logits, batch["input_ids"],
                               batch.get("attention_mask"))

    # audited no-donate: see train_step — merge-and-reset callers keep
    # the previous adapter tree alive across the step boundary
    @functools.partial(tracked_jit, "lora_train_step")
    def step(train, opt_state, frozen, batch):  # graftlint: disable=jax-missing-donate
        loss, grads = jax.value_and_grad(loss_fn)(train, frozen, batch)
        updates, opt_state = optimizer.update(grads, opt_state, train)
        train = optax.apply_updates(train, updates)
        return train, opt_state, loss

    if timer is None:
        return step

    def timed_step(train, opt_state, frozen, batch):
        return timer.timed("train_step", step, train, opt_state, frozen,
                           batch)

    return timed_step
