"""optimize_model: quantize an already-loaded parameter pytree.

The reference's generic entry point (`ipex_llm.optimize_model`, reference
optimize.py:196) walks an arbitrary nn.Module replacing Linears. Here the
equivalent walks a parameter pytree: any dense contraction-major [.., K, N]
linear leaf whose name isn't excluded becomes a QTensor (stacked per-layer
leaves are vmapped through the quantizer). Norm scales, biases and
embeddings stay dense, matching the reference's default module filter.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional, Tuple

import jax

from bigdl_tpu.ops.quant import QTensor, quantize

# leaf-name suffixes never quantized (reference skips non-Linear modules and
# `modules_to_not_convert`; embedding quantization is a separate opt-in)
_DEFAULT_SKIP = ("norm", "layernorm", "bias", "embed_tokens", "rotary")


def _should_quantize(name: str, leaf: Any, skip: Tuple[str, ...]) -> bool:
    if isinstance(leaf, QTensor) or not hasattr(leaf, "ndim"):
        return False
    if leaf.ndim < 2 or not jax.numpy.issubdtype(leaf.dtype, jax.numpy.floating):
        return False
    lname = name.lower()
    return not any(s in lname for s in skip)


def optimize_model(
    model_or_params: Any,
    low_bit: str = "sym_int4",
    modules_to_not_convert: Iterable[str] = (),
    optimize_llm: bool = True,   # parity kwarg; forwards are always optimized
) -> Any:
    """Quantize dense linear leaves of a model/pytree to `low_bit`.

    Accepts a TpuCausalLM (returns the same object with quantized params)
    or a raw parameter pytree (returns a new pytree).
    """
    from bigdl_tpu.transformers.model import TpuCausalLM

    skip = tuple(_DEFAULT_SKIP) + tuple(
        m.lower() for m in modules_to_not_convert)

    if isinstance(model_or_params, TpuCausalLM):
        model = model_or_params
        model.params = _quantize_tree(model.params, low_bit, skip)
        model.qtype = low_bit
        model._generator = None   # recompile against the new leaf types
        return model
    return _quantize_tree(model_or_params, low_bit, skip)


def _quantize_tree(tree: Any, qtype: str, skip: Tuple[str, ...],
                   _name: str = "") -> Any:
    from bigdl_tpu.ops.quant import MIXED_QTYPES, quantize_auto

    if isinstance(tree, dict):
        return {k: _quantize_tree(v, qtype, skip, f"{_name}.{k}")
                for k, v in tree.items()}
    if _should_quantize(_name, tree, skip):
        if tree.ndim == 2:
            return quantize_auto(tree, qtype)
        if tree.ndim == 3:  # stacked per-layer [L, K, N]
            if qtype in MIXED_QTYPES:
                # per-layer MSE pick needs host sync: quantize layer by
                # layer (load-time only), then restack
                qs = [quantize_auto(tree[i], qtype)
                      for i in range(tree.shape[0])]
                if len({q.qtype for q in qs}) > 1:
                    # candidates may differ per layer; a stacked leaf needs
                    # one format — pick the majority and requantize strays
                    from collections import Counter

                    best = Counter(q.qtype for q in qs).most_common(1)[0][0]
                    qs = [q if q.qtype == best else quantize(tree[i], best)
                          for i, q in enumerate(qs)]
                return jax.tree.map(lambda *xs: jax.numpy.stack(xs), *qs)
            return jax.vmap(lambda w: quantize(w, qtype))(tree)
    return tree
