"""LlamaIndex integration.

Equivalent of the reference's `BigdlLLM` llama-index class (reference
llamaindex/llms/bigdlllm.py:1-467). llama-index is optional; the class is
defined only when importable, over the same TpuLLMCore as langchain.
"""

from __future__ import annotations

from typing import Any

from bigdl_tpu.integrations.langchain import TpuLLMCore


def _make_llamaindex_class():
    from llama_index.core.llms import (CompletionResponse, CompletionResponseGen,
                                       CustomLLM, LLMMetadata)
    from llama_index.core.llms.callbacks import llm_completion_callback

    class BigdlTpuLLM(CustomLLM):
        """llama-index LLM over bigdl_tpu."""
        core: Any = None
        context_window: int = 2048
        num_output: int = 256

        @classmethod
        def from_model_id(cls, model_id: str, **kw):
            return cls(core=TpuLLMCore(model_id), **kw)

        @property
        def metadata(self) -> LLMMetadata:
            return LLMMetadata(context_window=self.context_window,
                               num_output=self.num_output,
                               model_name="bigdl-tpu")

        @llm_completion_callback()
        def complete(self, prompt: str, **kw) -> CompletionResponse:
            return CompletionResponse(
                text=self.core.complete(prompt,
                                        max_new_tokens=self.num_output))

        @llm_completion_callback()
        def stream_complete(self, prompt: str, **kw) -> CompletionResponseGen:
            def gen():
                # REAL incremental decoding (TpuLLMCore.stream), not a
                # post-hoc character replay of a finished completion
                acc = ""
                for delta in self.core.stream(
                        prompt, max_new_tokens=self.num_output):
                    acc += delta
                    yield CompletionResponse(text=acc, delta=delta)

            return gen()

    return BigdlTpuLLM


try:
    BigdlTpuLLM = _make_llamaindex_class()
except ImportError:
    BigdlTpuLLM = None
