"""LangChain integration: LLM + embeddings wrappers.

Equivalent of the reference's langchain package (reference
langchain/llms/bigdlllm.py `TransformersLLM`, langchain/embeddings/
bigdlllm.py `TransformersEmbeddings`; SURVEY.md §2). langchain is optional:
the `TpuLLMCore` below is dependency-free and the LangChain classes are
thin shells over it, generated only when langchain is importable.
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np


class TpuLLMCore:
    """Framework-only text-in/text-out core shared by the integrations."""

    def __init__(self, model_path: str, low_bit: str = "sym_int4",
                 max_seq: int = 2048, **model_kwargs: Any):
        from bigdl_tpu.transformers.model import AutoModelForCausalLM

        self.model = AutoModelForCausalLM.from_pretrained(
            model_path, load_in_low_bit=low_bit, max_seq=max_seq,
            **model_kwargs)
        from transformers import AutoTokenizer

        self.tokenizer = AutoTokenizer.from_pretrained(model_path)

    def complete(self, prompt: str, max_new_tokens: int = 256,
                 temperature: float = 0.0, stop: Optional[List[str]] = None
                 ) -> str:
        ids = self.tokenizer(prompt)["input_ids"]
        out = self.model.generate(
            ids, max_new_tokens=max_new_tokens,
            do_sample=temperature > 0, temperature=temperature)
        text = self.tokenizer.decode(out[0][len(ids):],
                                     skip_special_tokens=True)
        for s in stop or []:
            idx = text.find(s)
            if idx >= 0:
                text = text[:idx]
        return text

    def embed(self, texts: List[str]) -> List[List[float]]:
        """Mean-pooled token embeddings: hidden_size-dimensional vectors
        from the model's embedding table (the reference's transformers
        embeddings similarly pool model representations)."""
        m = self.model
        table = np.asarray(m.params["embed_tokens"], np.float32)
        outs = []
        for t in texts:
            ids = np.asarray(self.tokenizer(t)["input_ids"], np.int32)
            vec = table[ids].mean(axis=0)
            outs.append(vec.astype(np.float32).tolist())
        return outs


def _make_langchain_classes():
    from langchain_core.embeddings import Embeddings
    from langchain_core.language_models.llms import LLM

    class TransformersLLM(LLM):
        """LangChain LLM over bigdl_tpu (reference TransformersLLM)."""
        core: Any = None

        @classmethod
        def from_model_id(cls, model_id: str, model_kwargs=None, **kw):
            return cls(core=TpuLLMCore(model_id, **(model_kwargs or {})),
                       **kw)

        @property
        def _llm_type(self) -> str:
            return "bigdl-tpu"

        def _call(self, prompt: str, stop=None, run_manager=None, **kw):
            return self.core.complete(prompt, stop=stop, **kw)

    class TransformersEmbeddings(Embeddings):
        def __init__(self, core: TpuLLMCore):
            self.core = core

        @classmethod
        def from_model_id(cls, model_id: str, **kw):
            return cls(TpuLLMCore(model_id, **kw))

        def embed_documents(self, texts: List[str]) -> List[List[float]]:
            return self.core.embed(texts)

        def embed_query(self, text: str) -> List[float]:
            return self.core.embed([text])[0]

    return TransformersLLM, TransformersEmbeddings


try:
    TransformersLLM, TransformersEmbeddings = _make_langchain_classes()
except ImportError:
    TransformersLLM = TransformersEmbeddings = None
