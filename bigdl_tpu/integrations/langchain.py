"""LangChain integration: LLM + embeddings wrappers.

Equivalent of the reference's langchain package (reference
langchain/llms/bigdlllm.py `TransformersLLM`, langchain/embeddings/
bigdlllm.py `TransformersEmbeddings`; SURVEY.md §2). langchain is optional:
the `TpuLLMCore` below is dependency-free and the LangChain classes are
thin shells over it, generated only when langchain is importable.
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np


class TpuLLMCore:
    """Framework-only text-in/text-out core shared by the integrations."""

    def __init__(self, model_path: str, low_bit: str = "sym_int4",
                 max_seq: int = 2048, **model_kwargs: Any):
        from bigdl_tpu.transformers.model import AutoModelForCausalLM

        self.model = AutoModelForCausalLM.from_pretrained(
            model_path, load_in_low_bit=low_bit, max_seq=max_seq,
            **model_kwargs)
        try:
            from transformers import AutoTokenizer

            self.tokenizer = AutoTokenizer.from_pretrained(model_path)
        except Exception:
            tok_info = getattr(self.model, "gguf_tokenizer_info", None)
            if not tok_info:
                raise
            from bigdl_tpu.gguf_tokenizer import GGUFTokenizer

            self.tokenizer = GGUFTokenizer.from_tokenizer_info(tok_info)

        # contextual-embedding forward: probe once, jit once (compiles
        # per padded power-of-two length bucket, reused across calls)
        import inspect

        fwd = getattr(self.model.family, "forward_train", None)
        self._embed_fwd = None
        if fwd is not None and "return_hidden" in \
                inspect.signature(fwd).parameters:
            from bigdl_tpu.observability.compile_watch import tracked_jit

            cfg = self.model.config
            self._embed_fwd = tracked_jit(
                "langchain_embed_forward",
                lambda p, t: fwd(p, cfg, t, return_hidden=True))

    def complete(self, prompt: str, max_new_tokens: int = 256,
                 temperature: float = 0.0, stop: Optional[List[str]] = None
                 ) -> str:
        ids = self.tokenizer(prompt)["input_ids"]
        out = self.model.generate(
            ids, max_new_tokens=max_new_tokens,
            do_sample=temperature > 0, temperature=temperature)
        text = self.tokenizer.decode(out[0][len(ids):],
                                     skip_special_tokens=True)
        for s in stop or []:
            idx = text.find(s)
            if idx >= 0:
                text = text[:idx]
        return text

    def stream(self, prompt: str, max_new_tokens: int = 256,
               temperature: float = 0.0,
               stop: Optional[List[str]] = None):
        """Yield text DELTAS as tokens decode (incremental-prefix
        decoding handles multi-byte/multi-token glyphs). Stops early on
        any stop string; the streaming-callback surface the reference
        exposes via FastChat's TextIteratorStreamer."""
        ids = list(self.tokenizer(prompt)["input_ids"])
        stops = list(stop or [])
        new_ids: List[int] = []
        emitted = ""
        text = ""

        def holdback(t: str) -> int:
            """Longest tail of `t` that is a proper PREFIX of a stop
            string — withheld so a stop spanning token boundaries is
            never partially emitted."""
            h = 0
            for s_ in stops:
                for k in range(1, len(s_)):
                    if t.endswith(s_[:k]):
                        h = max(h, k)
            return h

        for t in self.model.generate_stream(
                ids, max_new_tokens=max_new_tokens,
                do_sample=temperature > 0, temperature=temperature):
            new_ids.append(t)
            text = self.tokenizer.decode(new_ids,
                                         skip_special_tokens=True)
            if text.endswith("�"):     # partial multi-byte glyph
                continue
            cut = None
            for s_ in stops:
                idx = text.find(s_)
                if idx >= 0:
                    cut = idx if cut is None else min(cut, idx)
            if cut is not None:
                if cut > len(emitted):
                    yield text[len(emitted):cut]
                return
            safe = text[:len(text) - holdback(text)]
            if len(safe) > len(emitted):
                yield safe[len(emitted):]
                emitted = safe
        # flush anything withheld at end-of-generation — re-applying the
        # stop scan (the last token may both complete a stop string and
        # end mid-glyph, in which case the loop never scanned it)
        cut = len(text)
        for s_ in stops:
            idx = text.find(s_)
            if idx >= 0:
                cut = min(cut, idx)
        if cut > len(emitted):
            yield text[len(emitted):cut]

    def embed(self, texts: List[str]) -> List[List[float]]:
        """Sentence embeddings by mean-pooling the model's FINAL hidden
        states (the reference's TransformersEmbeddings pools model
        outputs, langchain/embeddings/bigdlllm.py) — contextual vectors,
        not a static table lookup."""
        import jax.numpy as jnp

        m = self.model
        outs = []
        for t in texts:
            ids = np.asarray(self.tokenizer(t)["input_ids"], np.int32)
            if self._embed_fwd is not None:
                # pad right to a power-of-two bucket: causal attention
                # means pad positions cannot affect the real prefix, and
                # bucketed lengths reuse one compiled executable
                n = len(ids)
                bucket = max(16, 1 << (n - 1).bit_length())
                padded = np.zeros((1, bucket), np.int32)
                padded[0, :n] = ids
                hid = self._embed_fwd(m.params, jnp.asarray(padded))
                vec = np.asarray(hid[0, :n], np.float32).mean(axis=0)
            else:   # families without the tap: embedding-table pooling
                table = np.asarray(m.params["embed_tokens"], np.float32)
                vec = table[ids].mean(axis=0)
            outs.append(vec.astype(np.float32).tolist())
        return outs


def _make_langchain_classes():
    from langchain_core.embeddings import Embeddings
    from langchain_core.language_models.llms import LLM
    from langchain_core.outputs import GenerationChunk

    class TransformersLLM(LLM):
        """LangChain LLM over bigdl_tpu (reference TransformersLLM)."""
        core: Any = None

        @classmethod
        def from_model_id(cls, model_id: str, model_kwargs=None, **kw):
            return cls(core=TpuLLMCore(model_id, **(model_kwargs or {})),
                       **kw)

        @property
        def _llm_type(self) -> str:
            return "bigdl-tpu"

        def _call(self, prompt: str, stop=None, run_manager=None, **kw):
            return self.core.complete(prompt, stop=stop, **kw)

        def _stream(self, prompt: str, stop=None, run_manager=None,
                    **kw):
            for delta in self.core.stream(prompt, stop=stop, **kw):
                chunk = GenerationChunk(text=delta)
                if run_manager is not None:
                    run_manager.on_llm_new_token(delta, chunk=chunk)
                yield chunk

    class TransformersEmbeddings(Embeddings):
        def __init__(self, core: TpuLLMCore):
            self.core = core

        @classmethod
        def from_model_id(cls, model_id: str, **kw):
            return cls(TpuLLMCore(model_id, **kw))

        def embed_documents(self, texts: List[str]) -> List[List[float]]:
            return self.core.embed(texts)

        def embed_query(self, text: str) -> List[float]:
            return self.core.embed([text])[0]

    return TransformersLLM, TransformersEmbeddings


try:
    TransformersLLM, TransformersEmbeddings = _make_langchain_classes()
except ImportError:
    TransformersLLM = TransformersEmbeddings = None
