"""ReLoRA: periodic LoRA merge-and-restart training.

Equivalent of the reference's ReLoRA stack (reference transformers/
relora.py: `ReLoRATrainer` at :64, `ReLoRACallback` merging adapters every
`relora_steps` at :149, optimizer reset at :128, jagged-cosine LR schedule
`ReLoRAScheduler` at :286, `merge_and_save` at :383). High-rank updates
accumulate through a sequence of low-rank cycles.

Functional form: no Trainer subclass — a restart is a pure transformation
(merge adapters into the (re-quantized) base, re-init fresh adapters, reset
optimizer state) applied between train steps, and the jagged-cosine LR is
an optax-style schedule. Everything composes with training.py's partitioned
step and with sequence/data parallelism unchanged.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from bigdl_tpu.qlora import LoraConfig, attach_lora, lora_trainable_mask, merge_lora
from bigdl_tpu.training import combine, partition


def jagged_cosine_schedule(
    base_lr: float,
    relora_steps: int,
    warmup_steps: int = 10,
    min_lr_ratio: float = 0.1,
) -> Callable:
    """The reference's ReLoRAScheduler (relora.py:286): every cycle does a
    short linear re-warmup then cosine-decays to min_lr_ratio.

    Note: train_relora re-inits the optimizer at each restart, which
    resets optax's step count — there the `mod` below is a no-op and each
    cycle is just warmup+cosine. The mod matters when this schedule is
    used with a single long-lived optimizer (no state resets)."""

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        cycle_pos = jnp.mod(step, relora_steps)
        warm = jnp.minimum(cycle_pos / max(warmup_steps, 1), 1.0)
        cos = min_lr_ratio + (1.0 - min_lr_ratio) * 0.5 * (
            1.0 + jnp.cos(math.pi * cycle_pos / relora_steps))
        return base_lr * warm * cos

    return schedule


def relora_restart(
    train: Any,
    frozen: Any,
    optimizer: optax.GradientTransformation,
    config: LoraConfig,
    *,
    key: Optional[jax.Array] = None,
    requantize: bool = True,
) -> Tuple[Any, Any, Any, Any]:
    """Merge current adapters into the base and start a fresh cycle.

    Returns (train, frozen, opt_state, mask): the merged base becomes the
    new frozen tree, adapters re-initialize (B zero, so the restart is
    loss-neutral), and optimizer state resets (the reference prunes
    optimizer moments at :128; a fresh init is the clean equivalent for
    adapters that are themselves fresh).
    """
    if key is None:
        key = jax.random.PRNGKey(0)
    params = combine(train, frozen)
    merged = merge_lora(params, requantize=requantize)
    fresh = attach_lora(merged, config, key=key)
    mask = lora_trainable_mask(fresh)
    train2, frozen2 = partition(fresh, mask)
    opt_state = optimizer.init(train2)
    return train2, frozen2, opt_state, mask


def train_relora(
    forward_train: Callable,
    cfg: Any,
    params_lora: Any,
    batches,                        # iterable of batch dicts
    *,
    config: LoraConfig = LoraConfig(),
    base_lr: float = 1e-3,
    relora_steps: int = 50,
    warmup_steps: int = 5,
    seed: int = 0,
    requantize: bool = True,
) -> Tuple[Any, list]:
    """Reference ReLoRATrainer.train, functional: run `batches` with a
    merge-restart every `relora_steps`. Returns (merged_params, losses)."""
    from bigdl_tpu.training import make_lora_train_step

    sched = jagged_cosine_schedule(base_lr, relora_steps, warmup_steps)
    optimizer = optax.adamw(sched)
    mask = lora_trainable_mask(params_lora)
    train, frozen = partition(params_lora, mask)
    opt_state = optimizer.init(train)
    step_fn = make_lora_train_step(forward_train, cfg, optimizer)
    key = jax.random.PRNGKey(seed)

    losses = []
    for i, batch in enumerate(batches):
        if i > 0 and i % relora_steps == 0:
            key, sub = jax.random.split(key)
            train, frozen, opt_state, mask = relora_restart(
                train, frozen, optimizer, config, key=sub,
                requantize=requantize)
        train, opt_state, loss = step_fn(train, opt_state, frozen, batch)
        losses.append(float(loss))
    return merge_lora(combine(train, frozen), requantize=requantize), losses
