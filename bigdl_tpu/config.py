"""Central runtime flags: one typed object + env overrides.

The reference's de-facto flag system is ~12 scattered environment
variables (SURVEY.md §5: BIGDL_OPT_IPEX, IPEX_LLM_QUANTIZE_KV_CACHE,
IPEX_LLM_LOW_MEM, BIGDL_LLM_XMX_DISABLED, KV_CACHE_ALLOC_BLOCK_LENGTH...).
Here every knob lives on one dataclass, read once from the environment and
overridable in code — `flags()` is the single source of truth the rest of
the framework consults.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


def _env_bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() not in ("", "0", "false", "no", "off")


@dataclasses.dataclass
class RuntimeFlags:
    # kernel dispatch: "auto" (Pallas on TPU when supported), "xla", "pallas"
    matmul_backend: str = "auto"
    # decode-attention dispatch, same values (ops/pallas/decode_attention)
    attention_backend: str = "auto"
    # decode GEMV (M<=16) kernel variant: "auto" (MXU body when the
    # weights carry the int4-dtype layout, else the standard body),
    # "fold" (scale-folded body over the canonical packing), "mxuflat"
    # (int4-dtype load + per-weight scale + one flat full-K MXU dot),
    # "mxu8" (q8 activations against int4/int8 weights on the MXU's
    # int8 path — 2x bf16 throughput, q8 rounding on activations),
    # "off" (route small-M through the generic tiles) — the on-chip
    # A/B switch
    matmul_gemv: str = "auto"
    # In "auto" matmul dispatch, batch rows above this go to the XLA
    # matmul instead of the Pallas dequant kernel. First on-chip A/B
    # (v5e, llama2-7B INT4): XLA wins prefill-class M (197.9 vs 267.2ms
    # first token at M=1024) while Pallas wins decode-class M (30.2 vs
    # 74.1ms/token) — the dequant is VPU-bound, so at MXU-bound M the
    # dequantize-then-matmul XLA plan is faster. Forced "pallas" mode
    # ignores this.
    matmul_pallas_max_m: int = 128
    # MoE prefill dispatch: "auto" (sorted ragged kernel on TPU, dense
    # combine elsewhere), "ragged" (force, incl. interpret), "dense"
    moe_dispatch: str = "auto"
    # sym_int4 weight storage at model load: "auto" (int4-dtype MXU
    # layout on TPU — native Mosaic int4 loads instead of the VPU
    # nibble-unpack chain; canonical split-block elsewhere), "on", "off"
    mxu_layout: str = "auto"
    # load-time weight prepacking (ops/quant.prepack_tree): "auto"
    # (retile QTensor planes into the kernel layout when the target is
    # TPU — subsumes mxu_layout), "on" (force the retile anywhere),
    # "off" (keep the canonical split-block planes). Applied ONCE at
    # checkpoint load; save_low_bit always writes canonical planes.
    prepack: str = "auto"
    # resident single-dispatch decode step: fuse forward + sampling +
    # EOS bookkeeping into ONE tracked_jit per token so the serving
    # engine/generator issue a single host dispatch per step. "auto"
    # (on whenever the step has no host-side per-row work: no penalty
    # sampling, no fault hooks), "on" (same gate, assert-style intent),
    # "off" (legacy multi-dispatch step)
    decode_resident: str = "auto"
    # perf-regression sentinel (observability/sentinel.py): "auto"/"on"
    # watch the decode EWMAs against the rolling baseline, "off" skip
    # sentinel construction entirely (zero per-step overhead)
    sentinel: str = "auto"
    # quality observability (observability/quality.py): "auto"/"on"
    # record load-time quantization-error attribution and feed the
    # decode-path quality telemetry + QualitySentinel; "off" skips the
    # dequant round-trip at load and all per-step quality work
    quality: str = "auto"
    # host-side C++ kernels (bigdl_tpu.native); disable to force pure JAX
    disable_native: bool = False
    native_cache_dir: Optional[str] = None
    # default KV-cache storage dtype when the caller doesn't specify:
    # "bf16" | "fp8_e5m2" | "int8" | "int4" (block-scaled codes)
    kv_cache_dtype: str = "bf16"
    # DEPRECATED boolean alias for kv_cache_dtype="fp8_e5m2" (reference
    # IPEX_LLM_QUANTIZE_KV_CACHE); consulted only when kv_cache_dtype
    # is left at its default
    quantize_kv_cache: bool = False
    # default max sequence length for loaded models
    default_max_seq: int = 2048
    # paged KV cache: positions per arena page. 0 = off (per-slot slab);
    # otherwise a power of two that divides max_seq. 128 matches the TPU
    # lane tile (one page == one S-block in the paged Pallas kernel);
    # smaller values are legal on the XLA fallback path (tests use 16).
    kv_page_size: int = 0
    # paged KV cache: total physical pages in the arena. 0 = auto-size
    # to max_batch * (max_seq / page_size) + 1 (the +1 is the pinned
    # null page) — i.e. the same worst case the slab held. Undersize it
    # deliberately to oversubscribe: admission then rides on prefix
    # sharing actually deduplicating pages.
    kv_pages: int = 0
    # radix-tree prefix sharing across requests (paged mode only):
    # "auto"/"on" share full-page prompt chunks copy-on-write, "off"
    # keeps every sequence's pages private
    prefix_sharing: str = "auto"
    # AOT cross-compilation target: set to "tpu" while LOWERING a program
    # for a TPU topology from a CPU host (tests/test_aot_tpu.py) so kernel
    # dispatch routes to Pallas even though jax.default_backend() is cpu.
    # Compile probes are skipped (they cannot execute on an abstract
    # topology) — Mosaic rejections surface at .compile(), which is the
    # point of the AOT suite.
    aot_target: Optional[str] = None

    @classmethod
    def from_env(cls) -> "RuntimeFlags":
        return cls(
            matmul_backend=os.environ.get("BIGDL_TPU_MATMUL_BACKEND", "auto"),
            attention_backend=os.environ.get(
                "BIGDL_TPU_ATTENTION_BACKEND", "auto"),
            matmul_gemv=os.environ.get("BIGDL_TPU_MATMUL_GEMV", "auto"),
            matmul_pallas_max_m=int(os.environ.get(
                "BIGDL_TPU_MATMUL_PALLAS_MAX_M", "128")),
            moe_dispatch=os.environ.get("BIGDL_TPU_MOE_DISPATCH", "auto"),
            mxu_layout=os.environ.get("BIGDL_TPU_MXU_LAYOUT", "auto"),
            prepack=_tristate_env("BIGDL_TPU_PREPACK",
                                  lambda s: resolve_prepack(s)),
            decode_resident=_tristate_env(
                "BIGDL_TPU_DECODE_RESIDENT",
                lambda s: resolve_decode_resident(s)),
            sentinel=_tristate_env("BIGDL_TPU_SENTINEL",
                                   lambda s: resolve_sentinel(s)),
            quality=_tristate_env("BIGDL_TPU_QUALITY",
                                  lambda s: resolve_quality(s)),
            disable_native=_env_bool("BIGDL_TPU_DISABLE_NATIVE"),
            native_cache_dir=os.environ.get("BIGDL_TPU_NATIVE_CACHE"),
            kv_cache_dtype=os.environ.get(
                "BIGDL_TPU_KV_CACHE_DTYPE", "bf16").strip().lower() or "bf16",
            quantize_kv_cache=_env_bool("BIGDL_TPU_QUANTIZE_KV_CACHE"),
            default_max_seq=int(os.environ.get("BIGDL_TPU_MAX_SEQ", "2048")),
            kv_page_size=_checked_env(
                "BIGDL_TPU_KV_PAGE_SIZE", resolve_kv_page_size, 0),
            kv_pages=_checked_env("BIGDL_TPU_KV_PAGES", resolve_kv_pages, 0),
            prefix_sharing=_tristate_env(
                "BIGDL_TPU_PREFIX_SHARING",
                lambda s: resolve_prefix_sharing(s)),
            aot_target=(os.environ.get("BIGDL_TPU_AOT_TARGET") or "").strip()
            .lower() or None,
        )


_TRISTATE = ("auto", "on", "off")


def _tristate_env(name: str, resolver) -> str:
    """Resolve a tristate env knob, falling back to "auto" on a bad
    value: a typo must not crash the process at flag load —
    utils/env_check.py runs the same resolver and reports it."""
    try:
        return resolver(os.environ.get(name, "auto"))
    except ValueError:
        return "auto"


def _checked_env(name: str, resolver, default):
    """Resolve a validated (non-tristate) env knob, falling back to
    ``default`` on a bad value — same contract as ``_tristate_env``:
    utils/env_check.py re-runs the resolver and reports the typo."""
    try:
        return resolver(os.environ.get(name, default))
    except ValueError:
        return default


def resolve_kv_page_size(spec) -> int:
    """Normalize a BIGDL_TPU_KV_PAGE_SIZE spec: 0 disables paging,
    otherwise a power-of-two count of token positions per page."""
    try:
        n = int(str(spec).strip() or 0)
    except (TypeError, ValueError):
        raise ValueError(
            f"kv_page_size must be an integer, got {spec!r}")
    if n < 0 or (n and n & (n - 1)):
        raise ValueError(
            f"kv_page_size must be 0 (off) or a power of two, "
            f"got {spec!r}")
    return n


def resolve_kv_pages(spec) -> int:
    """Normalize a BIGDL_TPU_KV_PAGES spec: 0 auto-sizes the arena,
    otherwise a total page count >= 2 (page 0 is the pinned null page)."""
    try:
        n = int(str(spec).strip() or 0)
    except (TypeError, ValueError):
        raise ValueError(f"kv_pages must be an integer, got {spec!r}")
    if n < 0 or n == 1:
        raise ValueError(
            f"kv_pages must be 0 (auto) or >= 2 (page 0 is reserved), "
            f"got {spec!r}")
    return n


def resolve_prefix_sharing(spec) -> str:
    """Normalize a BIGDL_TPU_PREFIX_SHARING spec to "auto"|"on"|"off"."""
    s = str(spec).strip().lower() if spec is not None else "auto"
    s = {"1": "on", "true": "on", "0": "off", "false": "off",
         "": "auto"}.get(s, s)
    if s not in _TRISTATE:
        raise ValueError(
            f"unknown prefix_sharing mode {spec!r}; "
            f"choose from {_TRISTATE}")
    return s


def resolve_prepack(spec) -> str:
    """Normalize a BIGDL_TPU_PREPACK spec to "auto" | "on" | "off"."""
    s = str(spec).strip().lower() if spec is not None else "auto"
    s = {"1": "on", "true": "on", "0": "off", "false": "off",
         "": "auto"}.get(s, s)
    if s not in _TRISTATE:
        raise ValueError(
            f"unknown prepack mode {spec!r}; choose from {_TRISTATE}")
    return s


def resolve_decode_resident(spec) -> str:
    """Normalize a BIGDL_TPU_DECODE_RESIDENT spec to "auto"|"on"|"off"."""
    s = str(spec).strip().lower() if spec is not None else "auto"
    s = {"1": "on", "true": "on", "0": "off", "false": "off",
         "": "auto"}.get(s, s)
    if s not in _TRISTATE:
        raise ValueError(
            f"unknown decode_resident mode {spec!r}; "
            f"choose from {_TRISTATE}")
    return s


def resolve_sentinel(spec) -> str:
    """Normalize a BIGDL_TPU_SENTINEL spec to "auto" | "on" | "off"."""
    s = str(spec).strip().lower() if spec is not None else "auto"
    s = {"1": "on", "true": "on", "0": "off", "false": "off",
         "": "auto"}.get(s, s)
    if s not in _TRISTATE:
        raise ValueError(
            f"unknown sentinel mode {spec!r}; choose from {_TRISTATE}")
    return s


def sentinel_enabled() -> bool:
    """Effective perf-sentinel switch: "off" disables, "on"/"auto"
    enable (the sentinel's own warmup/baseline logic handles the rest)."""
    return flags().sentinel != "off"


def resolve_quality(spec) -> str:
    """Normalize a BIGDL_TPU_QUALITY spec to "auto" | "on" | "off"."""
    s = str(spec).strip().lower() if spec is not None else "auto"
    s = {"1": "on", "true": "on", "0": "off", "false": "off",
         "": "auto"}.get(s, s)
    if s not in _TRISTATE:
        raise ValueError(
            f"unknown quality mode {spec!r}; choose from {_TRISTATE}")
    return s


def quality_enabled() -> bool:
    """Effective quality-observability switch: "off" disables both the
    load-time attribution and the decode-path telemetry/sentinel;
    "on"/"auto" enable."""
    return flags().quality != "off"


def decode_resident_enabled() -> bool:
    """Effective resident-decode switch: "off" disables, "on"/"auto"
    enable (the per-step gate — penalties, fault hooks, logprob rows —
    lives at the call sites, which fall back to the legacy multi-
    dispatch step for work that must run on host)."""
    return flags().decode_resident != "off"


_flags: Optional[RuntimeFlags] = None


def flags() -> RuntimeFlags:
    global _flags
    if _flags is None:
        _flags = RuntimeFlags.from_env()
    return _flags


def default_kv_cache_dtype() -> str:
    """Effective default KV-cache storage dtype from flags.

    `kv_cache_dtype` wins when set to anything but the default; otherwise
    the deprecated `quantize_kv_cache` boolean maps True -> "fp8_e5m2"
    (with its one-time deprecation warning)."""
    from bigdl_tpu.ops.kvcache import resolve_kv_cache_dtype

    f = flags()
    if f.kv_cache_dtype and f.kv_cache_dtype != "bf16":
        return resolve_kv_cache_dtype(f.kv_cache_dtype)
    return resolve_kv_cache_dtype(f.quantize_kv_cache)


def target_is_tpu() -> bool:
    """True when code will EXECUTE on TPU: the live backend is TPU, or we
    are AOT-lowering for a TPU topology (flags().aot_target == 'tpu').
    Kernel dispatch consults this instead of jax.default_backend()."""
    t = flags().aot_target
    if t is not None and t != "tpu":
        raise ValueError(f"unknown aot_target {t!r}; only 'tpu' is supported")
    if t == "tpu":
        return True
    try:
        import jax

        return jax.default_backend() == "tpu"
    except Exception:
        return False


def under_spmd(*arrays) -> bool:
    """True when any array is (being traced as) sharded over a
    multi-device mesh. Pallas kernels cannot be auto-partitioned by
    GSPMD — dispatching one inside a sharded program is a hard compile
    error ("Mosaic kernels cannot be automatically partitioned") — so
    kernel dispatch consults this and falls back to XLA ops, which
    partition cleanly. Explicitly shard_mapped kernel calls (parallel/
    sp.py, cp.py) see LOCAL per-device shapes and are unaffected."""
    for a in arrays:
        sh = getattr(getattr(a, "aval", None), "sharding", None)
        mesh = getattr(sh, "mesh", None)
        if mesh is None or getattr(mesh, "size", 0) <= 1:
            continue
        # Manual axes = inside a shard_map body (per-device local view;
        # kernels are legal there) — only Auto/Explicit axes mean GSPMD
        # will partition this op
        try:
            from jax.sharding import AxisType

            sizes = dict(zip(mesh.axis_names, mesh.axis_sizes))
            auto = 1
            for name, t in zip(mesh.axis_names, mesh.axis_types):
                if t != AxisType.Manual:
                    auto *= sizes[name]
            if auto > 1:
                return True
        except Exception:
            return True     # unknown mesh shape info: be conservative
    return False


def set_flags(**kwargs) -> RuntimeFlags:
    """Override flags in code (tests, notebooks). Returns the new flags."""
    global _flags
    f = dataclasses.replace(flags(), **kwargs)
    _flags = f
    return f


def enable_compilation_cache(path: Optional[str] = None) -> bool:
    """Persistent XLA compilation cache (best effort).

    The TPU tunnel gives short live windows; first-compiles of the 7B
    programs cost 20-40s+ each and were burned anew by every bench
    subprocess. With the cache on disk, every window after the first
    skips straight to execution. Returns True when enabled."""
    import jax

    path = path or os.environ.get(
        "BIGDL_TPU_COMPILE_CACHE",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tpu_runs", "xla_cache"))
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              0)
        except Exception:
            pass            # knob renamed across jax versions
        return True
    except Exception:
        return False        # experimental backends may not support it
