"""Benchmark result reports: JSON-lines -> CSV / HTML.

Equivalent of the reference's benchmark reporting pipeline
(test/benchmark/csv_to_html.py + the CSV outputs of
dev/benchmark/all-in-one/run.py, wired into CI at
.github/workflows/llm_performance_tests.yml:90-147). `bench/run.py`
emits one JSON object per (model, qtype, in-out pair); this module turns
a file of those lines into a CSV table and a self-contained HTML page,
optionally diffing against a previous run (the check_results.py role).
"""

from __future__ import annotations

import csv
import html
import json
from typing import Any, Dict, List, Optional

# bench/run.py's row schema (run_one's return dict); extra keys — e.g.
# the *_prev/*_ratio columns diff_results adds — append after these
_COLUMNS = ("model", "low_bit", "api", "in_out", "first_token_ms",
            "rest_token_ms", "peak_memory")


def _ordered_columns(results: List[Dict[str, Any]]) -> List[str]:
    cols = [c for c in _COLUMNS if any(c in r for r in results)]
    extra = sorted({k for r in results for k in r
                    if k not in cols and not isinstance(r[k], (dict, list))})
    return cols + extra


def load_results(path: str) -> List[Dict[str, Any]]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def write_csv(results: List[Dict[str, Any]], path: str) -> None:
    with open(path, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=_ordered_columns(results),
                           extrasaction="ignore")
        w.writeheader()
        for r in results:
            w.writerow(r)


def _key(r: Dict[str, Any]):
    return (r.get("model"), r.get("low_bit"), r.get("api"),
            r.get("in_out"))


def diff_results(current: List[Dict[str, Any]],
                 previous: List[Dict[str, Any]],
                 field: str = "rest_token_ms") -> List[Dict[str, Any]]:
    """Attach `<field>_prev` and `<field>_ratio` (prev/cur: >1 = faster
    now) where a matching row exists in `previous`."""
    prev = {_key(r): r for r in previous}
    out = []
    for r in current:
        row = dict(r)
        p = prev.get(_key(r))
        if p is not None and p.get(field) and r.get(field):
            row[f"{field}_prev"] = p[field]
            row[f"{field}_ratio"] = round(p[field] / r[field], 3)
        out.append(row)
    return out


def write_html(results: List[Dict[str, Any]], path: str,
               title: str = "bigdl-tpu benchmark") -> None:
    cols = _ordered_columns(results)
    rows = []
    for r in results:
        tds = "".join(
            f"<td>{html.escape(str(r.get(c, '')))}</td>" for c in cols)
        rows.append(f"<tr>{tds}</tr>")
    ths = "".join(f"<th>{html.escape(c)}</th>" for c in cols)
    doc = (
        "<!doctype html><html><head><meta charset='utf-8'>"
        f"<title>{html.escape(title)}</title>"
        "<style>body{font-family:sans-serif}table{border-collapse:"
        "collapse}td,th{border:1px solid #999;padding:4px 8px;"
        "text-align:right}th{background:#eee}</style></head><body>"
        f"<h2>{html.escape(title)}</h2><table><tr>{ths}</tr>"
        f"{''.join(rows)}</table></body></html>")
    with open(path, "w") as f:
        f.write(doc)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="bench results (JSON lines) -> csv/html report")
    ap.add_argument("results", help="JSON-lines file from bench/run.py")
    ap.add_argument("--csv", default=None)
    ap.add_argument("--html", default=None)
    ap.add_argument("--baseline", default=None,
                    help="previous results file to diff against")
    args = ap.parse_args(argv)

    results = load_results(args.results)
    if args.baseline:
        results = diff_results(results, load_results(args.baseline))
    if args.csv:
        write_csv(results, args.csv)
        print(f"wrote {args.csv}")
    if args.html:
        write_html(results, args.html)
        print(f"wrote {args.html}")
    if not (args.csv or args.html):
        for r in results:
            print(json.dumps(r))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
