from bigdl_tpu.bench.benchmark_util import BenchmarkWrapper  # noqa: F401
from bigdl_tpu.bench.perplexity import perplexity  # noqa: F401
