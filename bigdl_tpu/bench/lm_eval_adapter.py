"""lm-evaluation-harness adapter (accuracy benchmarking).

Equivalent of the reference's harness adapter `BigDLLM`
(dev/benchmark/harness/bigdl_llm.py:38). Gated: lm_eval is optional; the
loglikelihood core below is also used directly by tests without lm_eval
installed.
"""

from __future__ import annotations

import functools
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _bucket(n: int, lo: int = 32) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


@functools.lru_cache(maxsize=None)
def _jitted(fwd):
    """One persistent jit wrapper per family forward — a fresh jax.jit per
    call would retrace/recompile every request."""
    from bigdl_tpu.observability.compile_watch import tracked_jit

    return tracked_jit("lm_eval_forward", fwd, static_argnums=1)


def context_logprobs(model: Any, context_ids) -> np.ndarray:
    """log p(next token | context) over the vocab, from ONE forward.

    Scores every single-token continuation of the same context at once
    (the multiple-choice fast path: n choices for the price of one)."""
    ids = np.asarray(context_ids, np.int32)
    padded = np.zeros((_bucket(len(ids)),), np.int32)
    padded[: len(ids)] = ids
    logits = np.asarray(_jitted(model.family.forward_train)(
        model.params, model.config, jnp.asarray(padded[None])))
    row = logits[0, len(ids) - 1]
    row = row - row.max()
    return row - np.log(np.exp(row).sum())


def sequence_loglikelihood(model: Any, context_ids, continuation_ids
                           ) -> Tuple[float, bool]:
    """(sum log p(continuation | context), is_greedy) for one pair.

    Sequences are right-padded to power-of-two buckets so a harness run
    compiles one forward per bucket, not one per distinct length (causality
    makes the pad rows inert for the scored positions)."""
    params, cfg = model.params, model.config
    fwd = model.family.forward_train
    ids = np.concatenate([np.asarray(context_ids, np.int32),
                          np.asarray(continuation_ids, np.int32)])
    padded = np.zeros((_bucket(len(ids)),), np.int32)
    padded[: len(ids)] = ids
    logits = np.asarray(_jitted(fwd)(
        params, cfg, jnp.asarray(padded[None])))[0][: len(ids)]
    ll = logits - logits.max(-1, keepdims=True)
    ll = ll - np.log(np.exp(ll).sum(-1, keepdims=True))
    nctx = len(context_ids)
    tgt = ids[nctx:]
    rows = np.arange(nctx - 1, len(ids) - 1)
    token_ll = ll[rows, tgt]
    greedy = bool((logits[rows].argmax(-1) == tgt).all())
    return float(token_ll.sum()), greedy


try:
    import lm_eval
    from lm_eval.api.model import LM

    class BigdlTpuLM(LM):
        """Use as: lm_eval.simple_evaluate(model=BigdlTpuLM(model, tok))."""

        def __init__(self, model: Any, tokenizer: Any, batch_size: int = 1):
            super().__init__()
            self.model = model
            self.tokenizer = tokenizer

        def loglikelihood(self, requests) -> List[Tuple[float, bool]]:
            out = []
            for req in requests:
                ctx, cont = req.args
                ctx_ids = self.tokenizer(ctx)["input_ids"]
                cont_ids = self.tokenizer(cont,
                                          add_special_tokens=False)["input_ids"]
                out.append(sequence_loglikelihood(self.model, ctx_ids,
                                                  cont_ids))
            return out

        def loglikelihood_rolling(self, requests) -> List[float]:
            out = []
            for req in requests:
                (text,) = req.args
                ids = self.tokenizer(text)["input_ids"]
                ll, _ = sequence_loglikelihood(self.model, ids[:1], ids[1:])
                out.append(ll)
            return out

        def generate_until(self, requests) -> List[str]:
            out = []
            for req in requests:
                ctx, kwargs = req.args
                ids = self.tokenizer(ctx)["input_ids"]
                full = self.model.generate(
                    ids, max_new_tokens=kwargs.get("max_gen_toks", 128))
                new = full[0][len(ids):]
                text = self.tokenizer.decode(new, skip_special_tokens=True)
                for stop in kwargs.get("until", []):
                    idx = text.find(stop)
                    if idx >= 0:
                        text = text[:idx]
                out.append(text)
            return out

except ImportError:   # lm_eval not installed: core helpers still usable
    BigdlTpuLM = None
