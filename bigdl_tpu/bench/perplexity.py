"""Perplexity evaluation over a token stream.

Equivalent of the reference's perplexity runner
(dev/benchmark/perplexity/ppl.py): strided windows over a long token
sequence, NLL of each window's non-overlapping tail, exp of the mean.
Windows are a fixed size so ONE compiled forward serves the whole run.
"""

from __future__ import annotations

import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np


def perplexity(
    model_or_parts: Any,
    token_ids,                     # [N] long token stream
    window: int = 512,
    stride: int = 256,
    max_windows: Optional[int] = None,
) -> float:
    """Sliding-window perplexity. Accepts a TpuCausalLM or a
    (params, cfg, forward_train) triple."""
    if isinstance(model_or_parts, tuple):
        params, cfg, fwd = model_or_parts
    else:
        m = model_or_parts
        params, cfg = m.params, m.config
        fwd = m.family.forward_train

    ids = np.asarray(token_ids, np.int32).reshape(-1)
    if ids.size < window + 1:
        raise ValueError(f"need > {window + 1} tokens, got {ids.size}")

    from bigdl_tpu.observability.compile_watch import tracked_jit

    logp = tracked_jit("perplexity_logp", lambda p, t: jax.nn.log_softmax(
        fwd(p, cfg, t).astype(jnp.float32), axis=-1), static_argnums=())

    total_nll, total_cnt = 0.0, 0
    starts = range(0, ids.size - window - 1, stride)
    for wi, s in enumerate(starts):
        if max_windows is not None and wi >= max_windows:
            break
        chunk = ids[s:s + window + 1]
        inp = jnp.asarray(chunk[None, :-1])
        ll = np.asarray(logp(params, inp))[0]         # [window, V]
        targets = chunk[1:]
        nll = -ll[np.arange(window), targets]
        # only score the non-overlapping tail (first window scores all)
        score_from = 0 if s == 0 else window - stride
        total_nll += float(nll[score_from:].sum())
        total_cnt += window - score_from
    return math.exp(total_nll / max(total_cnt, 1))
