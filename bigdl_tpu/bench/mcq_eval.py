"""Multiple-choice accuracy evaluation (C-Eval / MMLU style).

Equivalent of the reference's C-Eval runner (reference dev/benchmark/ceval:
per-subject CSVs of question + 4 choices scored by option loglikelihood).
This runner is dataset-agnostic: feed records {"question", "choices",
"answer"} (answer = index or letter) from any source; scoring picks the
choice with the highest length-normalized loglikelihood, sharing
`sequence_loglikelihood` with the lm-eval adapter.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional, Sequence

import numpy as np

from bigdl_tpu.bench.lm_eval_adapter import (context_logprobs,
                                             sequence_loglikelihood)

_LETTERS = "ABCDEFGH"


def _answer_index(ans, n_choices: int) -> int:
    if isinstance(ans, str) and ans.strip().upper() in tuple(_LETTERS):
        idx = _LETTERS.index(ans.strip().upper())
    else:
        idx = int(ans)
    if not 0 <= idx < n_choices:
        raise ValueError(f"answer {ans!r} out of range for {n_choices}")
    return idx


def format_mcq(question: str, choices: Sequence[str]) -> str:
    if len(choices) > len(_LETTERS):
        raise ValueError(
            f"record has {len(choices)} choices; at most {len(_LETTERS)} "
            f"({_LETTERS[0]}-{_LETTERS[-1]}) are supported")
    lines = [question.strip()]
    for i, c in enumerate(choices):
        lines.append(f"{_LETTERS[i]}. {c}")
    lines.append("Answer:")
    return "\n".join(lines)


def evaluate_mcq(
    model: Any,
    tokenizer: Any,
    records: Iterable[Dict[str, Any]],
    max_records: Optional[int] = None,
    length_normalize: bool = True,
) -> Dict[str, Any]:
    """Returns {"accuracy", "n", "per_record": [...]}."""
    n = 0
    correct = 0
    details: List[Dict[str, Any]] = []
    for rec in records:
        if max_records is not None and n >= max_records:
            break
        choices = rec["choices"]
        prompt = format_mcq(rec["question"], choices)
        ctx_ids = tokenizer(prompt)["input_ids"]
        conts = []
        for i in range(len(choices)):
            cont = tokenizer(f" {_LETTERS[i]}",
                             add_special_tokens=False)["input_ids"]
            if not cont:
                raise ValueError(
                    f"tokenizer produced no ids for option letter "
                    f"{_LETTERS[i]!r}; its vocabulary cannot score this "
                    "dataset")
            conts.append(cont)
        if all(len(c) == 1 for c in conts):
            # every option letter is a single token: score all of them
            # from the softmax of ONE context forward
            lp = context_logprobs(model, ctx_ids)
            scores = [float(lp[c[0]]) for c in conts]
        else:
            scores = []
            for cont in conts:
                ll, _ = sequence_loglikelihood(model, ctx_ids, cont)
                scores.append(ll / (len(cont) if length_normalize else 1))
        pred = int(np.argmax(scores))
        truth = _answer_index(rec["answer"], len(choices))
        correct += int(pred == truth)
        n += 1
        details.append({"pred": pred, "answer": truth, "scores": scores})
    return {"accuracy": correct / max(n, 1), "n": n, "per_record": details}


def main() -> None:
    """CLI: python -m bigdl_tpu.bench.mcq_eval --model M --data D.json"""
    import argparse

    from bigdl_tpu.transformers.loader import load_model

    ap = argparse.ArgumentParser()
    ap.add_argument("--model", required=True)
    ap.add_argument("--data", required=True,
                    help="JSON list of {question, choices, answer}")
    ap.add_argument("--low-bit", default="sym_int4")
    ap.add_argument("--max-records", type=int, default=None)
    args = ap.parse_args()

    model, tokenizer = load_model(args.model, low_bit=args.low_bit)
    records = json.load(open(args.data))
    res = evaluate_mcq(model, tokenizer, records,
                       max_records=args.max_records)
    print(json.dumps({"accuracy": res["accuracy"], "n": res["n"]}))


if __name__ == "__main__":
    main()
