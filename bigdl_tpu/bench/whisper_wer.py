"""Whisper WER evaluation harness.

Role of the reference's whisper benchmark (reference
dev/benchmark/whisper/run_whisper.py: librispeech test split through
`AutoModelForSpeechSeq2Seq.from_pretrained(load_in_low_bit=...)`,
word-error-rate via the `evaluate` package, per-sample wall time to
CSV). Differences by design:

- the WER metric is implemented here (plain word-level edit distance) —
  no `evaluate`/`jiwer` dependency, and it is unit-testable offline;
- the dataset is pluggable: `--dataset librispeech` uses HF `datasets`
  when installed (the reference's path), `--dataset dir:<path>` reads
  (x.npy [n_mels, T] precomputed log-mel + x.txt transcript) pairs so a
  WER run needs nothing beyond numpy;
- results stream to CSV the same shape the reference's
  whisper_csv_to_html.py consumes (model, data_type, WER, mean latency).

Run: python -m bigdl_tpu.bench.whisper_wer --model_path <whisper-ckpt>
         --load_in_low_bit sym_int4 --dataset dir:/data/asr_pairs
"""

from __future__ import annotations

import argparse
import csv
import os
import time
from typing import Iterable, List, Tuple

import numpy as np


# ---------------------------------------------------------------------------
# WER metric (word-level Levenshtein, the `evaluate`-package definition)
# ---------------------------------------------------------------------------


def _normalize(text: str) -> List[str]:
    """The reference normalizes with WhisperProcessor's tokenizer
    cleanup; offline we lowercase and strip punctuation to spaces."""
    out = []
    for word in text.lower().split():
        w = "".join(c for c in word if c.isalnum() or c == "'")
        if w:
            out.append(w)
    return out


def wer(references: Iterable[str], hypotheses: Iterable[str]) -> float:
    """Corpus WER: total word edits / total reference words."""
    edits = 0
    ref_words = 0
    for ref, hyp in zip(references, hypotheses):
        r, h = _normalize(ref), _normalize(hyp)
        ref_words += len(r)
        # single-row DP over the shorter dimension
        prev = list(range(len(h) + 1))
        for i, rw in enumerate(r, 1):
            cur = [i] + [0] * len(h)
            for j, hw in enumerate(h, 1):
                cur[j] = min(prev[j] + 1, cur[j - 1] + 1,
                             prev[j - 1] + (rw != hw))
            prev = cur
        edits += prev[-1]
    if ref_words == 0:
        return 0.0
    return edits / ref_words


# ---------------------------------------------------------------------------
# Dataset adapters
# ---------------------------------------------------------------------------


def iter_dir_dataset(path: str) -> Iterable[Tuple[np.ndarray, str]]:
    """(features, transcript) pairs from a directory of x.npy + x.txt.
    .npy files hold [n_mels, T] log-mel features (precomputed)."""
    for name in sorted(os.listdir(path)):
        if not name.endswith(".npy"):
            continue
        stem = name[:-4]
        txt = os.path.join(path, stem + ".txt")
        if not os.path.exists(txt):
            continue
        feats = np.load(os.path.join(path, name))
        with open(txt) as f:
            yield feats, f.read().strip()


def iter_librispeech(data_type: str, n: int, model_path: str):
    """The reference's dataset path; needs `datasets` + a processor."""
    try:
        from datasets import load_dataset
        from transformers import WhisperProcessor
    except ImportError as e:
        raise RuntimeError(
            "librispeech mode needs the `datasets` package and a local "
            "WhisperProcessor; use --dataset dir:<path> for offline "
            "runs") from e
    ds = load_dataset("librispeech_asr", name=data_type,
                      split="test").select(range(n))
    proc = WhisperProcessor.from_pretrained(model_path)
    for sample in ds:
        feats = proc(sample["audio"]["array"],
                     sampling_rate=sample["audio"]["sampling_rate"],
                     return_tensors="np").input_features[0]
        yield feats, sample["text"]


# ---------------------------------------------------------------------------
# Evaluation loop
# ---------------------------------------------------------------------------


def evaluate_wer(model, tokenizer, dataset, max_new_tokens: int = 128,
                 forced_ids: Tuple[int, ...] = ()) -> dict:
    """Transcribe every (features, transcript) pair; returns
    {wer, mean_latency_ms, first_latency_ms, n}."""
    refs: List[str] = []
    hyps: List[str] = []
    times: List[float] = []
    # the reference passes processor.get_decoder_prompt_ids() as
    # forced_decoder_ids [(pos, id), ...]; our generate takes the full
    # forced prefix as decoder_input_ids ([start] + forced)
    prefix = None
    if forced_ids:
        start = model.config.decoder_start_token_id
        prefix = np.asarray(
            [[start] + [t for _, t in sorted(forced_ids)]], np.int32)
    for feats, text in dataset:
        mel = np.asarray(feats, np.float32)[None]      # [1, n_mels, T]
        t0 = time.perf_counter()
        ids = np.asarray(model.generate(
            mel, decoder_input_ids=prefix,
            max_new_tokens=max_new_tokens))[0]
        times.append((time.perf_counter() - t0) * 1e3)
        hyp = tokenizer.decode(ids, skip_special_tokens=True) \
            if tokenizer is not None else " ".join(map(str, ids))
        refs.append(text)
        hyps.append(hyp)
    return {
        "wer": wer(refs, hyps),
        "n": len(refs),
        "first_latency_ms": times[0] if times else 0.0,
        "mean_latency_ms": (sum(times[1:]) / max(len(times) - 1, 1)
                            if len(times) > 1 else
                            (times[0] if times else 0.0)),
    }


def main(argv=None):
    # an explicit CPU request must be authoritative: the ambient TPU
    # plugin prepends itself to jax_platforms regardless of the env
    # var (same guard as bench/accuracy_eval.py and __graft_entry__)
    if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
        import jax

        jax.config.update("jax_platforms", "cpu")
    ap = argparse.ArgumentParser(
        description="Whisper WER + latency (reference run_whisper.py)")
    ap.add_argument("--model_path", required=True)
    ap.add_argument("--load_in_low_bit", default="sym_int4")
    ap.add_argument("--dataset", default="librispeech",
                    help="'librispeech' (needs datasets pkg), "
                    "'dir:<path>' for local .npy/.txt pairs")
    ap.add_argument("--data_type", default="clean")
    ap.add_argument("--n", type=int, default=500)
    ap.add_argument("--max_new_tokens", type=int, default=128)
    ap.add_argument("--save_result", action="store_true")
    ap.add_argument("--out_csv", default="whisper_wer.csv")
    args = ap.parse_args(argv)

    from bigdl_tpu.transformers import AutoModelForSpeechSeq2Seq

    model = AutoModelForSpeechSeq2Seq.from_pretrained(
        args.model_path, load_in_low_bit=args.load_in_low_bit)
    tokenizer = None
    processor = None
    try:
        from transformers import WhisperProcessor

        processor = WhisperProcessor.from_pretrained(args.model_path)
        tokenizer = processor.tokenizer
    except Exception:
        pass

    if args.dataset.startswith("dir:"):
        data = iter_dir_dataset(args.dataset[4:])
    else:
        data = iter_librispeech(args.data_type, args.n, args.model_path)

    # the reference forces <|lang|><|task|> via the processor's decoder
    # prompt ids (run_whisper.py get_decoder_prompt_ids) — without them
    # a multilingual checkpoint may pick the wrong task
    forced = ()
    if processor is not None:
        try:
            forced = tuple(processor.get_decoder_prompt_ids(
                language="en", task="transcribe"))
        except Exception:
            forced = ()

    res = evaluate_wer(model, tokenizer, data,
                       max_new_tokens=args.max_new_tokens,
                       forced_ids=forced)
    if res["n"] == 0:
        raise SystemExit(
            "dataset yielded 0 samples — dir mode needs paired "
            "<stem>.npy (log-mel [n_mels, T]) + <stem>.txt files")
    print(f"WER {res['wer']:.4f} over {res['n']} samples; "
          f"first {res['first_latency_ms']:.0f} ms, "
          f"mean {res['mean_latency_ms']:.0f} ms")
    if args.save_result:
        new = not os.path.exists(args.out_csv)
        with open(args.out_csv, "a", newline="") as f:
            w = csv.writer(f)
            if new:
                w.writerow(["model", "low_bit", "data", "n", "WER",
                            "first_ms", "mean_ms"])
            w.writerow([os.path.basename(args.model_path.rstrip("/")),
                        args.load_in_low_bit, args.dataset, res["n"],
                        f"{res['wer']:.4f}",
                        f"{res['first_latency_ms']:.1f}",
                        f"{res['mean_latency_ms']:.1f}"])
        print(f"appended to {args.out_csv}")
    return res


if __name__ == "__main__":
    main()
