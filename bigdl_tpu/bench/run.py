"""All-in-one benchmark runner: config-driven latency sweeps.

Equivalent of the reference's `dev/benchmark/all-in-one/run.py:66-124`
(YAML config with repo_id matrix, in_out_pairs like "1024-128", test_api
selection, CSV output). Differences: APIs here are the TPU framework's own
paths, and results also land as one JSON line per run for machine
consumption.

Config (YAML or JSON):
    model_paths: [/path/to/llama-2-7b]    # HF dir, low-bit dir, or .gguf
    low_bit: sym_int4
    in_out_pairs: ["32-32", "1024-128"]
    test_api: transformers_int4           # | speculative
    num_trials: 3
    warm_up: 1
Output: CSV-ish stdout table + list of result dicts.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any, Dict, List

import numpy as np

from bigdl_tpu.bench.benchmark_util import BenchmarkWrapper


def load_config(path: str) -> Dict[str, Any]:
    text = open(path).read()
    if path.endswith(".json"):
        return json.loads(text)
    import yaml

    return yaml.safe_load(text)


def run_one(model_path: str, low_bit: str, in_len: int, out_len: int,
            api: str, num_trials: int, warm_up: int) -> Dict[str, Any]:
    from bigdl_tpu.transformers.model import AutoModelForCausalLM

    max_seq = 1 << (in_len + out_len + 8 - 1).bit_length()
    model = AutoModelForCausalLM.from_pretrained(
        model_path, load_in_low_bit=low_bit,
        max_seq=max_seq, speculative=(api == "speculative"))
    bench = BenchmarkWrapper(model)
    vocab = model.config.vocab_size
    prompt = (np.arange(1, in_len + 1, dtype=np.int32) * 977) % vocab

    firsts, rests = [], []
    for trial in range(warm_up + num_trials):
        t0 = time.perf_counter()
        bench.generate(prompt, max_new_tokens=out_len)
        wall = time.perf_counter() - t0
        res = bench.results[-1]
        if trial >= warm_up:
            firsts.append(res.first_cost)
            rests.append(res.rest_cost_mean)
    return {
        "model": model_path,
        "low_bit": low_bit,
        "api": api,
        "in_out": f"{in_len}-{out_len}",
        "first_token_ms": round(min(firsts) * 1e3, 3),
        "rest_token_ms": round(min(rests) * 1e3, 3),
        "peak_memory": bench.results[-1].peak_memory,
    }


def run(config: Dict[str, Any]) -> List[Dict[str, Any]]:
    rows = []
    for model_path in config["model_paths"]:
        for pair in config.get("in_out_pairs", ["32-32"]):
            in_len, out_len = (int(x) for x in pair.split("-"))
            row = run_one(
                model_path,
                config.get("low_bit", "sym_int4"),
                in_len, out_len,
                config.get("test_api", "transformers_int4"),
                int(config.get("num_trials", 3)),
                int(config.get("warm_up", 1)),
            )
            print(json.dumps(row))
            rows.append(row)
    return rows


def main() -> None:
    cfg_path = sys.argv[1] if len(sys.argv) > 1 else "config.yaml"
    rows = run(load_config(cfg_path))
    if rows:
        cols = list(rows[0].keys())
        print(",".join(cols))
        for r in rows:
            print(",".join(str(r[c]) for c in cols))


if __name__ == "__main__":
    main()
