"""All-in-one benchmark runner: config-driven latency sweeps.

Equivalent of the reference's `dev/benchmark/all-in-one/run.py:66-124`
(YAML config with repo_id matrix, in_out_pairs like "1024-128", test_api
selection, CSV output). Differences: APIs here are the TPU framework's own
paths, and results also land as one JSON line per run for machine
consumption.

Config (YAML or JSON):
    model_paths: [/path/to/llama-2-7b]    # HF dir, low-bit dir, or .gguf
    low_bit: sym_int4                     # or a list for a qtype sweep
    in_out_pairs: ["32-32", "1024-128"]
    test_api: transformers_int4           # or a list; see TEST_APIS
    num_trials: 3
    warm_up: 1
Output: CSV-ish stdout table + list of result dicts.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any, Dict, List

import numpy as np

from bigdl_tpu.bench.benchmark_util import BenchmarkWrapper


def load_config(path: str) -> Dict[str, Any]:
    text = open(path).read()
    if path.endswith(".json"):
        return json.loads(text)
    import yaml

    return yaml.safe_load(text)


# test_api matrix (the reference's 20+ all-in-one modes collapse here:
# its matrix is mostly device/OS duplicates of the same four code paths
# — ours are distinct FRAMEWORK paths). Every mode measures
# BenchmarkWrapper-style first/rest latency unless noted.
TEST_APIS = (
    "transformers_int4",      # default generate (merged projections)
    "transformers_low_bit",   # alias; low_bit taken from the config
    "no_merge",               # split-projection layout A/B
    "fp8_kv",                 # e5m2-quantized KV cache
    "int8_kv",                # block-scaled int8 KV cache
    "int4_kv",                # block-scaled int4 KV cache
    "speculative",            # self-speculative decoding
    "serving",                # LLMEngine continuous batching: tokens/s
    "explicit_tp",            # shard_map TP over all local devices
    "gspmd_tp",               # GSPMD-sharded params, same generate
)


def _load(model_path, low_bit, max_seq, api):
    from bigdl_tpu.transformers.model import AutoModelForCausalLM

    kwargs: Dict[str, Any] = {}
    if api == "speculative":
        kwargs["speculative"] = True
    if api in ("no_merge", "explicit_tp"):
        # explicit TP shards the split layout; loading it directly
        # avoids a merge-then-unmerge round trip over every layer
        kwargs["merge_projections"] = False
    if api == "fp8_kv":
        kwargs["kv_cache_dtype"] = "fp8_e5m2"
    elif api.endswith("_kv"):
        kwargs["kv_cache_dtype"] = api[:-3]
    return AutoModelForCausalLM.from_pretrained(
        model_path, load_in_low_bit=low_bit, max_seq=max_seq, **kwargs)


def _bench_generate(model, prompt, out_len, num_trials, warm_up):
    bench = BenchmarkWrapper(model)
    firsts, rests = [], []
    for trial in range(warm_up + num_trials):
        bench.generate(prompt, max_new_tokens=out_len)
        res = bench.results[-1]
        if trial >= warm_up:
            firsts.append(res.first_cost)
            rests.append(res.rest_cost_mean)
    return {"first_token_ms": round(min(firsts) * 1e3, 3),
            "rest_token_ms": round(min(rests) * 1e3, 3),
            "peak_memory": bench.results[-1].peak_memory}


def _bench_serving(model, prompt, out_len, num_trials, warm_up):
    from bigdl_tpu.observability.metrics import MetricsRegistry
    from bigdl_tpu.serving import EngineConfig, LLMEngine, SamplingParams

    batch = 4
    # fresh registry per bench: the output rows report THIS run's
    # TTFT/TPOT distributions, not process-lifetime accumulation
    reg = MetricsRegistry()
    eng = LLMEngine(model, EngineConfig(
        max_batch=batch, max_seq=model.max_seq, prefix_cache_entries=0),
        registry=reg)
    prompts = [((prompt * (i + 3)) % model.config.vocab_size).tolist()
               for i in range(2 * batch)]
    sp = SamplingParams(max_tokens=out_len)
    for _ in range(max(warm_up, 1)):
        eng.generate(prompts[:batch], SamplingParams(max_tokens=2))
    best = 0.0
    for _ in range(max(num_trials, 1)):
        t0 = time.perf_counter()
        outs = eng.generate(prompts, sp)
        wall = time.perf_counter() - t0
        best = max(best, sum(len(o) for o in outs) / wall)
    summary = reg.summary()
    from bigdl_tpu.observability.compile_watch import compile_table

    out = {"serving_tokens_per_s": round(best, 2),
           "batch": batch, "requests": len(prompts),
           "observability": summary,
           "jit_compile_table": compile_table()}
    ttft = summary.get("bigdl_tpu_ttft_seconds")
    if isinstance(ttft, dict):
        out["ttft_p50_ms"] = round(ttft["p50"] * 1e3, 3)
    tpot = summary.get("bigdl_tpu_tpot_seconds")
    if isinstance(tpot, dict):
        out["tpot_p50_ms"] = round(tpot["p50"] * 1e3, 3)
    return out


def _bench_explicit_tp(model, prompt, out_len, num_trials, warm_up):
    import jax

    from jax.sharding import Mesh
    from bigdl_tpu.parallel.tp import shard_params_tp, tp_generate

    n = len(jax.devices())
    mesh = Mesh(np.array(jax.devices()), ("tp",))
    params = shard_params_tp(model.params, mesh)
    best = None
    for trial in range(warm_up + num_trials):
        t0 = time.perf_counter()
        tp_generate(params, model.config, prompt[None], mesh,
                    max_new_tokens=out_len, max_seq=model.max_seq)
        wall = time.perf_counter() - t0
        if trial >= warm_up:
            best = wall if best is None else min(best, wall)
    return {"tp": n, "wall_ms": round(best * 1e3, 3),
            "per_token_ms": round(best * 1e3 / out_len, 3)}


def _bench_gspmd_tp(model, prompt, out_len, num_trials, warm_up):
    import jax

    from bigdl_tpu.parallel import make_mesh, shard_params

    n = len(jax.devices())
    mesh = make_mesh(tp=n)
    with mesh:
        model.params = shard_params(model.params, mesh)
        out = _bench_generate(model, prompt, out_len, num_trials, warm_up)
    out["tp"] = n
    return out


def run_one(model_path: str, low_bit: str, in_len: int, out_len: int,
            api: str, num_trials: int, warm_up: int,
            model=None) -> Dict[str, Any]:
    if api not in TEST_APIS:
        raise ValueError(f"unknown test_api {api!r}; choose from "
                         f"{TEST_APIS}")
    if model is None:
        max_seq = 1 << (in_len + out_len + 8 - 1).bit_length()
        model = _load(model_path, low_bit, max_seq, api)
    vocab = model.config.vocab_size
    prompt = (np.arange(1, in_len + 1, dtype=np.int32) * 977) % vocab

    harness = {"serving": _bench_serving,
               "explicit_tp": _bench_explicit_tp,
               "gspmd_tp": _bench_gspmd_tp}.get(api, _bench_generate)
    metrics = harness(model, prompt, out_len, num_trials, warm_up)
    if api == "speculative":
        # the spec drivers publish acceptance to the default registry
        # (speculative._spec_observe); surface it in the row
        from bigdl_tpu.observability.metrics import default_registry

        summary = default_registry().summary()
        acc = {k: v for k, v in summary.items()
               if k.startswith(("bigdl_tpu_spec_accept_ratio",
                                "bigdl_tpu_spec_tokens_total"))}
        if acc:
            metrics["observability"] = acc
    if "jit_compile_table" not in metrics:
        from bigdl_tpu.observability.compile_watch import compile_table

        metrics["jit_compile_table"] = compile_table()
    return {
        "model": model_path,
        "low_bit": low_bit,
        "api": api,
        "in_out": f"{in_len}-{out_len}",
        **metrics,
    }


def run(config: Dict[str, Any]) -> List[Dict[str, Any]]:
    rows = []
    apis = config.get("test_api", "transformers_int4")
    if isinstance(apis, str):
        apis = [apis]
    low_bits = config.get("low_bit", "sym_int4")
    if isinstance(low_bits, str):
        low_bits = [low_bits]
    bad = [a for a in apis if a not in TEST_APIS]
    if bad:
        # fail BEFORE any model load: a typo'd api must not cost a 7B
        # quantize inside a scarce tunnel window
        raise ValueError(f"unknown test_api {bad}; choose from {TEST_APIS}")
    pairs = [tuple(int(x) for x in p.split("-"))
             for p in config.get("in_out_pairs", ["32-32"])]
    # one load per (model, api, low_bit) cell: in_out pairs reuse the
    # model (a 7B re-quantize per pair would double tunnel-window cost)
    max_seq = 1 << (max(i + o for i, o in pairs) + 8 - 1).bit_length()
    for model_path in config["model_paths"]:
        for api in apis:
            for low_bit in low_bits:
                model = _load(model_path, low_bit, max_seq, api)
                for in_len, out_len in pairs:
                    row = run_one(
                        model_path, low_bit, in_len, out_len, api,
                        int(config.get("num_trials", 3)),
                        int(config.get("warm_up", 1)),
                        model=model,
                    )
                    print(json.dumps(row))
                    rows.append(row)
    return rows


def main() -> None:
    cfg_path = sys.argv[1] if len(sys.argv) > 1 else "config.yaml"
    rows = run(load_config(cfg_path))
    if rows:
        # different apis report different metrics; the CSV carries the
        # column union with blanks
        cols = list(dict.fromkeys(c for r in rows for c in r))
        print(",".join(cols))
        for r in rows:
            print(",".join(str(r.get(c, "")) for c in cols))


if __name__ == "__main__":
    main()
