"""Per-token generation timing: the BenchmarkWrapper equivalent.

The reference forks HF's generate to time every token
(dev/benchmark/benchmark_util.py:489-520 `BenchmarkWrapper`, metrics
`first_cost`/`rest_cost_mean`/`peak_memory` at :2447-2476, injected into
serving via env in transformers/loader.py:43-77). Here the model already
owns its generate loop, so the wrapper simply drives it with a
GenerationStats collector and reads device memory stats from JAX.

Note on TPU timing: a tunneled/remote device pays a fixed dispatch+readback
cost per host sync; `rest_cost_mean` measured around a host-step loop
includes it. For kernel-true numbers use `timed_decode` (K steps inside one
jit, differenced) — the same technique bench.py uses.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from bigdl_tpu.generation import GenerationStats


def device_peak_memory() -> Optional[int]:
    """Peak device memory in bytes (None if the backend has no stats)."""
    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats:
            return int(stats.get("peak_bytes_in_use",
                                 stats.get("bytes_in_use", 0)))
    except Exception:
        pass
    return None


@dataclasses.dataclass
class BenchmarkResult:
    first_cost: float              # seconds, prompt -> first token
    rest_cost_mean: float          # seconds per subsequent token
    n_tokens: int
    peak_memory: Optional[int]     # bytes

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class BenchmarkWrapper:
    """Wrap a TpuCausalLM: `.generate()` passes through, timings recorded.

    >>> m = BenchmarkWrapper(model)
    >>> out = m.generate(ids, max_new_tokens=32)
    >>> m.results[-1].first_cost, m.results[-1].rest_cost_mean
    """

    def __init__(self, model: Any, do_print: bool = False):
        self.model = model
        self.do_print = do_print
        self.results: List[BenchmarkResult] = []

    def __getattr__(self, name):
        return getattr(self.model, name)

    def generate(self, input_ids, **kw):
        stats = GenerationStats()
        kw["stats"] = stats
        out = self.model.generate(input_ids, **kw)
        n = len(stats.rest_token_s) + 1
        res = BenchmarkResult(
            first_cost=stats.first_token_s,
            rest_cost_mean=stats.rest_cost_mean,
            n_tokens=n,
            peak_memory=device_peak_memory(),
        )
        self.results.append(res)
        if self.do_print:
            pm = (f"{res.peak_memory / 2**30:.2f} GB"
                  if res.peak_memory else "n/a")
            print(f"=========== BENCHMARK: first={res.first_cost*1e3:.1f} ms "
                  f"rest_mean={res.rest_cost_mean*1e3:.2f} ms "
                  f"tokens={res.n_tokens} peak_mem={pm} ===========")
        return out
