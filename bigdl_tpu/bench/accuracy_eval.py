"""Accuracy evidence for the quant formats: perplexity deltas on a model
trained in-repo.

The reference validates its formats with perplexity / lm-eval runs over
public checkpoints (reference dev/benchmark/perplexity/ppl.py,
harness/bigdl_llm.py:38). This environment has no network and ships no
pretrained weights, so random-weight logits KL would be the only proxy —
except a proxy is unnecessary: this runner TRAINS a small byte-level
llama on real text (the Python standard library's source, ~5 MB) with
the in-repo training stack, exports it as an HF checkpoint, and then
measures held-out perplexity through the PUBLIC loading path
(`from_pretrained(load_in_low_bit=..., imatrix=...)`) for every format.
Degradation ordering and imatrix gains measured this way are real model
behavior, not random-matrix artifacts.

Run:  python -m bigdl_tpu.bench.accuracy_eval --steps 800 --out ACCURACY.md
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sysconfig
import tempfile
import time
from typing import Dict, List

import numpy as np

VOCAB = 256      # byte-level


def build_corpus(max_bytes: int = 6_000_000) -> np.ndarray:
    """Concatenate stdlib .py sources into one byte stream (real,
    structured text that is present on every machine)."""
    lib = sysconfig.get_paths()["stdlib"]
    files = sorted(glob.glob(os.path.join(lib, "*.py")))
    files += sorted(glob.glob(os.path.join(lib, "*", "*.py")))
    chunks: List[bytes] = []
    total = 0
    for f in files:
        try:
            b = open(f, "rb").read()
        except OSError:
            continue
        chunks.append(b)
        total += len(b)
        if total >= max_bytes:
            break
    return np.frombuffer(b"".join(chunks), np.uint8).astype(np.int32)


def model_config(size: str = "small"):
    from bigdl_tpu.models.llama import LlamaConfig

    if size == "medium":
        # ~27M params: 2-bit formats quantize 512-wide blocks with
        # 256-value superblocks intact, and per-channel statistics are
        # estimated over 4x more channels (VERDICT r3 #9)
        return LlamaConfig(
            vocab_size=VOCAB, hidden_size=512, intermediate_size=1408,
            num_hidden_layers=8, num_attention_heads=8,
            num_key_value_heads=8, max_position_embeddings=512,
            rms_norm_eps=1e-5, rope_theta=10000.0,
            tie_word_embeddings=False, hidden_act="silu")
    return LlamaConfig(
        vocab_size=VOCAB, hidden_size=256, intermediate_size=512,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=512, rms_norm_eps=1e-5,
        rope_theta=10000.0, tie_word_embeddings=False, hidden_act="silu")


def load_hf_params(ckpt: str, cfg):
    """Inverse of `export_hf`: HF safetensors -> f32 training pytree
    (checkpoint-resume for longer training runs)."""
    import jax.numpy as jnp
    from safetensors.numpy import load_file

    t = load_file(os.path.join(ckpt, "model.safetensors"))

    def get(name, transpose=False):
        a = t[name]
        return jnp.asarray(a.T if transpose else a, jnp.float32)

    per = {"q_proj": "self_attn.q_proj.weight",
           "k_proj": "self_attn.k_proj.weight",
           "v_proj": "self_attn.v_proj.weight",
           "o_proj": "self_attn.o_proj.weight",
           "gate_proj": "mlp.gate_proj.weight",
           "up_proj": "mlp.up_proj.weight",
           "down_proj": "mlp.down_proj.weight"}
    layers = {}
    for key, hf in per.items():
        layers[key] = jnp.stack([
            get(f"model.layers.{i}.{hf}", transpose=True)
            for i in range(cfg.num_hidden_layers)])
    for key, hf in (("input_layernorm", "input_layernorm.weight"),
                    ("post_attention_layernorm",
                     "post_attention_layernorm.weight")):
        layers[key] = jnp.stack([get(f"model.layers.{i}.{hf}")
                                 for i in range(cfg.num_hidden_layers)])
    return {"embed_tokens": get("model.embed_tokens.weight"),
            "layers": layers,
            "norm": get("model.norm.weight"),
            "lm_head": get("lm_head.weight", transpose=True)}


def train(cfg, tokens: np.ndarray, steps: int, batch: int = 8,
          seq: int = 256, lr: float = 3e-3, seed: int = 0,
          log_every: int = 100, init_params=None,
          lr_offset_steps: int = 0):
    """Train with the in-repo stack (training.py), from random init or
    a resumed checkpoint pytree. On resume pass `lr_offset_steps` (the
    steps already taken) so the cosine schedule CONTINUES from where the
    original run left off instead of re-peaking on converged weights;
    the data RNG must also be re-seeded by the caller so the new steps
    draw fresh batches, not a replay."""
    import jax.numpy as jnp
    import optax

    from bigdl_tpu.models import llama as M
    from bigdl_tpu.training import make_train_step
    from bigdl_tpu.utils.testing import random_llama_params

    params = init_params if init_params is not None else \
        random_llama_params(cfg, qtype=None, seed=seed,
                            compute_dtype=jnp.float32)
    base_sched = optax.cosine_decay_schedule(
        lr, lr_offset_steps + steps, alpha=0.1)

    def sched(count):
        return base_sched(count + lr_offset_steps)

    opt = optax.adamw(sched, weight_decay=0.01)
    step = make_train_step(
        lambda p, c, t: M.forward_train(p, c, t,
                                        compute_dtype=jnp.float32),
        cfg, opt)
    opt_state = opt.init(params)

    rng = np.random.default_rng(seed)
    n_windows = tokens.size - seq - 1
    t0 = time.time()
    loss = None
    for i in range(steps):
        starts = rng.integers(0, n_windows, size=batch)
        batch_ids = np.stack([tokens[s:s + seq] for s in starts])
        params, opt_state, loss = step(
            params, opt_state, {"input_ids": jnp.asarray(batch_ids)})
        if (i + 1) % log_every == 0:
            print(f"  step {i + 1}/{steps}  loss {float(loss):.3f}  "
                  f"({(time.time() - t0) / (i + 1):.2f}s/step)",
                  flush=True)
    return params, float(loss)


def export_hf(params, cfg, outdir: str) -> None:
    """Trained pytree -> HF-named llama checkpoint (safetensors)."""
    from safetensors.numpy import save_file

    t: Dict[str, np.ndarray] = {}

    def put(name, arr, transpose=False):
        a = np.asarray(arr, np.float32)
        t[name] = np.ascontiguousarray(a.T if transpose else a)

    put("model.embed_tokens.weight", params["embed_tokens"])
    put("model.norm.weight", params["norm"])
    put("lm_head.weight", params["lm_head"], transpose=True)
    lp = params["layers"]
    per = {"self_attn.q_proj.weight": "q_proj",
           "self_attn.k_proj.weight": "k_proj",
           "self_attn.v_proj.weight": "v_proj",
           "self_attn.o_proj.weight": "o_proj",
           "mlp.gate_proj.weight": "gate_proj",
           "mlp.up_proj.weight": "up_proj",
           "mlp.down_proj.weight": "down_proj"}
    for i in range(cfg.num_hidden_layers):
        for hf_name, key in per.items():
            put(f"model.layers.{i}.{hf_name}", lp[key][i], transpose=True)
        put(f"model.layers.{i}.input_layernorm.weight",
            lp["input_layernorm"][i])
        put(f"model.layers.{i}.post_attention_layernorm.weight",
            lp["post_attention_layernorm"][i])

    os.makedirs(outdir, exist_ok=True)
    save_file(t, os.path.join(outdir, "model.safetensors"))
    with open(os.path.join(outdir, "config.json"), "w") as f:
        json.dump({
            "architectures": ["LlamaForCausalLM"],
            "vocab_size": cfg.vocab_size,
            "hidden_size": cfg.hidden_size,
            "intermediate_size": cfg.intermediate_size,
            "num_hidden_layers": cfg.num_hidden_layers,
            "num_attention_heads": cfg.num_attention_heads,
            "num_key_value_heads": cfg.num_key_value_heads,
            "max_position_embeddings": cfg.max_position_embeddings,
            "rms_norm_eps": cfg.rms_norm_eps,
            "rope_theta": cfg.rope_theta,
            "tie_word_embeddings": False,
            "hidden_act": "silu",
            "torch_dtype": "float32",
        }, f)


# (format, use_imatrix) rows; bpw from ops/quant.py block layouts
FORMATS = [
    ("bf16", False), ("sym_int8", False), ("fp8_e4m3", False),
    ("sym_int4", False), ("asym_int4", False), ("nf4", False),
    ("fp4", False),
    # mixed policies (per-tensor MSE pick) next to their base formats
    # so the pick's value is visible (VERDICT r4 weak #6)
    ("mixed_fp4", False), ("mixed_fp8", False),
    ("q2_k", False), ("q2_k", True),
    ("iq2_xxs", False), ("iq2_xxs", True),
    ("iq2_xs", False), ("iq2_xs", True),
    ("iq1_s", False), ("iq1_s", True),
    ("iq1_m", False), ("iq1_m", True),
]


def evaluate(ckpt_dir: str, heldout: np.ndarray, imatrix, window=256,
             stride=128, max_windows=40):
    import jax.numpy as jnp

    from bigdl_tpu.bench.perplexity import perplexity
    from bigdl_tpu.models import llama as M
    from bigdl_tpu.transformers.model import AutoModelForCausalLM

    rows = []
    for qt, use_im in FORMATS:
        m = AutoModelForCausalLM.from_pretrained(
            ckpt_dir,
            load_in_low_bit=None if qt == "bf16" else qt,
            imatrix=imatrix if use_im else None)
        ppl = perplexity(
            (m.params, m.config,
             lambda p, c, t: M.forward_train(p, c, t,
                                             compute_dtype=jnp.float32)),
            heldout, window=window, stride=stride, max_windows=max_windows)
        label = qt + ("+imatrix" if use_im else "")
        rows.append((label, ppl))
        print(f"  {label:18s} ppl {ppl:8.3f}", flush=True)
    return rows


def write_report(rows, out_path: str, meta: Dict) -> None:
    base = dict(rows)["bf16"]
    lines = [
        "# ACCURACY — quant-format perplexity on an in-repo-trained model",
        "",
        "No pretrained checkpoints exist in this offline environment, so "
        "the model under test is a byte-level llama TRAINED HERE "
        f"({meta['params']} params, {meta['steps']} steps, "
        f"{meta['train_tokens']} train bytes of Python-stdlib source; "
        f"final train loss {meta['loss']:.3f}). Perplexity is measured "
        "on held-out stdlib files through the public "
        "`from_pretrained(load_in_low_bit=...)` path, so every number "
        "covers conversion + runtime dequant end to end. Methodology "
        "mirrors the reference's ppl runner "
        "(dev/benchmark/perplexity/ppl.py); deltas (not absolutes) are "
        "the comparable quantity (the float baseline is bf16 — the runtime's "
        "production compute/storage float on TPU). bpw = bits per weight.",
        "",
        "| format | bpw | perplexity | Δ vs bf16 |",
        "|---|---|---|---|",
    ]
    bpw = {"bf16": 16, "sym_int8": 8.5, "fp8_e4m3": 8.5, "sym_int4": 4.5,
           "asym_int4": 5.0, "nf4": 4.5, "fp4": 4.5, "mixed_fp4": 4.5,
           "mixed_fp8": 8.5, "q2_k": 2.625,
           "iq2_xxs": 2.19, "iq2_xs": 2.19, "iq1_s": 1.19, "iq1_m": 1.44}
    for label, ppl in rows:
        fmt = label.split("+")[0]
        delta = (ppl / base - 1.0) * 100
        lines.append(f"| {label} | {bpw[fmt]} | {ppl:.3f} | "
                     f"{'+' if delta >= 0 else ''}{delta:.1f}% |")
    lines += [
        "",
        f"_Generated by `python -m bigdl_tpu.bench.accuracy_eval` "
        f"(window {meta['window']}, stride {meta['stride']}, "
        f"{meta['max_windows']} windows, heldout {meta['heldout']} bytes)._",
    ]
    with open(out_path, "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"wrote {out_path}")


def main(argv=None):
    # a CPU request in the env must be authoritative: the ambient TPU
    # plugin prepends itself to jax_platforms regardless of the env var,
    # and a wedged tunnel then hangs backend init (same guard as
    # __graft_entry__.py)
    if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
        import jax

        jax.config.update("jax_platforms", "cpu")
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=800)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument("--out", default="ACCURACY.md")
    ap.add_argument("--max-windows", type=int, default=40)
    ap.add_argument("--size", choices=("small", "medium"), default="small",
                    help="testbed size: small ~2.8M params, medium ~27M")
    ap.add_argument("--calib-windows", type=int, default=64,
                    help="calibration windows of --seq bytes for the "
                    "imatrix (r3's 8 windows = 2KB gave noisy "
                    "second moments at ultra-low bpw)")
    ap.add_argument("--ckpt-dir", default=None,
                    help="reuse a previously trained checkpoint dir")
    ap.add_argument("--train-more", type=int, default=0,
                    help="resume from --ckpt-dir and train this many "
                    "extra steps before evaluating (exports to a new "
                    "dir; requires --ckpt-dir)")
    args = ap.parse_args(argv)

    corpus = build_corpus()
    split = int(corpus.size * 0.9)
    train_tok, held = corpus[:split], corpus[split:]
    print(f"corpus {corpus.size} bytes ({split} train / "
          f"{held.size} heldout)")

    cfg = model_config(args.size)
    steps = args.steps
    if args.train_more:
        if not (args.ckpt_dir and os.path.exists(
                os.path.join(args.ckpt_dir, "model.safetensors"))):
            raise ValueError(
                "--train-more needs an existing --ckpt-dir checkpoint "
                f"(got {args.ckpt_dir!r}) — refusing to silently train "
                "from scratch")
        meta_p = os.path.join(args.ckpt_dir, "train_meta.json")
        prev = json.load(open(meta_p)) if os.path.exists(meta_p) else {}
        prev_steps = prev.get("steps", 0)
        print(f"resuming {args.ckpt_dir} "
              f"(+{args.train_more} steps after {prev_steps}) ...")
        params, loss = train(
            cfg, train_tok, args.train_more, args.batch, args.seq,
            # fresh data draws + continued LR schedule, not a replay
            seed=prev_steps + 1,
            lr_offset_steps=prev_steps,
            init_params=load_hf_params(args.ckpt_dir, cfg))
        steps = prev_steps + args.train_more
        ckpt = tempfile.mkdtemp(prefix="acc_eval_")
        export_hf(params, cfg, ckpt)
        json.dump({"loss": loss, "steps": steps},
                  open(os.path.join(ckpt, "train_meta.json"), "w"))
        print(f"exported checkpoint to {ckpt}")
    elif args.ckpt_dir and os.path.exists(
            os.path.join(args.ckpt_dir, "model.safetensors")):
        ckpt = args.ckpt_dir
        meta_p = os.path.join(ckpt, "train_meta.json")
        loss = float("nan")
        if os.path.exists(meta_p):
            m = json.load(open(meta_p))
            loss, steps = m.get("loss", loss), m.get("steps", steps)
        print(f"reusing checkpoint {ckpt}")
    else:
        print(f"training {args.steps} steps ...")
        params, loss = train(cfg, train_tok, args.steps, args.batch,
                             args.seq)
        ckpt = args.ckpt_dir or tempfile.mkdtemp(prefix="acc_eval_")
        export_hf(params, cfg, ckpt)
        json.dump({"loss": loss, "steps": args.steps},
                  open(os.path.join(ckpt, "train_meta.json"), "w"))
        print(f"exported checkpoint to {ckpt}")

    # imatrix from a slice of TRAIN data (calibration must not touch
    # the heldout split)
    from bigdl_tpu.imatrix import collect_imatrix
    from bigdl_tpu.transformers.model import AutoModelForCausalLM

    m_f = AutoModelForCausalLM.from_pretrained(ckpt)
    import jax.numpy as jnp

    # RANDOM windows across the whole train split: r3 calibrated on the
    # corpus PREFIX (one stdlib file), and a synthetic study showed mere
    # estimator noise does NOT flip imatrix from helping to hurting —
    # distribution mismatch between the calibration slice and the
    # heldout text is the live hypothesis for the iq1_s anomaly
    nw = args.calib_windows
    if train_tok.size < args.seq:
        raise ValueError(
            f"train split ({train_tok.size} tokens) smaller than one "
            f"calibration window (--seq {args.seq})")
    crng = np.random.default_rng(12345)
    starts = crng.integers(0, train_tok.size - args.seq + 1, size=nw)
    calib = np.stack([train_tok[s:s + args.seq] for s in starts])
    im = collect_imatrix(m_f.params, m_f.config, calib,
                         compute_dtype=jnp.float32)
    print(f"imatrix collected over {calib.size} calibration bytes "
          f"({nw} random windows)")

    rows = evaluate(ckpt, held, im, max_windows=args.max_windows)
    import jax

    n_params = sum(int(np.prod(p.shape)) for p in
                   jax.tree.leaves(m_f.params) if hasattr(p, "shape"))
    meta = dict(steps=steps, loss=loss,
                params=f"{n_params / 1e6:.1f}M", train_tokens=split,
                window=256, stride=128, max_windows=args.max_windows,
                heldout=held.size)
    write_report(rows, args.out, meta)


if __name__ == "__main__":
    main()
