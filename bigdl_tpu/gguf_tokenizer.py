"""Tokenizer reconstructed from GGUF vocabulary metadata.

Equivalent of the reference's tokenizer reconstruction for GGUF loads
(reference transformers/gguf/api.py builds an HF tokenizer from
tokenizer.ggml.* keys). Here a self-contained tokenizer is built from the
same keys — no sentencepiece/transformers dependency:

- decode: exact (sentencepiece ▁ convention + <0xNN> byte tokens)
- encode: longest-match greedy over the vocab for llama-style sentencepiece
  vocabs, with byte-token fallback for unknown bytes. Greedy matching is
  not bit-identical to sentencepiece's unigram segmentation for every
  string, but round-trips text exactly (encode -> decode == input) and
  produces valid ids for generation.
"""

from __future__ import annotations

from typing import Dict, List, Optional

_SP_SPACE = "▁"   # sentencepiece's meta-space


# GGUF tokenizer.ggml.token_type values (llama.cpp llama_token_type)
_TYPE_UNKNOWN, _TYPE_CONTROL, _TYPE_BYTE = 2, 3, 6


class GGUFTokenizer:
    def __init__(self, tokens: List[str],
                 bos_token_id: Optional[int] = None,
                 eos_token_id: Optional[int] = None,
                 add_bos: bool = True,
                 token_type: Optional[List[int]] = None):
        self.tokens = list(tokens)
        self.unk_token_id = (tokens.index("<unk>")
                             if "<unk>" in tokens else None)
        self.bos_token_id = bos_token_id
        self.eos_token_id = eos_token_id
        self.add_bos = add_bos and bos_token_id is not None
        types = list(token_type) if token_type is not None else None

        def is_plain(i: int, t: str) -> bool:
            """Token eligible for greedy TEXT matching. Byte and control
            tokens must not match their literal spelling in user text
            (a prompt containing \"</s>\" or \"<0x41>\" means those
            characters, not the special token)."""
            if types is not None and i < len(types):
                return types[i] not in (_TYPE_UNKNOWN, _TYPE_CONTROL,
                                        _TYPE_BYTE)
            # no type metadata: fall back on the spelling conventions
            if len(t) == 6 and t.startswith("<0x") and t.endswith(">"):
                return False
            return t not in ("<s>", "</s>", "<unk>", "<pad>")

        self._index: Dict[str, int] = {}
        for i, t in enumerate(self.tokens):
            if is_plain(i, t):
                self._index.setdefault(t, i)
        self._byte_ids: Dict[int, int] = {}
        for i, t in enumerate(self.tokens):
            if len(t) == 6 and t.startswith("<0x") and t.endswith(">"):
                try:
                    self._byte_ids[int(t[3:5], 16)] = i
                except ValueError:
                    pass
        self._max_len = max((len(t) for t in self.tokens), default=1)

    @classmethod
    def from_tokenizer_info(cls, info: Dict) -> "GGUFTokenizer":
        """Build from GGUFFile.tokenizer_info(). Sentencepiece vocabs only
        ("llama"/"spm"); BPE ("gpt2") vocabs would silently mis-tokenize
        under the ▁ convention, so they are rejected."""
        if not info.get("tokens"):
            raise ValueError("GGUF file carries no tokenizer vocabulary")
        model = info.get("model")
        if model not in (None, "llama", "spm"):
            raise ValueError(
                f"GGUF tokenizer model {model!r} is not sentencepiece; "
                "use the original HF tokenizer")
        return cls(info["tokens"], info.get("bos_token_id"),
                   info.get("eos_token_id"),
                   token_type=info.get("token_type"))

    # -- encode -------------------------------------------------------------

    def encode(self, text: str, add_special_tokens: bool = True) -> List[int]:
        norm = _SP_SPACE + text.replace(" ", _SP_SPACE)
        ids: List[int] = []
        i = 0
        while i < len(norm):
            match = None
            for ln in range(min(self._max_len, len(norm) - i), 0, -1):
                cand = self._index.get(norm[i:i + ln])
                if cand is not None:
                    match = (cand, ln)
                    break
            if match is not None:
                ids.append(match[0])
                i += match[1]
            else:
                # byte fallback — all-or-nothing per character: a partial
                # byte emission would decode to mojibake, so any missing
                # byte token downgrades the whole character to unk
                bs = norm[i].encode("utf-8")
                if all(b in self._byte_ids for b in bs):
                    ids.extend(self._byte_ids[b] for b in bs)
                elif self.unk_token_id is not None:
                    ids.append(self.unk_token_id)
                i += 1
        if add_special_tokens and self.add_bos:
            ids = [self.bos_token_id] + ids
        return ids

    def __call__(self, text: str, add_special_tokens: bool = True) -> Dict:
        return {"input_ids": self.encode(text, add_special_tokens)}

    # -- decode -------------------------------------------------------------

    def decode(self, ids, skip_special_tokens: bool = True) -> str:
        out: List[bytes] = []
        for i in ids:
            i = int(i)
            if not 0 <= i < len(self.tokens):
                continue
            if skip_special_tokens and i in (self.bos_token_id,
                                             self.eos_token_id):
                continue
            t = self.tokens[i]
            if len(t) == 6 and t.startswith("<0x") and t.endswith(">"):
                try:
                    out.append(bytes([int(t[3:5], 16)]))
                    continue
                except ValueError:
                    pass
            out.append(t.encode("utf-8"))
        text = b"".join(out).decode("utf-8", errors="replace")
        text = text.replace(_SP_SPACE, " ")
        # drop exactly the ONE meta-space encode() prepends — lstrip would
        # also eat genuine leading whitespace from the original text
        return text[1:] if text.startswith(" ") else text
