"""Host-side page accounting: refcounted page pool + radix prefix tree.

Pure scheduling state — no JAX anywhere. The engine owns ONE
:class:`PagePool` (physical pages of the device arena in
``ops/paged.py``) and, when prefix sharing is on, ONE :class:`RadixCache`
mapping prompt-token chunks to the pages that hold their KV.

Refcount discipline
-------------------
Every mapping of a physical page holds one reference: a slot's block-table
row, and each radix-tree node. A page is returned to the free list exactly
when its count reaches zero; decref below zero raises (the
refcount-never-negative invariant is load-bearing — a double free would
hand the same page to two sequences and silently corrupt both).

Copy-on-write contract
----------------------
The pool only *counts*; the engine decides. Before a slot appends into a
page with refcount > 1 it must allocate a fresh page, copy the old one
(``cow_copy_pages``), swap its block-table entry, and decref the shared
page — the shared copy is never written, so concurrent readers (the radix
tree, other slots) stay byte-identical.

Radix tree
----------
Nodes are keyed by **page-sized token chunks** so one node == one page.
Lookup is longest-prefix: it descends full-page nodes only and returns the
matched pages without touching refcounts (the engine increfs when it
commits the admission — match is a pure read plus an LRU stamp). The
prompt's partial tail chunk IS inserted (as a terminal "partial" node) so
the tail page survives eviction and the writer's next append sees a shared
page — that append is what exercises CoW. Eviction frees only leaves whose
page the tree alone still references (external refcount zero), LRU-first.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence, Tuple

from bigdl_tpu.ops.paged import NULL_PAGE


class PagePool:
    """Refcounts + free list over ``num_pages`` physical pages. Page 0
    (``NULL_PAGE``) is pinned: never allocated, never freed — it is the
    arena's write sink for padded positions."""

    def __init__(self, num_pages: int, page_size: int) -> None:
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is reserved), "
                f"got {num_pages}")
        self.num_pages = num_pages
        self.page_size = page_size
        # LIFO free list: recently-freed pages are re-handed first (their
        # arena tiles are the likeliest still resident in cache hierarchy)
        self._free: List[int] = list(range(1, num_pages))
        self._ref = [0] * num_pages
        self._ref[NULL_PAGE] = 1     # pinned
        self.exhausted_total = 0     # alloc failures (observability)
        # live-migration accounting (serving/engine.py export/import)
        self.exported_pages_total = 0
        self.imported_pages_total = 0
        self.import_exhausted_total = 0

    # -- allocation ---------------------------------------------------------

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` fresh pages at refcount 1, or None (all-or-nothing — a
        partial grant would deadlock two admissions against each other)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            self.exhausted_total += 1
            return None
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._ref[p] = 1
        return pages

    def incref(self, page: int) -> int:
        if page == NULL_PAGE:
            return self._ref[NULL_PAGE]
        if self._ref[page] <= 0:
            raise RuntimeError(
                f"incref of free page {page} (use-after-free)")
        self._ref[page] += 1
        return self._ref[page]

    def decref(self, page: int) -> int:
        """Drop one reference; frees the page at zero. Never goes
        negative — that would mean a double release, which is how two
        sequences end up sharing a 'private' page."""
        if page == NULL_PAGE:
            return self._ref[NULL_PAGE]
        if self._ref[page] <= 0:
            raise RuntimeError(
                f"decref of page {page} with refcount "
                f"{self._ref[page]} (double free)")
        self._ref[page] -= 1
        if self._ref[page] == 0:
            self._free.append(page)
        return self._ref[page]

    def refcount(self, page: int) -> int:
        return self._ref[page]

    # -- live migration -----------------------------------------------------

    def export_pages(self, pages: Sequence[int]) -> dict:
        """Validate that every page in a departing sequence's block
        table is LIVE and return the wire manifest for its KV transfer
        (serving/engine.export_sequence gathers the arena bytes; the
        pool only vouches for the mapping). Raises ``RuntimeError`` on
        a free or null page — exporting a page nobody maps would ship
        stale KV and resume the sequence on garbage."""
        for p in pages:
            if p == NULL_PAGE:
                raise RuntimeError(
                    "export of the pinned null page (block table holds "
                    "an unwritten entry)")
            if self._ref[p] <= 0:
                raise RuntimeError(
                    f"export of free page {p} (use-after-free)")
        self.exported_pages_total += len(pages)
        return {"pages": [int(p) for p in pages],
                "page_size": self.page_size,
                "num_pages": len(pages)}

    def import_pages(self, n: int) -> Optional[List[int]]:
        """All-or-nothing allocation of ``n`` fresh pages (refcount 1)
        for an arriving migrated sequence, or None when the arena
        cannot hold it — the sender keeps ownership and falls back
        (local resume / journal replay). Counted separately from
        admission exhaustion so capacity planning can tell organic
        pressure from migration pressure."""
        pages = self.alloc(n)
        if pages is None:
            # alloc() bumped exhausted_total; reattribute the failure
            self.exhausted_total -= 1
            self.import_exhausted_total += 1
            return None
        self.imported_pages_total += len(pages)
        return pages

    # -- accounting ---------------------------------------------------------

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_used(self) -> int:
        """Allocated pages (excludes the pinned null page)."""
        return (self.num_pages - 1) - len(self._free)

    @property
    def num_shared(self) -> int:
        """Pages mapped more than once (refcount >= 2)."""
        return sum(1 for p, r in enumerate(self._ref)
                   if p != NULL_PAGE and r >= 2)

    def publish(self, registry) -> None:
        """Set the ``bigdl_tpu_kv_pages_{used,shared,free}`` gauges.
        Best-effort — metric export never gates scheduling."""
        try:
            registry.gauge(
                "bigdl_tpu_kv_pages_used",
                "KV arena pages currently mapped by at least one "
                "sequence or radix node").set(float(self.num_used))
            registry.gauge(
                "bigdl_tpu_kv_pages_shared",
                "KV arena pages mapped more than once "
                "(copy-on-write candidates)").set(float(self.num_shared))
            registry.gauge(
                "bigdl_tpu_kv_pages_free",
                "KV arena pages on the free list").set(float(self.num_free))
        except Exception:
            pass


class _RadixNode:
    __slots__ = ("tokens", "page", "children", "parent", "partial", "tick")

    def __init__(self, tokens: Tuple[int, ...], page: int,
                 parent: Optional["_RadixNode"], partial: bool,
                 tick: int) -> None:
        self.tokens = tokens
        self.page = page
        self.children: Dict[Tuple[int, ...], "_RadixNode"] = {}
        self.parent = parent
        self.partial = partial
        self.tick = tick


class RadixCache:
    """Prompt-prefix radix tree over page-sized token chunks.

    The tree holds ONE reference on every node's page (taken at insert,
    released at evict/drop). ``match`` never mutates refcounts; callers
    incref the returned pages themselves when they commit."""

    def __init__(self, pool: PagePool) -> None:
        self.pool = pool
        self._root = _RadixNode((), NULL_PAGE, None, False, 0)
        self._clock = itertools.count(1)
        self.num_nodes = 0
        # host-visible counters (the engine mirrors them into metrics)
        self.lookups = 0
        self.hits = 0
        self.lookup_tokens = 0
        self.hit_tokens = 0

    def _chunks(self, tokens: Sequence[int]):
        ps = self.pool.page_size
        for i in range(0, len(tokens), ps):
            yield tuple(tokens[i:i + ps])

    # -- insert -------------------------------------------------------------

    def insert(self, tokens: Sequence[int], pages: Sequence[int]) -> int:
        """Record a prompt's pages; returns how many NEW nodes were
        created (each new node increfs its page). Existing nodes keep
        their original page — first writer wins, the newcomer's private
        copy simply stays private to its slot."""
        assert len(pages) == -(-len(tokens) // self.pool.page_size), \
            "one page per (possibly partial) chunk"
        node = self._root
        created = 0
        tick = next(self._clock)
        for chunk, page in zip(self._chunks(tokens), pages):
            partial = len(chunk) < self.pool.page_size
            # dict keys ARE the token tuples, so a partial tail chunk can
            # only ever collide with an identical partial node — full and
            # partial entries with a common prefix coexist as siblings
            child = node.children.get(chunk)
            if child is None:
                child = _RadixNode(chunk, page, node, partial, tick)
                node.children[chunk] = child
                self.pool.incref(page)
                created += 1
                self.num_nodes += 1
            child.tick = tick
            if child.partial:
                break                 # partial nodes are terminal
            node = child
        return created

    # -- lookup -------------------------------------------------------------

    def match(self, tokens: Sequence[int]) -> Tuple[int, List[int]]:
        """Longest-prefix match over FULL pages only: (matched_tokens,
        pages). Pure read apart from the LRU stamp; no refcounts move."""
        ps = self.pool.page_size
        node = self._root
        pages: List[int] = []
        tick = next(self._clock)
        for chunk in self._chunks(tokens):
            if len(chunk) < ps:
                break                 # tail chunk: never shared via match
            child = node.children.get(chunk)
            if child is None or child.partial:
                break
            child.tick = tick
            pages.append(child.page)
            node = child
        matched = len(pages) * ps
        self.lookups += 1
        self.lookup_tokens += len(tokens)
        if matched:
            self.hits += 1
            self.hit_tokens += matched
        return matched, pages

    # -- eviction -----------------------------------------------------------

    def _leaves(self) -> List[_RadixNode]:
        out, stack = [], list(self._root.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            else:
                out.append(n)
        return out

    def evict(self, n_pages: int) -> int:
        """Free up to ``n_pages`` pages by removing LRU leaves whose page
        only the tree still references (external refcount zero — a page a
        live slot maps is NEVER evicted). Removing a leaf can expose its
        parent; the sweep repeats until satisfied or nothing qualifies."""
        freed = 0
        while freed < n_pages:
            victims = sorted(
                (leaf for leaf in self._leaves()
                 if self.pool.refcount(leaf.page) == 1),
                key=lambda leaf: leaf.tick)
            if not victims:
                break
            for leaf in victims:
                self._remove(leaf)
                freed += 1
                if freed >= n_pages:
                    break
        return freed

    def _remove(self, node: _RadixNode) -> None:
        assert not node.children
        node.parent.children.pop(node.tokens, None)
        self.num_nodes -= 1
        self.pool.decref(node.page)

    def drop(self, tokens: Sequence[int]) -> int:
        """Purge the exact path for ``tokens`` bottom-up, stopping at the
        first node shared with other prompts (it has other children).
        Used when a prompt is quarantined — its KV must not seed future
        admissions. Returns nodes removed."""
        node = self._root
        path: List[_RadixNode] = []
        for chunk in self._chunks(tokens):
            child = node.children.get(chunk)
            if child is None:
                break
            path.append(child)
            if child.partial:
                break
            node = child
        removed = 0
        for n in reversed(path):
            if n.children:
                break
            self._remove(n)
            removed += 1
        return removed

    def clear(self) -> int:
        """Drop every node (decref all pages); returns nodes removed."""
        removed = 0
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            n.children.clear()
            self.num_nodes -= 1
            self.pool.decref(n.page)
            removed += 1
        self._root.children.clear()
        return removed

    def snapshot(self) -> dict:
        return {
            "nodes": self.num_nodes,
            "lookups": self.lookups,
            "hits": self.hits,
            "lookup_tokens": self.lookup_tokens,
            "hit_tokens": self.hit_tokens,
        }
