from bigdl_tpu.serving.engine import (  # noqa: F401
    EngineConfig,
    LLMEngine,
    LogprobEntry,
    Request,
    RequestOutput,
    SamplingParams,
)
from bigdl_tpu.serving.router import (  # noqa: F401
    Router,
    RouterConfig,
)
