from bigdl_tpu.serving.engine import (  # noqa: F401
    EngineConfig,
    EngineDraining,
    LLMEngine,
    LogprobEntry,
    Request,
    RequestOutput,
    SamplingParams,
)
from bigdl_tpu.serving.overload import (  # noqa: F401
    QOS_CLASSES,
    OverloadConfig,
    OverloadController,
    RequestShed,
)
from bigdl_tpu.serving.router import (  # noqa: F401
    Router,
    RouterConfig,
)
