from bigdl_tpu.serving.engine import (  # noqa: F401
    EngineConfig,
    LLMEngine,
    LogprobEntry,
    Request,
    RequestOutput,
    SamplingParams,
)
