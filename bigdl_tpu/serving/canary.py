"""Golden-canary correctness probes for the replica fleet.

The fault models the supervisor already catches are LOUD: crashes,
hangs, NaN logits, drains. What nothing above caught is a replica that
decodes *wrong-but-finite* tokens — a corrupted weight shard, a bad
quantized kernel, an injected ``logit_drift`` — serving garbage at
full speed with every gauge green (the motivation case in ISSUE/
PAPERS: "When Quantization Is Free" quality drift).

The prober drives a pinned set of greedy (temperature 0) probes
through each HEALTHY replica round-robin. Because every replica loads
the same seeded weights and greedy decoding is deterministic, every
replica must produce byte-identical completions; the FIRST successful
probe per (prompt, kind) records the golden answer, and any later
byte mismatch — from any replica — is a correctness alert:

- ``canary_mismatch`` flight event + ``canary_failures`` counter +
  ``bigdl_tpu_router_canary_failures_total{replica}``,
- the replica is quarantined through the existing supervisor path
  (state QUARANTINED, process SIGTERMed, no restarts fed to it) —
  exactly like a crash-looping replica, because a silently wrong
  replica is WORSE than a dead one.

Probe kinds (the paths that can each break independently):

- ``plain``   — straight ``POST /v1/completions`` at the replica: the
  decode path itself.
- ``prefix``  — the same prompt re-probed plus a longer prompt sharing
  its prefix: with paged KV + radix sharing enabled this is served
  from copy-on-write shared pages, so a corrupted prefix-cache path
  diverges here while ``plain`` stays golden.
- ``handoff`` — only probed when the fleet has prefill-role replicas:
  the probe carries ``X-Handoff-Targets`` (built by the router the
  same way as a client forward), so prefill -> KV-ship -> remote
  decode must reproduce the same bytes.

Byte equality has a blind spot: a drift too small (or too aligned)
to flip any argmax serves byte-identical completions while the
distribution underneath degrades. The **NLL-tolerance mode**
(``$BIGDL_TPU_CANARY_NLL_TOL`` > 0) closes it: every probe also
requests per-token logprobs, the first successful probe per
(prompt, kind) records the golden mean NLL, and a later probe whose
mean NLL drifts more than the tolerance (nats/token, either
direction) quarantines the replica with ``kind="nll"`` — even when
its bytes still match. Pick the tolerance from
``observability.quality.golden_nll_allowance(qtype)`` plus margin.

Knobs: ``$BIGDL_TPU_CANARY_SEC`` — probe sweep interval in seconds,
0 disables (default); ``$BIGDL_TPU_CANARY_NLL_TOL`` — NLL drift
tolerance in nats/token, 0 disables the NLL mode (default). Both
validated by utils/env_check.py.
"""

from __future__ import annotations

import http.client
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

CANARY_SEC_ENV = "BIGDL_TPU_CANARY_SEC"
CANARY_NLL_TOL_ENV = "BIGDL_TPU_CANARY_NLL_TOL"

#: pinned probe prompts: raw token-id lists (the API accepts them and
#: answers with token ids — no tokenizer needed, and ids this small
#: exist in every vocab). The third shares the second's prefix so the
#: radix/paged-KV path serves it from shared pages.
DEFAULT_PROMPTS: Tuple[Tuple[int, ...], ...] = (
    (1, 2, 3, 4, 5, 6, 7, 8),
    (11, 12, 13, 14, 15, 16, 17, 18, 19, 20, 21, 22),
    (11, 12, 13, 14, 15, 16, 17, 18, 19, 20, 21, 22, 23, 24),
)

DEFAULT_MAX_TOKENS = 8

KINDS = ("plain", "prefix", "handoff")


def resolve_canary_sec(value: Optional[str] = None) -> float:
    """Canary sweep interval in seconds: explicit value, else
    ``$BIGDL_TPU_CANARY_SEC``, else 0.0 (disabled). Raises
    ``ValueError`` on a negative or non-numeric value (env_check
    surfaces it)."""
    raw = value if value is not None else os.environ.get(
        CANARY_SEC_ENV, "")
    if not raw:
        return 0.0
    sec = float(raw)                   # ValueError propagates
    if sec < 0:
        raise ValueError(
            f"{CANARY_SEC_ENV} must be >= 0 (0 disables), got {raw!r}")
    return sec


def resolve_canary_nll_tol(value: Optional[str] = None) -> float:
    """NLL drift tolerance in nats/token for the canary's
    NLL-tolerance mode: explicit value, else
    ``$BIGDL_TPU_CANARY_NLL_TOL``, else 0.0 (byte-equality only).
    Raises ``ValueError`` on a negative or non-numeric value
    (env_check surfaces it)."""
    raw = value if value is not None else os.environ.get(
        CANARY_NLL_TOL_ENV, "")
    if not raw:
        return 0.0
    tol = float(raw)                   # ValueError propagates
    if tol < 0:
        raise ValueError(
            f"{CANARY_NLL_TOL_ENV} must be >= 0 (0 disables), "
            f"got {raw!r}")
    return tol


class CanaryProber:
    """Periodic golden-probe sweeps over a Router's replicas.

    Owns a daemon thread (started by ``start()``, stopped by
    ``stop()``) so a slow probe can never stall the supervisor's
    health loop. All mutable state (goldens, counters) is only touched
    from that thread; ``snapshot()`` copies under the router lock-free
    dict-read idiom (GIL-atomic reads of append-only state)."""

    def __init__(self, router: Any, interval_sec: float,
                 prompts: Optional[List[Tuple[int, ...]]] = None,
                 max_tokens: int = DEFAULT_MAX_TOKENS,
                 timeout_sec: float = 30.0,
                 nll_tol: Optional[float] = None):
        self.router = router
        self.interval_sec = interval_sec
        self.prompts = [tuple(p) for p in (prompts or DEFAULT_PROMPTS)]
        self.max_tokens = max_tokens
        self.timeout_sec = timeout_sec
        try:
            self.nll_tol = (nll_tol if nll_tol is not None
                            else resolve_canary_nll_tol())
        except ValueError:
            self.nll_tol = 0.0         # env_check reports the bad knob
        # (prompt_idx, kind) -> golden choice payload (JSON-stable str)
        self.goldens: Dict[Tuple[int, str], str] = {}
        # (prompt_idx, kind) -> golden mean NLL (nats/token)
        self.goldens_nll: Dict[Tuple[int, str], float] = {}
        self.probes_total = 0
        self.failures_total = 0
        self.nll_failures_total = 0
        self.last_sweep: Optional[float] = None
        self.last_error: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self.interval_sec <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop,
                                        name="canary", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_sec):
            try:
                self.sweep()
            except Exception as e:       # the prober must survive
                self.last_error = f"{type(e).__name__}: {e}"

    # -- probing ------------------------------------------------------------

    def _healthy(self) -> List[Any]:
        from bigdl_tpu.serving.router import HEALTHY
        return [r for r in self.router.replicas if r.state == HEALTHY]

    def _post_completion(self, port: int, prompt: Tuple[int, ...],
                         headers: Optional[Dict[str, str]] = None
                         ) -> Optional[dict]:
        payload: Dict[str, Any] = {
            "model": "canary", "prompt": list(prompt),
            "max_tokens": self.max_tokens, "temperature": 0.0,
        }
        if self.nll_tol > 0:
            # NLL mode rides the same probe: top-0 logprobs returns
            # just the chosen-token logprob per position
            payload["logprobs"] = 0
        body = json.dumps(payload).encode()
        h = {"Content-Type": "application/json"}
        if headers:
            h.update(headers)
        # router-owned network chaos (net_latency@point=canary /
        # net_drop@point=canary): a dropped probe is simply a probe
        # that found nothing, never a mismatch
        nf = getattr(self.router, "_net_fault", None)
        if nf is not None:
            try:
                nf("/v1/completions")
            except OSError:
                return None
        conn = http.client.HTTPConnection(self.router.host, port,
                                          timeout=self.timeout_sec)
        try:
            conn.request("POST", "/v1/completions", body=body,
                         headers=h)
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                return None
            return json.loads(data)
        except (OSError, ValueError, http.client.HTTPException):
            return None
        finally:
            conn.close()

    @staticmethod
    def _canonical(doc: dict) -> Optional[str]:
        """The byte-comparable part of a completion response: the
        choice texts/token payloads and finish reasons, stripped of
        ids/timestamps that legitimately differ per request."""
        try:
            choices = doc["choices"]
            return json.dumps(
                [{"text": c.get("text"),
                  "finish_reason": c.get("finish_reason")}
                 for c in choices],
                sort_keys=True, separators=(",", ":"))
        except (KeyError, TypeError):
            return None

    @staticmethod
    def _mean_nll(doc: dict) -> Optional[float]:
        """Mean NLL (nats/token) over the completion's chosen tokens,
        or None when the response carries no usable logprobs."""
        try:
            lps = doc["choices"][0]["logprobs"]["token_logprobs"]
            vals = [float(v) for v in lps if v is not None]
            if not vals:
                return None
            return -sum(vals) / len(vals)
        except (KeyError, IndexError, TypeError, ValueError):
            return None

    def _probe_specs(self, r: Any) -> List[Tuple[int, str,
                                                 Optional[Dict[str, str]]]]:
        """(prompt_idx, kind, extra_headers) probes for one replica."""
        specs: List[Tuple[int, str, Optional[Dict[str, str]]]] = [
            (0, "plain", None)]
        if len(self.prompts) > 2:
            # prompts 1+2 share a prefix: the radix/paged-KV path
            specs.append((1, "prefix", None))
            specs.append((2, "prefix", None))
        if r.role == "prefill":
            # the KV-handoff path: same header the router's client
            # forwards carry, decode candidates chosen the same way
            targets = self.router._handoff_targets(r)
            if targets:
                specs.append((0, "handoff",
                              {"X-Handoff-Targets": ",".join(targets)}))
        return specs

    def sweep(self) -> dict:
        """One probe sweep over every HEALTHY replica. Returns a
        summary dict (probes run, mismatches found)."""
        ran, mismatches = 0, 0
        for r in self._healthy():
            for prompt_idx, kind, headers in self._probe_specs(r):
                if self._stop.is_set():
                    break
                # re-check per probe: an earlier mismatch in this very
                # sweep may have quarantined the replica
                if not self._still_healthy(r):
                    break
                doc = self._post_completion(
                    r.port, self.prompts[prompt_idx], headers)
                self.probes_total += 1
                ran += 1
                counted = getattr(self.router, "canary_probe", None)
                if counted is not None:
                    counted()
                if doc is None:
                    # transport/5xx: the health prober owns liveness;
                    # the canary only judges byte correctness
                    continue
                got = self._canonical(doc)
                if got is None:
                    continue
                key = (prompt_idx, kind)
                golden = self.goldens.get(key)
                if golden is None:
                    # first successful probe defines the golden —
                    # recorded while the fleet is healthy, so a
                    # later-onset drift (after_step-armed fault, decayed
                    # weights) diverges from it
                    self.goldens[key] = got
                elif got != golden:
                    mismatches += 1
                    self.failures_total += 1
                    self.router.canary_mismatch(
                        r, kind=kind, prompt_idx=prompt_idx,
                        expected=golden, got=got)
                    continue           # quarantined; skip the NLL test
                if self.nll_tol > 0:
                    # NLL-tolerance mode: catches drift that never
                    # flips an argmax — the bytes above stay golden
                    # while the distribution underneath degrades
                    nll = self._mean_nll(doc)
                    if nll is None:
                        continue
                    g_nll = self.goldens_nll.get(key)
                    if g_nll is None:
                        self.goldens_nll[key] = nll
                    elif abs(nll - g_nll) > self.nll_tol:
                        mismatches += 1
                        self.failures_total += 1
                        self.nll_failures_total += 1
                        self.router.canary_mismatch(
                            r, kind="nll", prompt_idx=prompt_idx,
                            expected=f"nll={g_nll:.4f}"
                                     f"±{self.nll_tol:.4f}",
                            got=f"nll={nll:.4f} ({kind})")
        self.last_sweep = time.time()
        return {"probes": ran, "mismatches": mismatches}

    def _still_healthy(self, r: Any) -> bool:
        from bigdl_tpu.serving.router import HEALTHY
        return r.state == HEALTHY

    def snapshot(self) -> dict:
        return {
            "enabled": self.interval_sec > 0,
            "interval_sec": self.interval_sec,
            "prompts": len(self.prompts),
            "goldens_recorded": len(self.goldens),
            "probes_total": self.probes_total,
            "failures_total": self.failures_total,
            "nll_tol": self.nll_tol,
            "nll_goldens_recorded": len(self.goldens_nll),
            "nll_failures_total": self.nll_failures_total,
            "last_sweep": self.last_sweep,
            "last_error": self.last_error,
        }


__all__ = [
    "CANARY_NLL_TOL_ENV",
    "CANARY_SEC_ENV",
    "DEFAULT_PROMPTS",
    "CanaryProber",
    "resolve_canary_nll_tol",
    "resolve_canary_sec",
]
