"""Continuous-batching serving engine, TPU-native.

Re-design of the reference's vLLM port (reference vllm/engine/llm_engine.py:
66-687 `LLMEngine.step`, vllm/core/scheduler.py:93 `FixedWindowScheduler`,
vllm/worker/worker.py:260 single in-process worker, per-sequence padded KV
dicts at vllm/model_executor/models/bigdl_model.py:88-139).

The reference re-pads and re-assembles a python dict of per-sequence KV
tensors every step — unusable under XLA. Here the design is slot-based and
fully static:

- ONE batched KV cache [L, max_batch, max_seq, H, D] with a per-slot
  position vector (ops/kvcache.py per_slot_pos). A slot is a sequence's
  home for its whole lifetime; admission = prefill into the slot,
  completion = slot freed (pos reset), nothing ever re-pads or copies KV.
- ONE compiled decode executable for the whole engine lifetime: tokens
  [max_batch, 1] + cache -> logits. Finished/empty slots decode garbage
  that is never read — the FLOP cost of static shapes, repaid by zero
  recompiles and an always-full MXU batch.
- Prefill is compiled per prompt-length bucket and writes K/V straight
  into the batched cache at the slot index.
- Scheduling is FCFS admission (the reference's FixedWindowScheduler
  semantics) driven from `step()`; sampling runs on host per-slot so every
  request can carry its own temperature/top-k/top-p (the reference's
  BigDLSampler is also host-side).
"""

from __future__ import annotations

import dataclasses
import functools
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.ops.kvcache import KVCache, init_cache


@dataclasses.dataclass
class SamplingParams:
    """Per-request sampling (reference vllm/sampling_params.py surface)."""
    max_tokens: int = 128
    temperature: float = 0.0       # 0 = greedy
    top_k: int = 0
    top_p: float = 1.0
    stop_token_ids: Tuple[int, ...] = ()
    ignore_eos: bool = False


@dataclasses.dataclass
class Request:
    request_id: str
    prompt_token_ids: List[int]
    params: SamplingParams
    arrival: float = dataclasses.field(default_factory=time.time)


@dataclasses.dataclass
class RequestOutput:
    request_id: str
    new_token_ids: List[int]
    finished: bool
    finish_reason: Optional[str] = None


@dataclasses.dataclass
class EngineConfig:
    max_batch: int = 8
    max_seq: int = 2048
    prefill_bucket: int = 16       # smallest prefill compile bucket
    kv_quantized: bool = False


class _Slot:
    __slots__ = ("req", "generated", "last_token", "active")

    def __init__(self):
        self.req: Optional[Request] = None
        self.generated: List[int] = []
        self.last_token: int = 0
        self.active: bool = False


class LLMEngine:
    """Synchronous continuous-batching engine over one model.

    model: a TpuCausalLM (bigdl_tpu.transformers.model) or anything exposing
    .params/.config/.family. Drive with add_request() + step(), identical in
    spirit to the reference engine loop (llm_engine.py:543).
    """

    def __init__(self, model: Any, config: Optional[EngineConfig] = None):
        self.cfg_engine = config or EngineConfig()
        self.params = model.params
        self.cfg = model.config
        self.family = model.family
        if getattr(self.family, "is_recurrent", False):
            raise ValueError(
                f"continuous batching is KV-cache based; the "
                f"{self.family.name!r} family carries recurrent state "
                "whose slots cannot be rewound/packed — serve it through "
                "model.generate() instead")
        self.eos_token_id = None
        hf = getattr(model, "hf_config", None) or {}
        eos = hf.get("eos_token_id")
        self.eos_token_id = eos[0] if isinstance(eos, list) else eos

        ce = self.cfg_engine
        B = ce.max_batch
        self.cache = init_cache(
            self.cfg.num_hidden_layers, B, ce.max_seq,
            self.cfg.num_key_value_heads, self.cfg.hd,
            quantized=ce.kv_quantized, per_slot_pos=True)

        self.slots = [_Slot() for _ in range(B)]
        self.waiting: "queue.Queue[Request]" = queue.Queue()
        self._outputs: Dict[str, List[RequestOutput]] = {}
        self._abort: set = set()
        self._lock = threading.Lock()

        fwd = self.family.forward

        @functools.partial(jax.jit, donate_argnums=(2,))
        def decode(params, tokens, cache):   # tokens [B] int32
            logits, cache = fwd(params, self.cfg, tokens[:, None], cache)
            return logits[:, -1, :], cache

        self._decode = decode

        # prefill one sequence on a private 1-row cache, then splice its K/V
        # and position into the batched cache at the slot index
        @functools.partial(jax.jit, donate_argnums=(0,))
        def insert(cache: KVCache, k1, v1, slot, plen):
            k = jax.lax.dynamic_update_slice(
                cache.k, k1.astype(cache.k.dtype), (0, slot, 0, 0, 0))
            v = jax.lax.dynamic_update_slice(
                cache.v, v1.astype(cache.v.dtype), (0, slot, 0, 0, 0))
            pos = cache.pos.at[slot].set(plen)
            return KVCache(k, v, pos)

        self._insert = insert
        self._prefills: Dict[int, Callable] = {}

    # -- public api ---------------------------------------------------------

    def add_request(self, request_id: str, prompt_token_ids, params=None):
        params = params or SamplingParams()
        ids = list(prompt_token_ids)
        if len(ids) + 1 > self.cfg_engine.max_seq:
            raise ValueError(
                f"prompt length {len(ids)} exceeds engine max_seq "
                f"{self.cfg_engine.max_seq}")
        if not ids:
            raise ValueError("empty prompt")
        self.waiting.put(Request(request_id, ids, params))
        with self._lock:
            self._outputs[request_id] = []

    def abort_request(self, request_id: str) -> None:
        """Reference api_server behavior on client disconnect
        (vllm/entrypoints/openai/api_server.py:371)."""
        self._abort.add(request_id)

    def has_unfinished(self) -> bool:
        return (not self.waiting.empty()) or any(
            s.active for s in self.slots)

    def get_outputs(self, request_id: str) -> List[RequestOutput]:
        with self._lock:
            out = self._outputs.get(request_id, [])
            if any(o.finished for o in out):
                # request complete: drop the entry (unread finished entries
                # of aborted streams must not accumulate)
                self._outputs.pop(request_id, None)
            elif out:
                self._outputs[request_id] = []
        return out

    # -- engine internals ---------------------------------------------------

    def _bucket(self, n: int) -> int:
        b = self.cfg_engine.prefill_bucket
        while b < n:
            b *= 2
        return min(b, self.cfg_engine.max_seq)

    def _prefill_fn(self, bucket: int) -> Callable:
        fn = self._prefills.get(bucket)
        if fn is None:
            fwd = self.family.forward

            @jax.jit
            def prefill(params, tokens):      # [1, bucket]
                cache1 = init_cache(
                    self.cfg.num_hidden_layers, 1, bucket,
                    self.cfg.num_key_value_heads, self.cfg.hd,
                    quantized=self.cfg_engine.kv_quantized)
                logits, cache1 = fwd(params, self.cfg, tokens, cache1)
                return logits, cache1.k, cache1.v

            fn = self._prefills[bucket] = prefill
        return fn

    def _admit(self, req: Request, slot_idx: int) -> None:
        s = self.slots[slot_idx]
        plen = len(req.prompt_token_ids)
        bucket = self._bucket(plen)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :plen] = req.prompt_token_ids
        logits, k1, v1 = self._prefill_fn(bucket)(
            self.params, jnp.asarray(padded))
        self.cache = self._insert(self.cache, k1, v1, slot_idx, plen)
        first = self._sample_host(
            np.asarray(logits)[0, plen - 1], req.params)
        s.req = req
        s.generated = [int(first)]
        s.last_token = int(first)
        s.active = True
        self._emit(s)

    @staticmethod
    def _sample_host(logits: np.ndarray, p: SamplingParams) -> int:
        if p.temperature <= 0.0:
            return int(np.argmax(logits))
        lg = logits.astype(np.float64) / p.temperature
        if p.top_k > 0:
            kth = np.sort(lg)[-p.top_k]
            lg = np.where(lg < kth, -np.inf, lg)
        if p.top_p < 1.0:
            order = np.argsort(lg)[::-1]
            probs = np.exp(lg[order] - np.max(lg))
            probs /= probs.sum()
            cum = np.cumsum(probs)
            cut = int(np.searchsorted(cum, p.top_p)) + 1
            mask = np.full_like(lg, -np.inf)
            mask[order[:cut]] = lg[order[:cut]]
            lg = mask
        probs = np.exp(lg - np.max(lg[np.isfinite(lg)]))
        probs = np.where(np.isfinite(lg), probs, 0.0)
        probs /= probs.sum()
        return int(np.random.choice(len(probs), p=probs))

    def _finish(self, idx: int, reason: str) -> None:
        s = self.slots[idx]
        if s.req is None:
            return
        with self._lock:
            self._outputs.setdefault(s.req.request_id, []).append(
                RequestOutput(s.req.request_id, [], True, reason))
        s.req = None
        s.active = False
        s.generated = []
        # reset the slot's position so the idle row stops deepening
        self.cache = KVCache(self.cache.k, self.cache.v,
                             self.cache.pos.at[idx].set(0))

    def _emit(self, s: _Slot) -> None:
        with self._lock:
            self._outputs.setdefault(s.req.request_id, []).append(
                RequestOutput(s.req.request_id, [s.last_token], False))

    def _check_done(self, idx: int) -> bool:
        s = self.slots[idx]
        p = s.req.params
        tok = s.last_token
        if (not p.ignore_eos and self.eos_token_id is not None
                and tok == self.eos_token_id):
            self._finish(idx, "stop")
            return True
        if tok in p.stop_token_ids:
            self._finish(idx, "stop")
            return True
        if len(s.generated) >= p.max_tokens:
            self._finish(idx, "length")
            return True
        plen = len(s.req.prompt_token_ids)
        if plen + len(s.generated) + 1 >= self.cfg_engine.max_seq:
            self._finish(idx, "length")
            return True
        return False

    def step(self) -> bool:
        """One engine iteration (reference LLMEngine.step): admit waiting
        requests into free slots, then run one batched decode step.
        Returns True if any work was done."""
        # aborts
        for i, s in enumerate(self.slots):
            if s.active and s.req.request_id in self._abort:
                self._abort.discard(s.req.request_id)
                self._finish(i, "abort")

        # admission
        for i, s in enumerate(self.slots):
            if not s.active and not self.waiting.empty():
                try:
                    req = self.waiting.get_nowait()
                except queue.Empty:
                    break
                if req.request_id in self._abort:
                    self._abort.discard(req.request_id)
                    continue
                self._admit(req, i)
                if self._check_done(i):
                    pass

        active = [i for i, s in enumerate(self.slots) if s.active]
        if not active:
            return False

        tokens = np.zeros((self.cfg_engine.max_batch,), np.int32)
        for i in active:
            tokens[i] = self.slots[i].last_token
        logits, self.cache = self._decode(
            self.params, jnp.asarray(tokens), self.cache)
        logits = np.asarray(logits)

        for i in active:
            s = self.slots[i]
            tok = self._sample_host(logits[i], s.req.params)
            s.last_token = tok
            s.generated.append(tok)
            self._emit(s)
            self._check_done(i)
        return True

    # -- convenience: blocking one-shot generation --------------------------

    def generate(self, prompts: List[List[int]],
                 params: Optional[SamplingParams] = None) -> List[List[int]]:
        """Batch-generate (the reference's offline `LLM.generate` analog)."""
        ids = [f"gen-{i}" for i in range(len(prompts))]
        for rid, p in zip(ids, prompts):
            self.add_request(rid, p, params)
        done: Dict[str, List[int]] = {rid: [] for rid in ids}
        finished: set = set()
        while len(finished) < len(ids):
            if not self.step():
                time.sleep(0.001)
            for rid in ids:
                for out in self.get_outputs(rid):
                    done[rid].extend(out.new_token_ids)
                    if out.finished:
                        finished.add(rid)
        return [done[rid] for rid in ids]
