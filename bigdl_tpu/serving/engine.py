"""Continuous-batching serving engine, TPU-native.

Re-design of the reference's vLLM port (reference vllm/engine/llm_engine.py:
66-687 `LLMEngine.step`, vllm/core/scheduler.py:93 `FixedWindowScheduler`,
vllm/worker/worker.py:260 single in-process worker, per-sequence padded KV
dicts at vllm/model_executor/models/bigdl_model.py:88-139).

The reference re-pads and re-assembles a python dict of per-sequence KV
tensors every step — unusable under XLA. Here the design is slot-based and
fully static:

- ONE batched KV cache [L, max_batch, max_seq, H, D] with a per-slot
  position vector (ops/kvcache.py per_slot_pos). A slot is a sequence's
  home for its whole lifetime; admission = prefill into the slot,
  completion = slot freed (pos reset), nothing ever re-pads or copies KV.
- ONE compiled decode executable for the whole engine lifetime: tokens
  [max_batch, 1] + cache -> logits. Finished/empty slots decode garbage
  that is never read — the FLOP cost of static shapes, repaid by zero
  recompiles and an always-full MXU batch.
- Prefill is compiled per prompt-length bucket and writes K/V straight
  into the batched cache at the slot index.
- Scheduling is FCFS admission (the reference's FixedWindowScheduler
  semantics) driven from `step()`. Sampling: per-slot temperature/top-k/
  top-p/seed runs batched ON DEVICE (gumbel-max; only [B] ints cross the
  tunnel); slots needing penalty counts or logprobs fall back to the
  host sampler (the reference's BigDLSampler role, which is host-side
  for every request).
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import math
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.config import (decode_resident_enabled, flags,
                              quality_enabled, resolve_kv_page_size,
                              resolve_kv_pages, resolve_prefix_sharing,
                              sentinel_enabled)
from bigdl_tpu.observability import roofline
from bigdl_tpu.observability.compile_watch import (annotate_costs,
                                                   compiles_in_progress,
                                                   top_offenders,
                                                   tracked_jit)
from bigdl_tpu.observability.quality import (GOLDEN_PROBE_PROMPTS,
                                             QUALITY_METRICS,
                                             QualitySentinel,
                                             golden_nll_allowance,
                                             resolve_quality_probe_steps)
from bigdl_tpu.observability.sentinel import PerfSentinel
from bigdl_tpu.observability.disttrace import SpanRecorder, new_span_id
from bigdl_tpu.observability.flight import (FlightRecorder, build_postmortem,
                                            exception_fields)
from bigdl_tpu.observability.flight import write_postmortem as \
    _write_postmortem_file
from bigdl_tpu.observability.memory import MemoryLedger, tree_nbytes
from bigdl_tpu.observability.metrics import RATIO_BUCKETS, default_registry
from bigdl_tpu.observability.slo import SLOTracker
from bigdl_tpu.observability.stats import ewma as stats_ewma
from bigdl_tpu.observability.tracing import RequestTracer
from bigdl_tpu.observability.usage import UsageLedger
from bigdl_tpu.ops.kvcache import (KVCache, init_cache, kv_cache_bytes,
                                   kv_cache_nbytes,
                                   publish_kv_cache_bytes,
                                   resolve_kv_cache_dtype)
from bigdl_tpu.ops.paged import (NULL_PAGE, PagedKVCache, cow_copy_pages,
                                 gather_pages_dense, paged_cache_bytes,
                                 publish_paged_cache_bytes)
from bigdl_tpu.robustness import (resolve_drain_timeout_sec,
                                  resolve_request_deadline_ms)
from bigdl_tpu.robustness.faults import FaultInjector
from bigdl_tpu.serving.overload import (QOS_CLASSES, SHED_REASONS,
                                        OverloadConfig, OverloadController,
                                        RequestShed)
from bigdl_tpu.serving.pagepool import PagePool, RadixCache


class EngineDraining(RuntimeError):
    """Raised by ``add_request`` while the engine drains (SIGTERM /
    ``begin_drain``): the caller should retry against another replica.
    The API server maps it to 503 + ``Retry-After``."""


#: ``bigdl_tpu_migrations_total{outcome}`` label values (live sequence
#: migration, export_sequence/import_sequence). Source side: exported ->
#: committed (the target owns the sequence) or failed + local_resume
#: (the sender gave up; the sequence re-admits here); unexportable means
#: the request was not mid-decode when asked. Target side: imported (KV
#: staged into the arena / prefix cache) -> claimed (the resumed
#: request's admission picked the staged pages up).
MIGRATION_OUTCOMES = ("exported", "committed", "failed", "local_resume",
                      "unexportable", "imported", "claimed")


@dataclasses.dataclass
class SamplingParams:
    """Per-request sampling (reference vllm/sampling_params.py surface:
    temperature/top_k/top_p/penalties/n/best_of/logprobs/stop)."""
    max_tokens: int = 128
    temperature: float = 0.0       # 0 = greedy
    top_k: int = 0
    top_p: float = 1.0
    stop_token_ids: Tuple[int, ...] = ()
    ignore_eos: bool = False
    # llama.cpp-form repetition penalty + OpenAI-form count penalties
    # (see bigdl_tpu.generation.apply_penalties). 1.0 / 0.0 = off.
    repetition_penalty: float = 1.0
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    # parallel sampling: generate best_of sequences, return the n best by
    # mean logprob (best_of defaults to n). n>1 streams with choice
    # indices; best_of>n buffers until all candidates finish.
    n: int = 1
    best_of: Optional[int] = None
    # per-token logprobs: 0 = chosen token only, k>0 = also top-k
    # alternatives per step. None = off.
    logprobs: Optional[int] = None
    seed: Optional[int] = None
    # per-request deadline (wall ms from arrival, enforced per step);
    # None defers to $BIGDL_TPU_REQUEST_DEADLINE_MS (unset = no
    # deadline). An expired request finishes with reason "deadline"
    # (HTTP 504 at the API server) wherever it is in its lifecycle —
    # queued, mid-prefill, or decoding.
    max_time_ms: Optional[float] = None
    # overload control (serving/overload.py): QoS class — one of
    # "interactive"/"standard"/"batch" (admission priority + who sheds
    # first under pressure); None defers to $BIGDL_TPU_QOS_DEFAULT.
    qos: Optional[str] = None
    # tenant key for fair queuing and rate limits (the API server fills
    # it from X-Tenant-Id / the API-key hash); empty = "default"
    tenant: str = "default"

    @property
    def needs_counts(self) -> bool:
        return (self.repetition_penalty != 1.0
                or self.presence_penalty != 0.0
                or self.frequency_penalty != 0.0)


@dataclasses.dataclass
class Request:
    request_id: str
    prompt_token_ids: List[int]
    params: SamplingParams
    arrival: float = dataclasses.field(default_factory=time.time)
    # preempt-resume: tokens already generated (and streamed) before this
    # (re-)admission; they are part of prompt_token_ids now and must count
    # against max_tokens without being re-emitted
    generated_offset: int = 0
    resumed_cum_logprob: float = 0.0
    # absolute deadline (time.time()), resolved at add_request from
    # max_time_ms / $BIGDL_TPU_REQUEST_DEADLINE_MS; survives
    # preempt-resume (the clock does not restart on readmission)
    deadline: Optional[float] = None
    # step/prefill failures attributed to this request (blast-radius
    # blame counter); past max_slot_crashes the request is quarantined
    crashes: int = 0
    # distributed-trace context (observability/disttrace.py):
    # (trace_id, parent_span_id) propagated from the traceparent header;
    # None for untraced requests
    trace: Optional[Tuple[str, str]] = None
    # live-migration resume (export_sequence/import_sequence): the
    # source slot's device-sampler stream carried over verbatim — an
    # unseeded request otherwise draws a fresh nonce at admission and
    # its continuation diverges from the unmigrated run
    resume_dev_seed: Optional[int] = None
    # staging key a migrated-in sequence presents at admission:
    # _paged_admit claims the imported arena pages stashed under it
    # (one-shot; None after the claim, or for ordinary requests)
    resume_id: Optional[str] = None


@dataclasses.dataclass
class LogprobEntry:
    """One emitted token's logprob record."""
    token_id: int
    logprob: float
    top: List[Tuple[int, float]] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class RequestOutput:
    request_id: str
    new_token_ids: List[int]
    finished: bool
    finish_reason: Optional[str] = None
    index: int = 0                    # choice index (n>1 fan-out)
    logprobs: Optional[List[LogprobEntry]] = None
    # structured failure detail for finish_reason "error" (quarantine):
    # {"reason", "request_id"[, "type", "message"]}
    error: Optional[dict] = None


@dataclasses.dataclass
class EngineConfig:
    max_batch: int = 8
    max_seq: int = 2048
    prefill_bucket: int = 16       # smallest prefill compile bucket
    # KV cache storage dtype: "bf16", "fp8_e5m2", "int8" or "int4"
    # (int8/int4 carry per-(token, head) scales and need a family with
    # SUPPORTS_SCALED_KV). "bf16" defers to the deprecated kv_quantized.
    kv_cache_dtype: str = "bf16"
    kv_quantized: bool = False     # deprecated: True == "fp8_e5m2"
    # chunked prefill: a step() never runs more than this many prompt
    # tokens of prefill before the batched decode, so a long admission
    # cannot stall in-flight streams for more than one chunk's latency
    # (the reference engine runs the whole prefill inline and freezes
    # every stream, llm_engine.py:543 + scheduler.py:93)
    prefill_chunk: int = 256
    # starvation guard (the reference scheduler's preemption-by-recompute,
    # vllm/core/scheduler.py:52-66): when requests have been waiting this
    # many consecutive steps with every slot busy, the LATEST-arrived
    # running sequence is evicted to the BACK of the queue — its tokens
    # so far become prompt, recomputed on readmission (the prompt-prefix
    # cache makes that cheap when enabled), while the starved requests
    # admit into the freed slot first. 0 disables.
    preempt_after_steps: int = 64
    # context-parallel overflow lane: with a mesh passed to LLMEngine,
    # prompts longer than max_seq are admitted anyway — their KV shards
    # over the mesh (parallel/cp.py ring prefill + sequence-sharded
    # decode) up to this many tokens (multiple of the mesh size). One CP
    # request runs at a time, advancing one token per engine step
    # alongside the batched slots. None disables.
    cp_max_seq: Optional[int] = None
    # prompt-prefix KV reuse (the reference gen-1 pipeline's LlamaCache/
    # LlamaState, ggml/model/llama/llama.py:63,109-121,1346-1373): after
    # each admission the prompt's KV snapshot is kept on HOST; a later
    # prompt sharing a prefix seeds its private cache from the longest
    # match and prefills only the tail. 0 (the default) disables: for a
    # 7B-class model each entry holds on the order of 100-500 MB of host
    # DRAM (2*L*prefix_cache_max_tokens*Hkv*hd values) and its device
    # slices pin HBM until the next cache touch — opt in per deployment.
    prefix_cache_entries: int = 0
    # only the first N prompt tokens are snapshotted — bounds the D2H
    # transfer and host memory per entry (system prompts live here)
    prefix_cache_max_tokens: int = 1024
    # -- paged KV cache (ops/paged.py + serving/pagepool.py) ----------
    # token positions per arena page. None defers to
    # $BIGDL_TPU_KV_PAGE_SIZE; 0 keeps the per-slot slab. Must be a
    # power of two dividing max_seq. With paging on, the per-slot slab
    # becomes one [P, page_size, H, hd] arena per layer addressed
    # through per-sequence block tables, and prompt prefixes are shared
    # copy-on-write across requests via a radix tree.
    kv_page_size: Optional[int] = None
    # total physical pages in the arena. None defers to
    # $BIGDL_TPU_KV_PAGES; 0 auto-sizes to max_batch *
    # (max_seq / page_size) + 1 — the slab's worst case plus the pinned
    # null page. Configure it below that to oversubscribe: admission
    # then depends on prefix sharing actually deduplicating pages.
    kv_pages: Optional[int] = None
    # radix-tree prefix sharing across requests (paged mode only).
    # None defers to $BIGDL_TPU_PREFIX_SHARING; "auto"/"on" share
    # full-page prompt chunks copy-on-write, "off" keeps every
    # sequence's pages private.
    prefix_sharing: Optional[str] = None
    # retention bound for prefix-cache entries seeded by remote KV
    # handoffs (disaggregated prefill). -1 defers to 2 * max_batch;
    # 0 drops staged snapshots outright. Kept SEPARATE from
    # prefix_cache_entries so a decode-role replica that disables the
    # local prefix cache (prefix_cache_entries == 0) still expresses
    # an explicit bound instead of silently re-enabling caching.
    handoff_cache_entries: int = -1
    # headroom-aware admission: an admission whose private prefill
    # cache would push bytes_in_use past this fraction of the device's
    # bytes_limit is deferred (FCFS order kept) until headroom returns.
    # None defers to $BIGDL_TPU_HBM_BUDGET_FRACTION (default 0.9).
    # Backends without memory_stats() (CPU/interpret) always admit.
    hbm_budget_fraction: Optional[float] = None
    # -- robustness (bigdl_tpu/robustness/) ---------------------------
    # default per-request deadline in ms; None defers to
    # $BIGDL_TPU_REQUEST_DEADLINE_MS (unset = no deadline).
    # SamplingParams.max_time_ms overrides per request.
    request_deadline_ms: Optional[float] = None
    # transient step failures: a failing step() is retried up to this
    # many consecutive times (exponential backoff from
    # retry_backoff_ms) before the exception propagates. Failures that
    # can be blamed on one request (mid-admission, or a slot crossing
    # max_slot_crashes) quarantine that request and refresh the budget
    # — the engine degrades per-request, never per-process.
    max_step_retries: int = 3
    retry_backoff_ms: float = 20.0
    # per-request crash budget: once this many step/prefill failures
    # are attributed to one request it is quarantined (finish reason
    # "error", bigdl_tpu_requests_quarantined_total{reason="crash_loop"})
    max_slot_crashes: int = 3
    # per-step NaN/Inf logits health check: a non-finite decode row
    # quarantines exactly that slot (reason "nan_logits") while every
    # other slot keeps decoding. Costs one tiny [B]-bool readback per
    # decode step; False disables.
    logits_health_check: bool = True
    # graceful drain: in-flight work gets this long to finish after
    # begin_drain() before being failed with reason "drain_timeout".
    # None defers to $BIGDL_TPU_DRAIN_TIMEOUT_SEC (default 30).
    drain_timeout_sec: Optional[float] = None
    # hard bound on queued requests (waiting + CP lanes), enforced at
    # add_request with a RequestShed (HTTP 503) even when every other
    # overload feature is off — an unbounded deque under a traffic
    # storm is an OOM. None defers to $BIGDL_TPU_MAX_QUEUE_DEPTH
    # (default 256). Shorthand for overload.max_queue_depth.
    max_queue_depth: Optional[int] = None
    # full overload-control policy (QoS aging, tenant rate limits,
    # queue byte caps, brownout thresholds); None resolves every knob
    # from its $BIGDL_TPU_* env variable (serving/overload.py)
    overload: Optional[OverloadConfig] = None
    # perf-regression sentinel (observability/sentinel.py): None defers
    # to config.sentinel_enabled() ($BIGDL_TPU_SENTINEL tristate);
    # True/False force it per engine (tests)
    sentinel: Optional[bool] = None
    # perf-history JSONL path the sentinel baselines against; None
    # defers to $BIGDL_TPU_PERF_HISTORY (unset = in-memory baseline)
    perf_history: Optional[str] = None
    # live quality telemetry + QualitySentinel (observability/
    # quality.py): None defers to config.quality_enabled()
    # ($BIGDL_TPU_QUALITY tristate); True/False force it per engine
    quality: Optional[bool] = None
    # quality-history JSONL the QualitySentinel baselines against; None
    # defers to $BIGDL_TPU_QUALITY_HISTORY (unset = in-memory baseline)
    quality_history: Optional[str] = None
    # teacher-forced NLL probe period in DECODE STEPS (not seconds, so
    # tests are deterministic); None defers to
    # $BIGDL_TPU_QUALITY_PROBE_STEPS (default 0 = probe off)
    quality_probe_steps: Optional[int] = None


class _Slot:
    __slots__ = ("req", "generated", "last_token", "active", "counts",
                 "counts_out", "rng", "cum_logprob", "n_logprobs",
                 "dev_seed")

    def __init__(self):
        self.req: Optional[Request] = None
        self.generated: List[int] = []
        self.last_token: int = 0
        self.active: bool = False
        # [V] int32 penalty counts: `counts` over prompt + output
        # (repetition penalty), `counts_out` over output only
        # (presence/frequency — vllm semantics)
        self.counts: Optional[np.ndarray] = None
        self.counts_out: Optional[np.ndarray] = None
        self.rng: Optional[np.random.Generator] = None
        self.cum_logprob: float = 0.0              # over generated tokens
        self.n_logprobs: int = 0
        # 31-bit seed for the DEVICE sampler stream (SamplingParams.seed
        # folded down, or a per-admission nonce when unseeded)
        self.dev_seed: int = 0


@dataclasses.dataclass
class _CPActive:
    """The in-flight context-parallel request: a pseudo-slot carries its
    sampler state; the KV cache lives sequence-sharded on the mesh."""
    slot: _Slot
    cache: Tuple[Any, Any]
    pos: int                 # global position of the NEXT cache write
    alloc: int               # sharded cache capacity (tokens)


@dataclasses.dataclass
class _CPAdmitting:
    """A long prompt mid-chunked-CP-prefill: like _Admission, the engine
    advances it ONE chunk per step so batched decodes keep flowing."""
    req: Request
    cache: Tuple[Any, Any]
    consumed: int
    alloc: int


@dataclasses.dataclass
class _Fanout:
    """Parent bookkeeping for n/best_of parallel sampling: child requests
    `rid#i` run as independent sequences; outputs route back under the
    parent id with choice indices (the reference scheduler forks
    SequenceGroups for the same purpose)."""
    parent_id: str
    n: int
    best_of: int
    # best_of > n: buffer each child's stream until all finish, then emit
    # the n best (by mean logprob); n == best_of streams through directly
    buffered: Dict[int, List["RequestOutput"]] = dataclasses.field(
        default_factory=dict)
    scores: Dict[int, float] = dataclasses.field(default_factory=dict)
    lengths: Dict[int, int] = dataclasses.field(default_factory=dict)
    done: int = 0


@dataclasses.dataclass
class _Admission:
    """A sequence mid-(chunked)-prefill: consumed tokens so far and its
    private 1-row cache (spliced into the batched cache on completion)."""
    req: Request
    slot_idx: int
    bucket: int
    consumed: int
    cache1: KVCache
    # effective prefill chunk, FROZEN at admission start: a brownout
    # level change mid-admission must not change the chunk width the
    # private cache was sized for
    chunk: int
    # paged mode (kv_page_size > 0): radix pages seeding the prompt
    # prefix (one slot reference each, taken at admission start) and
    # the freshly allocated private pages. The slot's block-table row
    # is written only at COMPLETION — until then it stays all-null, so
    # mid-admission decode steps of other slots can never write into
    # shared data through this row.
    shared_pages: Optional[List[int]] = None
    new_pages: Optional[List[int]] = None


def _device_sample_rows(lg, temps, top_ks, top_ps, seeds, poss):
    """Batched on-device sampler body: temperature / top-k / top-p via
    gumbel-max, one seeded stream per row. Shared by the standalone
    ``engine_sample_device`` jit and the fused resident decode step so
    the two paths are numerically identical token-for-token."""
    lg = lg.astype(jnp.float32)                      # [B, V]
    v = lg.shape[-1]
    greedy = temps <= 0.0
    t = lg / jnp.maximum(temps, 1e-6)[:, None]
    # top-k: per-row threshold from the sorted copy (k=0 -> all;
    # greedy rows keep all, their argmax ignores masking anyway)
    k = jnp.where(greedy | (top_ks <= 0), v, top_ks)
    sd = -jnp.sort(-t, axis=-1)
    kth = jnp.take_along_axis(
        sd, jnp.clip(k - 1, 0, v - 1)[:, None], axis=-1)
    t = jnp.where(t < kth, -jnp.inf, t)
    # top-p (nucleus) on the post-top-k distribution: keep the
    # smallest sorted prefix whose mass reaches p (first always)
    p = jnp.where(greedy, 1.0, top_ps)[:, None]
    sd = -jnp.sort(-t, axis=-1)
    probs = jax.nn.softmax(sd, axis=-1)
    # p >= 1.0 keeps ALL tokens (matching _sample_host's
    # `top_p < 1.0` gate): without it, f32 cumsum rounding can
    # push the pre-token mass to 1.0 and mask real tail tokens
    # on temperature-only requests
    keep = ((jnp.cumsum(probs, axis=-1) - probs) < p) | (p >= 1.0)
    # the top token survives even top_p=0.0 (OpenAI clients send
    # it to mean greedy; all-False keep would mask every token)
    keep = keep | (jnp.arange(v)[None, :] == 0)
    cutoff = jnp.min(jnp.where(keep, sd, jnp.inf), axis=-1)
    t = jnp.where(t < cutoff[:, None], -jnp.inf, t)

    def row(row_t, row_lg, g, seed, pos):
        key = jax.random.fold_in(jax.random.PRNGKey(seed), pos)
        gum = jax.random.gumbel(key, row_t.shape, row_t.dtype)
        z = jnp.where(g, row_lg, row_t + gum)
        return jnp.argmax(z).astype(jnp.int32)

    return jax.vmap(row)(t, lg, greedy, seeds, poss)


class LLMEngine:
    """Synchronous continuous-batching engine over one model.

    model: a TpuCausalLM (bigdl_tpu.transformers.model) or anything exposing
    .params/.config/.family. Drive with add_request() + step(), identical in
    spirit to the reference engine loop (llm_engine.py:543).
    """

    def __init__(self, model: Any, config: Optional[EngineConfig] = None,
                 cp_mesh: Any = None, registry=None, tracer=None,
                 flight: Optional[FlightRecorder] = None,
                 ledger: Optional[MemoryLedger] = None,
                 memory_stats_provider: Optional[Callable[[], dict]] = None,
                 faults: Optional[FaultInjector] = None):
        self.cfg_engine = config or EngineConfig()
        self.params = model.params
        self.cfg = model.config
        self.family = model.family
        # quality-observability inputs: the serving qtype labels every
        # bigdl_tpu_quality_* sample; the load-time attribution report
        # (transformers/model.py) backs GET /v1/quality
        self.qtype = getattr(model, "qtype", None) or "bf16"
        self.quality_report = getattr(model, "quality_report", None)
        if getattr(self.family, "is_recurrent", False):
            raise ValueError(
                f"continuous batching is KV-cache based; the "
                f"{self.family.name!r} family carries recurrent state "
                "whose slots cannot be rewound/packed — serve it through "
                "model.generate() instead")
        probe_cache = self.family.new_cache(self.cfg, 1, 8, False)
        if not isinstance(probe_cache, KVCache):
            raise ValueError(
                f"the {self.family.name!r} family uses a custom cache "
                f"({type(probe_cache).__name__}) the slot engine cannot "
                "splice — serve it through model.generate() instead")
        self.eos_token_id = None
        hf = getattr(model, "hf_config", None) or {}
        eos = hf.get("eos_token_id")
        self.eos_token_id = eos[0] if isinstance(eos, list) else eos

        ce = self.cfg_engine
        B = ce.max_batch
        self.kv_cache_dtype = resolve_kv_cache_dtype(
            ce.kv_cache_dtype if ce.kv_cache_dtype != "bf16"
            else ce.kv_quantized)
        if (self.kv_cache_dtype in ("int8", "int4")
                and not getattr(self.family, "SUPPORTS_SCALED_KV", False)):
            raise ValueError(
                f"kv_cache_dtype={self.kv_cache_dtype!r} needs a family "
                f"that threads scale planes through its forward; "
                f"{getattr(self.family, 'name', '?')!r} does not "
                "(SUPPORTS_SCALED_KV)")
        # -- paged KV mode: one [P, page_size, H, hd] arena per layer +
        # host-owned block tables instead of the per-slot slab.
        # Explicit EngineConfig values validate loudly here; env-driven
        # values already passed through config.flags() (typos fall back
        # to off/auto and utils/env_check.py reports them).
        page_size = resolve_kv_page_size(
            ce.kv_page_size if ce.kv_page_size is not None
            else flags().kv_page_size)
        n_pages_spec = resolve_kv_pages(
            ce.kv_pages if ce.kv_pages is not None else flags().kv_pages)
        sharing = resolve_prefix_sharing(
            ce.prefix_sharing if ce.prefix_sharing is not None
            else flags().prefix_sharing)
        self._paged = page_size > 0
        self._page_size = page_size
        self.pool: Optional[PagePool] = None
        self.radix: Optional[RadixCache] = None
        if self._paged:
            if not getattr(self.family, "SUPPORTS_PAGED_KV", False):
                raise ValueError(
                    f"kv_page_size={page_size} needs a family with a "
                    f"paged forward (SUPPORTS_PAGED_KV); "
                    f"{getattr(self.family, 'name', '?')!r} has none")
            if ce.max_seq % page_size:
                raise ValueError(
                    f"max_seq {ce.max_seq} must be a multiple of "
                    f"kv_page_size {page_size}")
            self._pages_per_seq = ce.max_seq // page_size
            self._num_pages = n_pages_spec or B * self._pages_per_seq + 1
            self.cache = self.family.new_paged_cache(
                self.cfg, self._num_pages, page_size, B,
                kv_cache_dtype=self.kv_cache_dtype)
            self.pool = PagePool(self._num_pages, page_size)
            if sharing != "off":
                self.radix = RadixCache(self.pool)
            # host-authoritative block tables ([B, pages_per_seq] int32,
            # 0 = null page); the device mirror refreshes lazily through
            # _bt() only when a row changed, so the per-token step path
            # never indexes page state on the host
            self._bt_np = np.zeros((B, self._pages_per_seq), np.int32)
            self._bt_dev = jnp.asarray(self._bt_np)
            self._bt_dirty = False
        else:
            self._pages_per_seq = 0
            self._num_pages = 0
            self.cache = init_cache(
                self.cfg.num_hidden_layers, B, ce.max_seq,
                self.cfg.num_key_value_heads, self.cfg.hd,
                kv_cache_dtype=self.kv_cache_dtype, per_slot_pos=True)

        self.slots = [_Slot() for _ in range(B)]
        # deque (admission pops the front; preemption appends the back)
        self.waiting: "collections.deque[Request]" = collections.deque()
        self._outputs: Dict[str, List[RequestOutput]] = {}
        self._abort: set = set()
        self._lock = threading.Lock()
        # n/best_of fan-out: child request id -> (parent id, choice index)
        self._children: Dict[str, Tuple[str, int]] = {}
        self._fanouts: Dict[str, _Fanout] = {}
        self._stall_steps = 0       # consecutive steps with starved queue
        self._step_idx = 0          # lifetime step() counter
        self._last_step_ts = time.monotonic()   # step-loop heartbeat

        # observability backbone, created BEFORE the jit definitions so
        # tracked_jit can mirror compile metrics into the engine's
        # registry (bigdl_tpu/observability/__init__.py has the full
        # metric-name <-> engine-field map). Families are get-or-create,
        # so sharing a registry across engines or with the probe/spec
        # sites is safe.
        self.registry = registry if registry is not None \
            else default_registry()
        self.tracer = tracer if tracer is not None else RequestTracer()
        # distributed-trace span store (observability/disttrace.py):
        # per-request queue_wait/prefill/decode spans and per-step
        # dispatch/device sub-spans for requests carrying a traceparent;
        # the API server serves it at GET /v1/internal/spans
        self.spans = SpanRecorder(service="engine")
        # flight recorder: bounded ring of structured step/scheduling
        # events; its tail is the core of every postmortem dump
        self.flight = flight if flight is not None else FlightRecorder()
        # HBM ledger: static bytes for params + batched KV registered
        # below, live device telemetry for headroom-aware admission. A
        # passed-in ledger keeps its own budget fraction; tests inject
        # memory_stats_provider for deterministic deferral.
        self.ledger = ledger if ledger is not None else MemoryLedger(
            stats_provider=memory_stats_provider,
            budget_fraction=ce.hbm_budget_fraction)
        self._deferred_admissions = 0   # lifetime deferral count
        self._deferred_streak = False   # one flight event per streak

        # -- robustness: fault injection + lifecycle hardening
        # (bigdl_tpu/robustness/). The injector's hooks sit in the real
        # step/admit/prefill/logits paths below; with no spec configured
        # each is one attribute check.
        self.faults = faults if faults is not None \
            else FaultInjector.from_env()
        self.faults.on_fire = self._on_fault_fired
        try:
            self._request_deadline_ms = (
                ce.request_deadline_ms
                if ce.request_deadline_ms is not None
                else resolve_request_deadline_ms())
        except ValueError:
            self._request_deadline_ms = None    # env_check reports it
        try:
            self._drain_timeout_sec = (
                ce.drain_timeout_sec if ce.drain_timeout_sec is not None
                else resolve_drain_timeout_sec())
        except ValueError:
            self._drain_timeout_sec = 30.0      # env_check reports it
        self._draining = False
        self._drain_deadline: Optional[float] = None
        self._any_deadline = False      # fast path: skip expiry scans
        self._consec_failures = 0       # consecutive failing step()s
        self._retry_total = 0           # lifetime retried steps

        # -- overload control (serving/overload.py): QoS priorities,
        # tenant fair queuing + rate limits, bounded queues with early
        # shedding, and the brownout ladder. Always constructed — the
        # queue-depth hard bound protects even deployments that leave
        # every policy knob at its default.
        try:
            oc = ce.overload or OverloadConfig()
            if ce.max_queue_depth is not None:
                oc = dataclasses.replace(
                    oc, max_queue_depth=ce.max_queue_depth)
            self.overload = OverloadController(oc)
        except ValueError:
            # env_check reports the bad knob; serve with pure defaults
            self.overload = OverloadController(OverloadConfig(
                qos_default="standard", qos_aging_sec=5.0,
                tenant_rps=0.0, tenant_tps=0.0, tenant_burst=4.0,
                brownout_high=0.85, brownout_low=0.6,
                max_queue_depth=ce.max_queue_depth or 256,
                max_queue_bytes=64 << 20))
        # decode-step latency EWMA + its observed floor: the queue-wait
        # admission test and the brownout latency-inflation signal
        self._tpot_ewma = 0.0
        self._tpot_floor: Optional[float] = None
        # host-dispatch share of the decode step (dispatch-return vs
        # blocked block_until_ready — the bench.py tunnel_overhead_ms
        # measurement, run every step): the attribution denominator for
        # the decode roofline gap, surfaced as stats_snapshot()
        # dispatch_overhead_ms and ratcheted by tools/bench_diff.py
        self._dispatch_ewma = 0.0
        # recent finish timestamps -> measured drain rate (Retry-After)
        self._finish_times: "collections.deque[float]" = \
            collections.deque(maxlen=64)

        # context-parallel overflow lane (long prompts)
        self._cp_mesh = cp_mesh
        self._cp_axis = cp_mesh.axis_names[0] if cp_mesh is not None \
            else None
        self._cp_waiting: "collections.deque[Request]" = collections.deque()
        self._cp_active: Optional[_CPActive] = None
        self._cp_admitting: Optional[_CPAdmitting] = None
        if cp_mesh is not None and ce.cp_max_seq:
            n_cp = cp_mesh.shape[self._cp_axis]
            if ce.cp_max_seq % n_cp:
                raise ValueError(f"cp_max_seq {ce.cp_max_seq} must be a "
                                 f"multiple of the mesh size {n_cp}")
            layer_keys = set(self.params.get("layers") or {})
            if not ({"q_proj", "qkv_proj"} & layer_keys):
                raise ValueError(
                    "context-parallel serving needs the generalized "
                    "llama-family parameter layout (layers/q_proj or "
                    "the merged layers/qkv_proj)")

        fwd = self.family.forward

        @functools.partial(tracked_jit, "engine_decode",
                           registry=self.registry, donate_argnums=(2,))
        def decode(params, tokens, cache):   # tokens [B] int32
            logits, cache = fwd(params, self.cfg, tokens[:, None], cache)
            return logits[:, -1, :], cache

        self._decode = decode
        # greedy fast path: one fused argmax, [B] ints across the tunnel
        self._argmax = tracked_jit(
            "engine_argmax",
            lambda lg: jnp.argmax(lg, axis=-1).astype(jnp.int32),
            registry=self.registry)
        # per-slot logits health: [B] bools across the tunnel — the
        # blast-radius check that turns a NaN/Inf decode row into ONE
        # quarantined request instead of a poisoned batch
        self._health = tracked_jit(
            "engine_health",
            lambda lg: jnp.isfinite(lg).all(axis=-1),
            registry=self.registry)
        # batched DEVICE sampler: temperature / top-k / top-p via
        # gumbel-max, one seeded stream per slot. Serves every slot that
        # needs no penalty counts and no logprobs — the [B, V] logits
        # never leave the chip for such batches, extending the greedy
        # fast path to sampled traffic (host _sample_host remains the
        # full-featured path). Seeded slots derive their key from
        # (seed, absolute position), so a preempt-resume — or a change
        # in WHICH other requests share the batch — replays identically.
        @functools.partial(tracked_jit, "engine_sample_device",
                           registry=self.registry)
        def sample_device(lg, temps, top_ks, top_ps, seeds, poss):
            return _device_sample_rows(lg, temps, top_ks, top_ps,
                                       seeds, poss)

        self._sample_device = sample_device

        # resident single-dispatch decode step: layer-scanned forward +
        # per-slot health check + on-device sampling fused into ONE
        # executable, so a pure-decode engine step costs exactly one
        # host dispatch (vs decode + health + argmax/sampler = 3). The
        # greedy branch is the same fused argmax as engine_argmax (so
        # greedy serving stays byte-identical) and the sampled branch
        # is the shared _device_sample_rows body (so seeded streams
        # replay identically whichever path served them). Used by
        # _step_inner when every active slot is device-samplable and
        # no fault clauses are live (poison_rows needs the logits on
        # the host side of the dispatch).
        @functools.partial(tracked_jit, "engine_decode_resident",
                           registry=self.registry, donate_argnums=(2,),
                           static_argnames=("all_greedy", "with_quality"))
        def decode_resident(params, tokens, cache, temps, top_ks,
                            top_ps, seeds, poss, *, all_greedy,
                            with_quality=False):
            logits, cache = fwd(params, self.cfg, tokens[:, None], cache)
            lg = logits[:, -1, :]
            finite = jnp.isfinite(lg).all(axis=-1)
            if all_greedy:
                toks = jnp.argmax(lg, axis=-1).astype(jnp.int32)
            else:
                toks = _device_sample_rows(lg, temps, top_ks, top_ps,
                                           seeds, poss)
            qrows = None
            if with_quality:
                # live decode-quality telemetry, fused into the SAME
                # executable so the single-dispatch invariant survives:
                # per-slot chosen-token logprob, full-softmax entropy,
                # and top-1 margin, returned as one [B, 3] f32 block
                # the host pulls alongside toks/finite
                lp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
                chosen = jnp.take_along_axis(
                    lp, toks[:, None].astype(jnp.int32), axis=-1)[:, 0]
                entropy = -jnp.sum(jnp.exp(lp) * lp, axis=-1)
                top2, _ = jax.lax.top_k(lg.astype(jnp.float32), 2)
                margin = top2[:, 0] - top2[:, 1]
                qrows = jnp.stack([chosen, entropy, margin], axis=-1)
            return toks, finite, cache, qrows

        self._decode_resident = decode_resident

        # prefill one sequence on a private 1-row cache, then splice its K/V
        # (and, for scaled dtypes, the per-token scale planes) and position
        # into the batched cache at the slot index
        @functools.partial(tracked_jit, "engine_insert",
                           registry=self.registry, donate_argnums=(0,))
        def insert(cache: KVCache, cache1: KVCache, slot, plen):
            # the private cache may be chunk-padded past max_seq; the
            # tail holds only pad garbage (plen <= max_seq is enforced
            # at add_request), so clip the splice statically
            max_s = cache.k.shape[2]
            k1 = cache1.k[:, :, :max_s]
            v1 = cache1.v[:, :, :max_s]
            k = jax.lax.dynamic_update_slice(
                cache.k, k1.astype(cache.k.dtype), (0, slot, 0, 0, 0))
            v = jax.lax.dynamic_update_slice(
                cache.v, v1.astype(cache.v.dtype), (0, slot, 0, 0, 0))
            ks = vs = None
            if cache.k_scale is not None:
                ks = jax.lax.dynamic_update_slice(
                    cache.k_scale, cache1.k_scale[:, :, :max_s],
                    (0, slot, 0, 0))
                vs = jax.lax.dynamic_update_slice(
                    cache.v_scale, cache1.v_scale[:, :, :max_s],
                    (0, slot, 0, 0))
            pos = cache.pos.at[slot].set(plen)
            return KVCache(k, v, pos, ks, vs)

        self._insert = insert

        @functools.partial(tracked_jit, "engine_prefill",
                           registry=self.registry, donate_argnums=(2,))
        def prefill_chunk(params, tokens, cache1):
            # one tracked fn; XLA caches an executable per (chunk width,
            # cache bucket, kv dtype) shape tuple — the compile table's
            # per-signature rows ARE the engine's prefill executables
            return fwd(params, self.cfg, tokens, cache1)

        self._prefill = prefill_chunk

        # -- paged-mode executables. Prefill stays on the slab path (a
        # private 1-row cache1 per admission); only the splice into the
        # batched store, the cross-request page machinery, and the
        # decode step itself change shape.
        if self._paged:
            fwd_paged = self.family.forward_paged

            # paged decode: same contract as engine_decode, but K/V
            # gathers go through the block tables INSIDE the jit — the
            # host never indexes the arena per token (graftlint's
            # paged-host-gather rule holds the line)
            @functools.partial(tracked_jit, "engine_decode_paged",
                               registry=self.registry,
                               donate_argnums=(2,))
            def decode_paged(params, tokens, cache, block_tables):
                logits, cache = fwd_paged(
                    params, self.cfg, tokens[:, None], cache,
                    block_tables, last_only=True)
                return logits[:, -1, :], cache

            self._decode_paged = decode_paged

            # splice a finished admission's private cache1 into the
            # arena: per-token (page, offset) coordinates are computed
            # on host ONCE per admission; positions inside the shared
            # prefix (and chunk padding) point at the null page, the
            # arena's write sink
            @functools.partial(tracked_jit, "engine_insert_paged",
                               registry=self.registry,
                               donate_argnums=(0,))
            def insert_paged(cache, cache1, phys, off, slot, plen):
                cap = phys.shape[0]
                k = cache.k.at[:, phys, off].set(
                    cache1.k[:, 0, :cap].astype(cache.k.dtype))
                v = cache.v.at[:, phys, off].set(
                    cache1.v[:, 0, :cap].astype(cache.v.dtype))
                ks = vs = None
                if cache.k_scale is not None:
                    ks = cache.k_scale.at[:, phys, off].set(
                        cache1.k_scale[:, 0, :cap])
                    vs = cache.v_scale.at[:, phys, off].set(
                        cache1.v_scale[:, 0, :cap])
                pos = cache.pos.at[slot].set(plen)
                return PagedKVCache(k, v, pos, ks, vs)

            self._insert_paged = insert_paged

            # seed a fresh cache1 from shared radix pages: one dense
            # gather of n full pages into positions [0, n*page_size)
            @functools.partial(tracked_jit, "engine_seed_pages",
                               registry=self.registry,
                               donate_argnums=(0,))
            def seed_pages(cache1, cache, pages, consumed):
                planes = gather_pages_dense(
                    cache.k, cache.v, pages,
                    cache_ks=cache.k_scale, cache_vs=cache.v_scale)
                k = jax.lax.dynamic_update_slice(
                    cache1.k, planes[0].astype(cache1.k.dtype),
                    (0, 0, 0, 0, 0))
                v = jax.lax.dynamic_update_slice(
                    cache1.v, planes[1].astype(cache1.v.dtype),
                    (0, 0, 0, 0, 0))
                ks = vs = None
                if cache1.k_scale is not None:
                    ks = jax.lax.dynamic_update_slice(
                        cache1.k_scale, planes[2], (0, 0, 0, 0))
                    vs = jax.lax.dynamic_update_slice(
                        cache1.v_scale, planes[3], (0, 0, 0, 0))
                pos = jnp.full_like(cache1.pos, consumed)
                return KVCache(k, v, pos, ks, vs)

            self._seed_pages = seed_pages

            # batched copy-on-write: gather every shared source page,
            # scatter into the fresh destinations. Pairs are padded to
            # max_batch with null->null self-copies so ONE executable
            # serves every CoW step regardless of how many slots hit
            # their shared tail page simultaneously.
            @functools.partial(tracked_jit, "engine_cow_pages",
                               registry=self.registry,
                               donate_argnums=(0,))
            def cow_pages(cache, srcs, dsts):
                planes = cow_copy_pages(
                    cache.k, cache.v, srcs, dsts,
                    cache_ks=cache.k_scale, cache_vs=cache.v_scale)
                if cache.k_scale is not None:
                    k, v, ks, vs = planes
                else:
                    (k, v), ks, vs = planes, None, None
                return PagedKVCache(k, v, cache.pos, ks, vs)

            self._cow_pages = cow_pages

        # chunk width must divide the private cache length or the last
        # chunk's dynamic_update_slice would CLAMP its start index and
        # silently overwrite earlier positions — normalize to a power of
        # two and size the cache up to a multiple of it (_admission_step)
        self._chunk = 1 << (max(1, ce.prefill_chunk).bit_length() - 1)
        self._admitting: Optional[_Admission] = None
        # prefix cache: {prompt_tuple: (k, v[, k_scale, v_scale])} in
        # insertion (LRU) order — host DRAM, not HBM
        self._prefix_cache: Dict[Tuple[int, ...], Tuple[Any, ...]] = {}
        # lookup index over the prefix cache: length (a multiple of the
        # granularity g) -> {hash(prompt[:length]): stored key}. Admission
        # probes O(max_seq/chunk) bucketed lengths instead of scanning
        # every entry token-by-token. Usable only when every possible
        # chunk width is a multiple of g; otherwise _seed_from_prefix_cache
        # falls back to the linear scan.
        g = min(self._chunk, max(1, ce.prefill_bucket))
        self._prefix_g = g if (self._chunk % g == 0
                               and ce.prefill_bucket % g == 0) else 0
        self._prefix_index: Dict[int, Dict[int, Tuple[int, ...]]] = {}
        # KV handoff inbox: (prompt_tuple, planes) staged by HTTP
        # handler threads (stage_handoff), drained into the prefix
        # cache by the engine loop at the top of _admission_step. The
        # deque is the only cross-thread structure — append/popleft
        # are atomic, and all prefix-cache mutation stays on the
        # engine thread.
        self._handoff_in: "collections.deque" = collections.deque()
        # staged handoff keys in arrival order, engine-thread only:
        # bounds how many remote snapshots can pin host DRAM when the
        # local prefix cache is disabled (prefix_cache_entries == 0)
        self._handoff_keys: "collections.deque" = collections.deque()
        # -- live sequence migration (export_sequence/import_sequence).
        # HTTP sender threads only touch the thread-safe set/deques and
        # the _lock-guarded dicts; every slot/page/cache mutation stays
        # on the engine thread (_migration_step / _drain_migrations).
        self._migrate_req: set = set()      # rids to suspend + export
        self._migration_out: Dict[str, dict] = {}   # rid -> wire state
        self._migration_meta: Dict[str, dict] = {}  # rid -> local resume
        self._migration_done: "collections.deque" = collections.deque()
        self._migration_fail: "collections.deque" = collections.deque()
        self._migration_in: "collections.deque" = collections.deque()
        # target-side staging: resume_id -> (state, staged_at). A lost
        # commit-ack means the source resumed locally — the stale copy
        # here must expire UNCLAIMED or the sequence would run twice.
        self._migration_staged: Dict[str, Tuple[dict, float]] = {}
        # resume_id -> (imported pages, kv_len, staged_at); claimed by
        # _paged_admit, expired (pages decref'd) with the stage above
        self._migration_pages: Dict[str, Tuple[List[int], int,
                                               float]] = {}
        self._migration_ttl = 30.0
        self._mig: Dict[str, int] = {oc: 0 for oc in MIGRATION_OUTCOMES}
        self._mig["migrated_tokens_total"] = 0
        self._mig["recomputed_tokens_total"] = 0

        # -- metric families (registry/tracer/flight created above,
        # before the jit definitions)
        m = self.registry
        self._m_phase = m.histogram(
            "bigdl_tpu_request_phase_seconds",
            "Per-request phase latency (queue wait, prefill, decode).",
            labelnames=("phase",))
        for ph in ("queue", "prefill", "decode"):   # render from scrape 1
            self._m_phase.labels(ph)
        self._m_step_phase = m.histogram(
            "bigdl_tpu_step_phase_seconds",
            "Engine step critical-path decomposition: per-request "
            "queue_wait/prefill, per-step host dispatch vs device "
            "compute (blocked block_until_ready on the decode result).",
            labelnames=("phase",))
        for ph in ("queue_wait", "prefill", "dispatch", "device"):
            self._m_step_phase.labels(ph)   # render from scrape 1
        self._m_ttft = m.histogram(
            "bigdl_tpu_ttft_seconds",
            "Time to first token: arrival to first sampled token.")
        self._m_tpot = m.histogram(
            "bigdl_tpu_tpot_seconds",
            "Time per output token: batched decode step wall time "
            "(every active stream advances one token per step).")
        self._m_occupancy = m.gauge(
            "bigdl_tpu_slot_occupancy", "Active decode slots.")
        self._m_queue_depth = m.gauge(
            "bigdl_tpu_queue_depth",
            "Requests waiting for admission (slot + CP lanes).")
        self._m_admissions = m.counter(
            "bigdl_tpu_admissions_total",
            "Completed admissions (prefill finished, slot running).")
        self._m_preemptions = m.counter(
            "bigdl_tpu_preemptions_total",
            "Sequences evicted to the queue by the starvation guard.")
        self._m_stall_trips = m.counter(
            "bigdl_tpu_stall_guard_trips_total",
            "Times the stall guard reached preempt_after_steps.")
        self._m_finished = m.counter(
            "bigdl_tpu_requests_finished_total",
            "Finished sequences by reason.", labelnames=("reason",))
        self._m_steps = m.counter(
            "bigdl_tpu_engine_steps_total",
            "step() iterations that did work.")
        self._m_tokens = m.counter(
            "bigdl_tpu_tokens_generated_total",
            "Tokens emitted to clients.")
        self._m_handoff_staged = m.counter(
            "bigdl_tpu_handoff_staged_total",
            "Remote KV-handoff snapshots staged into the prefix cache.")
        # live-migration observability: outcomes, source-side wall
        # time, and the migrated-vs-recomputed token ledger the bench
        # rolling-restart lane and tools/bench_diff.py gate on
        self._m_migrations = m.counter(
            "bigdl_tpu_migrations_total",
            "Live sequence migrations by outcome (bench_diff gates "
            "outcome=\"failed\" lower-is-better).",
            labelnames=("outcome",))
        for oc in MIGRATION_OUTCOMES:    # render from scrape 1
            self._m_migrations.labels(oc)
        self._m_migration_ms = m.histogram(
            "bigdl_tpu_migration_ms",
            "Source-side migration wall milliseconds, slot export to "
            "commit-ack.",
            buckets=(1.0, 5.0, 25.0, 100.0, 500.0, 2500.0, 10000.0))
        self._m_migrated_tokens = m.counter(
            "bigdl_tpu_migrated_tokens_total",
            "Generated-so-far tokens preserved across committed "
            "migrations (decode work NOT thrown away by a drain, "
            "rolling restart, or scale-down).")
        self._m_recomputed_tokens = m.counter(
            "bigdl_tpu_recomputed_tokens_total",
            "Generated-so-far tokens whose KV must be recomputed "
            "because a failed migration had no staged copy to fall "
            "back on (bench_diff gates this lower-is-better).")
        # pre-register the families fed by ops/probing.py and
        # speculative.py so /metrics exposes them before the first
        # probe or speculative round runs in this process
        m.counter("bigdl_tpu_kernel_probe_total",
                  "Kernel compile-probe outcomes "
                  "(compiled vs XLA fallback) per kernel.",
                  labelnames=("kernel", "outcome"))
        m.histogram("bigdl_tpu_spec_accept_ratio",
                    "Speculative decoding acceptance ratio per "
                    "verify round.", labelnames=("mode",),
                    buckets=RATIO_BUCKETS)
        self._m_deferred = m.counter(
            "bigdl_tpu_admission_deferred_total",
            "Admissions deferred by the headroom guard, by reason.",
            labelnames=("reason",))
        for r in ("memory", "pages"):   # render from scrape 1
            self._m_deferred.labels(r)
        # paged-KV observability: pool pressure + radix-tree traffic.
        # PagePool/RadixCache keep plain host ints (scheduling code
        # stays metrics-free); _update_gauges mirrors them by delta-inc
        # once per working step.
        self._m_pool_exhausted = m.counter(
            "bigdl_tpu_page_pool_exhausted_total",
            "KV page-pool allocation failures (admissions deferred on "
            "pages, copy-on-write eviction fallbacks). bench_diff "
            "gates this lower-is-better.")
        self._m_radix_lookups = m.counter(
            "bigdl_tpu_prefix_radix_lookups_total",
            "Radix prefix-tree lookups at admission, by outcome.",
            labelnames=("outcome",))
        for oc in ("hit", "miss"):       # render from scrape 1
            self._m_radix_lookups.labels(oc)
        self._m_radix_tokens = m.counter(
            "bigdl_tpu_prefix_radix_tokens_total",
            "Prompt tokens looked up vs already resident in shared "
            "radix pages.", labelnames=("kind",))
        for kd in ("looked_up", "hit"):  # render from scrape 1
            self._m_radix_tokens.labels(kd)
        self._pub_pool_exhausted = 0     # delta-inc mirror baselines
        self._pub_radix = {"lookups": 0, "hits": 0,
                           "lookup_tokens": 0, "hit_tokens": 0}
        self._m_quarantined = m.counter(
            "bigdl_tpu_requests_quarantined_total",
            "Requests failed by blast-radius isolation, by reason.",
            labelnames=("reason",))
        for r in ("nan_logits", "crash_loop"):   # render from scrape 1
            self._m_quarantined.labels(r)
        self._m_retries = m.counter(
            "bigdl_tpu_step_retries_total",
            "Engine steps retried after a transient failure.")
        self._m_faults = m.counter(
            "bigdl_tpu_faults_injected_total",
            "Faults fired by the injection harness "
            "($BIGDL_TPU_FAULT_SPEC), by kind.", labelnames=("kind",))
        self._m_draining = m.gauge(
            "bigdl_tpu_engine_draining",
            "1 while the engine refuses new requests (graceful drain).")
        self._m_shed = m.counter(
            "bigdl_tpu_requests_shed_total",
            "Requests rejected at admission by overload control, by "
            "shed reason and QoS class.", labelnames=("reason", "qos"))
        for r in SHED_REASONS:           # render from scrape 1
            for q in QOS_CLASSES:
                self._m_shed.labels(r, q)
        self._m_brownout = m.gauge(
            "bigdl_tpu_brownout_level",
            "Brownout degradation level (0 healthy ... 3 shedding "
            "batch QoS at admission).")
        self._m_brownout.set(0)
        self._m_tenant_queued = m.gauge(
            "bigdl_tpu_tenant_queue_depth",
            "Queued requests per tenant.", labelnames=("tenant",))
        self._m_tenant_reqs = m.counter(
            "bigdl_tpu_tenant_requests_total",
            "Per-tenant admission outcomes.",
            labelnames=("tenant", "outcome"))
        # -- service-level objectives + usage metering
        # (observability/slo.py, usage.py): the SLO tracker gets TTFT /
        # TPOT / result feeds from the hooks below and evaluates
        # burn-rate alerts on a throttle inside step(); the usage
        # ledger writes one JSONL record per finished/shed request off
        # this thread and backs GET /v1/usage
        self.slo = SLOTracker(registry=m, flight=self.flight)
        self.usage = UsageLedger()
        # request id -> (tenant, qos), set at admission (fanout
        # children individually), popped at finish — the attribution
        # map for both the SLO feeds and the usage ledger
        self._usage_meta: Dict[str, Tuple[str, str]] = {}
        # batched-cache storage footprint per component (codes vs scales);
        # shapes are static for the engine lifetime, so set once
        self._weight_bytes = tree_nbytes(self.params)
        self.ledger.register(
            "weights", "engine_params", self._weight_bytes,
            family=getattr(self.family, "name",
                           type(self.family).__name__))
        if self._paged:
            # the arena is the ONE static KV allocation: admission
            # cost stays the private cache1, and page availability —
            # not worst-case per-slot bytes — gates concurrency, so
            # max_batch can rise far past what the slab admitted in
            # the same ledger budget
            publish_paged_cache_bytes(self.cache, m)
            kvb = paged_cache_bytes(self.cache)
            self.ledger.register(
                "kv_cache", "engine_paged_arena", kvb["total"],
                dtype=self.kv_cache_dtype, codes=kvb["codes"],
                scales=kvb["scales"], pages=self._num_pages,
                page_size=self._page_size)
            self._kv_bytes_per_page = kvb["total"] // self._num_pages
            self._kv_bytes_per_slot = (
                self._kv_bytes_per_page * self._pages_per_seq)
        else:
            publish_kv_cache_bytes(self.cache, m)
            # static ledger entries: params (packed, QTensor/int4-aware)
            # and the batched KV cache; per-slot bytes drive the
            # admission cost
            kvb = kv_cache_bytes(self.cache)
            self.ledger.register(
                "kv_cache", "engine_batched", kvb["total"],
                dtype=self.kv_cache_dtype, codes=kvb["codes"],
                scales=kvb["scales"], slots=B)
            self._kv_bytes_per_slot = kvb["total"] // B
            self._kv_bytes_per_page = 0
        self.ledger.publish(m)

        # -- live roofline attribution + perf-regression sentinel
        # (observability/roofline.py + sentinel.py). The decode gauge is
        # the bench decode_hbm_roofline_util formula evaluated each
        # working step from the measured step wall time; tests assert
        # 4-decimal agreement with bench.py's offline math.
        self._m_roofline = m.gauge(
            "bigdl_tpu_roofline_util",
            "Live roofline utilization per phase: decode is "
            "bandwidth-bound (ideal bytes-ms over measured ms), "
            "prefill is compute-bound (MFU).", labelnames=("phase",))
        for ph in ("decode", "prefill"):    # render from scrape 1
            self._m_roofline.labels(ph)
        self._m_decode_ideal = m.gauge(
            "bigdl_tpu_decode_ideal_ms",
            "Bandwidth-bound floor for the current decode step "
            "(weights + live KV over peak HBM GB/s).")
        self._m_perf_regress = m.counter(
            "bigdl_tpu_perf_regression_total",
            "Sentinel trips by regressed metric "
            "(tools/bench_diff.py gates this at 0).",
            labelnames=("metric",))
        from bigdl_tpu.observability.sentinel import METRICS as \
            _SENTINEL_METRICS
        for mt in _SENTINEL_METRICS:        # render from scrape 1
            self._m_perf_regress.labels(mt)
        self._last_perf: Optional[dict] = None     # last decode step
        self._last_prefill_perf: Optional[dict] = None
        self._pending_perf: Optional[Tuple[int, int]] = None
        self._auto_capture_dir: Optional[str] = None
        use_sentinel = (ce.sentinel if ce.sentinel is not None
                        else sentinel_enabled())
        self.sentinel: Optional[PerfSentinel] = None
        if use_sentinel:
            self.sentinel = PerfSentinel(
                history_path=ce.perf_history,
                on_trip=self._on_perf_trip,
                on_recover=self._on_perf_recover)

        # -- live quality telemetry + QualitySentinel (observability/
        # quality.py). All histogram samples carry (qtype,
        # kv_cache_dtype, qos) so a fleet scrape can slice quality by
        # quantization format. Families exist from scrape 1 for the
        # standard QoS classes (render-before-traffic idiom above).
        self._use_quality = (ce.quality if ce.quality is not None
                             else quality_enabled())
        _qlabels = ("qtype", "kv_cache_dtype", "qos")
        self._m_q_logprob = m.histogram(
            "bigdl_tpu_quality_token_logprob",
            "Chosen-token logprob per decode step (resident path "
            "computes it inside the fused dispatch).",
            labelnames=_qlabels,
            buckets=(-16.0, -8.0, -4.0, -2.0, -1.0, -0.5, -0.25,
                     -0.1, -0.01, 0.0))
        self._m_q_entropy = m.histogram(
            "bigdl_tpu_quality_entropy",
            "Full-softmax entropy (nats) of the decode distribution.",
            labelnames=_qlabels,
            buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 4.0, 8.0))
        self._m_q_margin = m.histogram(
            "bigdl_tpu_quality_top1_margin",
            "Top-1 minus top-2 logit margin of the decode "
            "distribution.",
            labelnames=_qlabels,
            buckets=(0.1, 0.25, 0.5, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0))
        self._m_q_eos = m.counter(
            "bigdl_tpu_quality_eos_total",
            "EOS tokens emitted, by qtype/kv dtype/QoS.",
            labelnames=_qlabels)
        self._m_q_repeat = m.counter(
            "bigdl_tpu_quality_repeat_total",
            "Immediate token repetitions (tok == previous tok) "
            "emitted, by qtype/kv dtype/QoS.",
            labelnames=_qlabels)
        for q in QOS_CLASSES:              # render from scrape 1
            lbl = (self.qtype, self.kv_cache_dtype, q)
            self._m_q_logprob.labels(*lbl)
            self._m_q_entropy.labels(*lbl)
            self._m_q_margin.labels(*lbl)
            self._m_q_eos.labels(*lbl)
            self._m_q_repeat.labels(*lbl)
        self._m_q_probe_nll = m.gauge(
            "bigdl_tpu_quality_probe_nll",
            "Latest teacher-forced NLL over the golden probe prompts "
            "(nats/token).")
        self._m_q_regress = m.counter(
            "bigdl_tpu_quality_regression_total",
            "QualitySentinel trips by regressed metric "
            "(tools/bench_diff.py gates this at 0).",
            labelnames=("metric",))
        for mt in QUALITY_METRICS:         # render from scrape 1
            self._m_q_regress.labels(mt)
        self._last_quality: Optional[dict] = None   # last observed step
        self._last_probe: Optional[dict] = None     # last probe result
        self._quality_probe_fn = None               # lazily compiled
        try:
            self._quality_probe_steps = (
                ce.quality_probe_steps
                if ce.quality_probe_steps is not None
                else resolve_quality_probe_steps())
        except ValueError:
            self._quality_probe_steps = 0   # env_check reports it
        self.qsentinel: Optional[QualitySentinel] = None
        if self._use_quality:
            self.qsentinel = QualitySentinel(
                history_path=ce.quality_history,
                on_trip=self._on_quality_trip,
                on_recover=self._on_quality_recover)
        # annotate the compile table with analytical per-jit costs so
        # compile_table()/top_offenders() rank jits by bytes moved
        try:
            for name, c in roofline.jit_costs(
                    self.cfg, self._weight_bytes, B, ce.max_seq,
                    ce.prefill_bucket, self.kv_cache_dtype).items():
                annotate_costs(name, flops=c["flops"],
                               hbm_bytes=c["hbm_bytes"])
        except Exception:
            pass    # cost annotation is telemetry, never load-bearing

        self.flight.record(
            "engine_init", max_batch=B, max_seq=ce.max_seq,
            kv_cache_dtype=self.kv_cache_dtype,
            kv_cache_total_bytes=kvb["total"],
            kv_page_size=self._page_size, kv_pages=self._num_pages,
            prefix_sharing=self.radix is not None,
            prefill_chunk=self._chunk, family=getattr(
                self.family, "name", type(self.family).__name__))

    # -- public api ---------------------------------------------------------

    def add_request(self, request_id: str, prompt_token_ids, params=None,
                    trace=None, resume=None):
        if self._draining:
            raise EngineDraining(
                "engine is draining (admission stopped); retry against "
                "another replica")
        params = params or SamplingParams()
        ids = list(prompt_token_ids)
        long = len(ids) + 1 > self.cfg_engine.max_seq
        cp_cap = (self.cfg_engine.cp_max_seq
                  if self._cp_mesh is not None else None)
        if long and (cp_cap is None or len(ids) + 1 > cp_cap):
            raise ValueError(
                f"prompt length {len(ids)} exceeds engine max_seq "
                f"{self.cfg_engine.max_seq}"
                + ("" if cp_cap is None else
                   f" and cp_max_seq {cp_cap}"))
        if not ids:
            raise ValueError("empty prompt")
        # validate CLIENT input here (HTTP clients send raw token ids):
        # a bad id crashing inside step() would wedge the admission lane
        # for every future request
        v = self.cfg.vocab_size
        if any(not isinstance(t, (int, np.integer)) or t < 0 or t >= v
               for t in ids):
            raise ValueError(f"prompt token ids must be ints in [0, {v})")
        if params.logprobs is not None and not (
                0 <= params.logprobs < v):
            raise ValueError(f"logprobs must be in [0, {v})")
        if params.n < 1:
            raise ValueError("n must be >= 1")
        if params.max_tokens < 1:
            raise ValueError("max_tokens must be >= 1")
        best_of = params.best_of or params.n
        if best_of < params.n:
            raise ValueError(f"best_of ({best_of}) < n ({params.n})")
        if resume is not None and best_of > 1:
            # migration exports only simple (non-fanout) slots; a
            # resume of a fan-out parent has no single sampler stream
            raise ValueError("migration resume requires n=1/best_of=1")
        if params.max_time_ms is not None and params.max_time_ms <= 0:
            raise ValueError("max_time_ms must be positive")
        deadline_ms = (params.max_time_ms
                       if params.max_time_ms is not None
                       else self._request_deadline_ms)
        if deadline_ms is not None:
            self._any_deadline = True
        # -- overload control: validate QoS, run every early-shedding
        # test (RequestShed -> HTTP 429/503 with Retry-After), and
        # apply the brownout max_tokens cap — all BEFORE any engine
        # state is created for the request
        qos = params.qos or self.overload.cfg.qos_default
        if qos not in QOS_CLASSES:
            raise ValueError(
                f"qos must be one of {QOS_CLASSES}, got {qos!r}")
        params = dataclasses.replace(
            params, qos=qos, tenant=params.tenant or "default")
        if resume is None:
            self._overload_admit(request_id, ids, params, deadline_ms,
                                 best_of, trace)
            cap = self.overload.max_tokens_cap()
            if cap is not None and params.max_tokens > cap:
                params = dataclasses.replace(params, max_tokens=cap)
        # a migration resume bypasses early shedding and the brownout
        # max_tokens cap: the sequence passed admission control when it
        # first entered the fleet, its staged state is already claimed
        # (a shed here would strand it mid-stream), and a cap would
        # silently truncate tokens the client was promised. The intake
        # membrane for an overloaded target is /v1/internal/migrate_in.
        with self._lock:
            self._outputs[request_id] = []
        target = self._cp_waiting if long else self.waiting
        if best_of > 1:
            # fan out into independent child sequences; ranking needs
            # per-token logprobs, so force their computation on children
            self._fanouts[request_id] = _Fanout(request_id, params.n,
                                                best_of)
            for i in range(best_of):
                cid = f"{request_id}#{i}"
                cparams = dataclasses.replace(
                    params, n=1, best_of=None,
                    seed=None if params.seed is None else params.seed + i)
                self._children[cid] = (request_id, i)
                self._usage_meta[cid] = (params.tenant, qos)
                creq = Request(cid, list(ids), cparams)
                creq.trace = trace
                if deadline_ms is not None:
                    creq.deadline = creq.arrival + deadline_ms / 1000.0
                self.tracer.start(cid, prompt_len=len(ids),
                                  t_arrival=creq.arrival,
                                  trace=self._child_trace(trace))
                target.append(creq)
            return
        self._usage_meta[request_id] = (params.tenant, qos)
        req = Request(request_id, ids, params)
        req.trace = trace
        if deadline_ms is not None:
            req.deadline = req.arrival + deadline_ms / 1000.0
        if resume is not None:
            # live-migration resume: generation continues mid-stream.
            # The generated-so-far tail already rides in the prompt;
            # the sampler stream and logprob accumulator carry over,
            # and the source's absolute deadline (if any) keeps ticking
            # — the clock does not restart on the new replica.
            req.generated_offset = int(resume.get("generated_offset", 0))
            req.resumed_cum_logprob = float(
                resume.get("cum_logprob", 0.0))
            if resume.get("dev_seed") is not None:
                req.resume_dev_seed = int(resume["dev_seed"])
            if resume.get("resume_id"):
                req.resume_id = str(resume["resume_id"])
            if resume.get("deadline") is not None:
                req.deadline = float(resume["deadline"])
                self._any_deadline = True
        self.tracer.start(request_id, prompt_len=len(ids),
                          t_arrival=req.arrival,
                          trace=self._child_trace(trace))
        target.append(req)

    @staticmethod
    def _child_trace(trace):
        # (trace_id, parent_span_id) from the wire becomes a tracer
        # 3-tuple with a fresh span id for THIS request's engine span
        if trace is None:
            return None
        return (trace[0], trace[1], new_span_id())

    def abort_request(self, request_id: str) -> None:
        """Reference api_server behavior on client disconnect
        (vllm/entrypoints/openai/api_server.py:371)."""
        fo = self._fanouts.get(request_id)
        if fo is not None:
            for i in range(fo.best_of):
                if i not in fo.scores:       # skip finished children
                    self._abort.add(f"{request_id}#{i}")
            return
        self._abort.add(request_id)

    def step_heartbeat_age(self) -> float:
        """Seconds since the last step() entered. The driving loop
        calls step() continuously (even idle), so a large age with
        unfinished work means the step loop is WEDGED — a hung device
        transfer, a replica_hang fault — while the process (and its
        HTTP threads) look alive. `/health` turns this into a 503 so a
        supervisor can kill and replace the replica."""
        return time.monotonic() - self._last_step_ts

    def has_unfinished(self) -> bool:
        # a suspended migration-out sequence is still this replica's
        # responsibility until its sender commits or resumes it —
        # draining must not declare victory while one is in flight
        return (len(self.waiting) > 0 or self._admitting is not None
                or any(s.active for s in self.slots)
                or len(self._cp_waiting) > 0 or self._cp_active is not None
                or self._cp_admitting is not None
                or bool(self._migration_meta) or bool(self._migrate_req)
                or bool(self._migration_done)
                or bool(self._migration_fail))

    def get_outputs(self, request_id: str) -> List[RequestOutput]:
        with self._lock:
            out = self._outputs.get(request_id, [])
            if any(o.finished for o in out):
                # request complete: drop the entry (unread finished entries
                # of aborted streams must not accumulate)
                self._outputs.pop(request_id, None)
            elif out:
                self._outputs[request_id] = []
        return out

    @property
    def speculative_allowed(self) -> bool:
        """False while browned out (level >= 1): speculative lookahead
        is the first work shed under pressure. Speculative drivers
        (bigdl_tpu/speculative.py harnesses) must consult this before
        each propose/verify round when serving through an engine."""
        return self.overload.speculative_allowed

    # -- overload control ----------------------------------------------------

    def _queue_bytes(self) -> int:
        """Summed prompt footprint (int32 ids) of every queued request
        — recomputed on demand so it can never drift from the queues
        themselves (admission, expiry, preemption and aborts all
        mutate them)."""
        return 4 * (sum(len(r.prompt_token_ids) for r in self.waiting)
                    + sum(len(r.prompt_token_ids)
                          for r in self._cp_waiting))

    def _drain_rate(self) -> float:
        """Measured drain rate in finished requests/sec over the
        recent finish window (0.0 until two finishes land)."""
        ft = self._finish_times
        if len(ft) >= 2 and ft[-1] > ft[0]:
            return (len(ft) - 1) / (ft[-1] - ft[0])
        return 0.0

    def _shed_retry_after(self) -> int:
        """Retry-After seconds for a capacity shed: time for the
        current backlog to drain at the measured rate (TPOT-based
        estimate before any request finished), floored higher while
        the memory ledger reports thin headroom — a memory-bound
        engine drains slower than its request rate suggests."""
        depth = len(self.waiting) + len(self._cp_waiting)
        rate = self._drain_rate()
        if rate > 0:
            est = depth / rate
        else:
            est = max(1.0, depth * max(self._tpot_ewma, 0.01))
        hr = self.ledger.headroom()
        hb, lim = hr.get("headroom_bytes"), hr.get("bytes_limit")
        if hb is not None and lim and hb < 0.1 * lim:
            est = max(est, 5.0)
        return max(1, min(60, int(math.ceil(est))))

    def _overload_admit(self, request_id: str, ids: List[int],
                        params: SamplingParams,
                        deadline_ms: Optional[float],
                        n_seqs: int, trace=None) -> None:
        """Run the controller's early-shedding tests for one incoming
        request; on shed, count + breadcrumb and re-raise."""
        depth = len(self.waiting) + len(self._cp_waiting)
        try:
            self.overload.check_admission(
                qos=params.qos, tenant=params.tenant, n_seqs=n_seqs,
                prompt_len=len(ids), queue_depth=depth,
                queue_bytes=self._queue_bytes(),
                deadline_sec=(deadline_ms / 1000.0
                              if deadline_ms is not None else None),
                tpot_sec=self._tpot_ewma,
                retry_after_sec=self._shed_retry_after(),
                now=time.monotonic())
        except RequestShed as e:
            self._m_shed.labels(e.reason, e.qos).inc()
            # tenant ids are admission-controlled (PR-7 quota map),
            # not caller-invented — audited
            self._m_tenant_reqs.labels(e.tenant, "shed").inc()  # graftlint: disable=metric-label-cardinality
            # a shed spends the availability budget and is a ledger
            # line the tenant can reconcile against their 429s
            self.slo.observe_result(e.qos, "shed")
            self.usage.record_shed(request_id, e.tenant, e.qos,
                                   e.reason)
            self.flight.record(
                "shed", step=self._step_idx, request_id=request_id,
                reason=e.reason, qos=e.qos, tenant=e.tenant,
                retry_after_sec=e.retry_after_sec, queue_depth=depth,
                brownout_level=self.overload.level,
                **({"trace_id": trace[0]} if trace else {}))
            if trace is not None:
                self.spans.annotate(trace[0], "shed", parent_id=trace[1],
                                    request_id=request_id,
                                    reason=e.reason, qos=e.qos,
                                    tenant=e.tenant)
            raise
        # tenant ids are admission-controlled (PR-7 quota map) —
        # audited
        self._m_tenant_reqs.labels(params.tenant, "admitted").inc()  # graftlint: disable=metric-label-cardinality

    def _overload_pressure(self) -> float:
        """Measured pressure in [0, 1]: worst of queue-depth ratio,
        memory-ledger headroom exhaustion, and decode-step latency
        inflation over its observed floor (3x the floor saturates)."""
        p = ((len(self.waiting) + len(self._cp_waiting))
             / max(1, self.overload.cfg.max_queue_depth))
        hr = self.ledger.headroom()
        hb, lim = hr.get("headroom_bytes"), hr.get("bytes_limit")
        if hb is not None and lim:
            p = max(p, 1.0 - hb / lim)
        if self._tpot_floor and self._tpot_ewma > self._tpot_floor:
            p = max(p, min(1.0, (self._tpot_ewma / self._tpot_floor
                                 - 1.0) / 2.0))
        return min(1.0, max(0.0, p))

    def _update_brownout(self) -> None:
        pressure = self._overload_pressure()
        storm = self.faults.storm_pressure(self._step_idx)
        if storm is not None:
            pressure = max(pressure, storm)
        if self.overload.update_pressure(pressure) is not None:
            self._m_brownout.set(self.overload.level)
            self.flight.record(
                "brownout", step=self._step_idx,
                level=self.overload.level, pressure=round(pressure, 4),
                speculative_allowed=self.overload.speculative_allowed)
            self.spans.annotate_recent(
                "brownout", level=self.overload.level,
                pressure=round(pressure, 4))

    # -- engine internals ---------------------------------------------------

    def _bucket(self, n: int) -> int:
        b = self.cfg_engine.prefill_bucket
        while b < n:
            b *= 2
        return min(b, self.cfg_engine.max_seq)

    def _admission_cost(self, prompt_len: int) -> int:
        """HBM bytes the admission of a prompt of this length newly
        allocates: its private 1-row prefill cache, sized exactly as
        `_admission_step` will size it (chunk-multiple >= bucket)."""
        bucket = self._bucket(prompt_len)
        chunk = min(self._chunk, bucket)
        alloc = -(-bucket // chunk) * chunk
        return kv_cache_nbytes(
            self.cfg.num_hidden_layers, 1, alloc,
            self.cfg.num_key_value_heads, self.cfg.hd,
            self.kv_cache_dtype)["total"]

    def _admission_step(self) -> None:
        """Advance chunked admission by AT MOST one chunk (bounds the
        decode gap a long prompt can cause). Starts a new admission when
        a slot is free and the queue is non-empty."""
        self._drain_migrations()    # before handoffs: slab-mode imports
        self._drain_handoffs()      # ride the handoff staging inbox
        a = self._admitting
        if a is None:
            free = next((i for i, s in enumerate(self.slots)
                         if not s.active), None)
            if free is None:
                return
            # overload-aware scheduling replaces pure FCFS: strict QoS
            # priority with aging promotion, then least-served tenant
            # (deficit round-robin, quantum 1), then arrival order. The
            # pick runs over a snapshot (HTTP threads append
            # concurrently) and is removed by identity.
            req = None
            while req is None:
                snapshot = list(self.waiting)
                if not snapshot:
                    return
                cand = snapshot[self.overload.select_index(
                    snapshot, time.time())]
                try:
                    self.waiting.remove(cand)
                except ValueError:
                    return               # raced with another mutation
                if cand.request_id in self._abort:
                    # aborted while still queued: the client is owed a
                    # finished output or its poll loop never ends
                    self._abort.discard(cand.request_id)
                    self._push_output(cand.request_id, RequestOutput(
                        cand.request_id, [], True, "abort"))
                    self._obs_finish(cand.request_id, "abort")
                    cand = None
                req = cand
            # headroom guard: the admission's private prefill cache is
            # the one new HBM allocation this path makes — defer (FCFS
            # order kept, request back at the FRONT) while it would
            # push bytes_in_use past the budget. would_fit() is None on
            # backends without memory_stats(): always admit there.
            cost = self._admission_cost(len(req.prompt_token_ids))
            if self.ledger.would_fit(cost) is False:
                self.waiting.appendleft(req)
                self._deferred_admissions += 1
                self._m_deferred.labels("memory").inc()
                if not self._deferred_streak:
                    self._deferred_streak = True
                    hr = self.ledger.headroom()
                    self.flight.record(
                        "admit_deferred", step=self._step_idx,
                        request_id=req.request_id, reason="memory",
                        needed_bytes=cost,
                        headroom_bytes=hr.get("headroom_bytes"),
                        bytes_limit=hr.get("bytes_limit"))
                return
            self._deferred_streak = False
            self.overload.note_scheduled(req.params.tenant or "default")
            # private cache sized to a chunk multiple (>= bucket) so no
            # chunk write can straddle the end; _insert clips the splice
            # back down to the batched cache's max_seq. Brownout level
            # >= 2 shrinks the chunk (still a power of two) so admission
            # work yields to in-flight decodes sooner under pressure.
            bucket = self._bucket(len(req.prompt_token_ids))
            chunk = min(max(1, self._chunk
                            >> self.overload.chunk_shift()), bucket)
            alloc = -(-bucket // chunk) * chunk
            shared_pages = new_pages = None
            if self._paged:
                # page-side reservation FIRST (before the cache1 HBM
                # allocation): radix longest-prefix match + worst-case
                # page grab, or a requeue-and-defer on exhaustion
                paged_adm = self._paged_admit(req, chunk)
                if paged_adm is None:
                    return
                consumed, shared_pages, new_pages = paged_adm
            cache1 = init_cache(
                self.cfg.num_hidden_layers, 1, alloc,
                self.cfg.num_key_value_heads, self.cfg.hd,
                kv_cache_dtype=self.kv_cache_dtype)
            if self._paged:
                if consumed:
                    cache1 = self._seed_pages(
                        cache1, self.cache,
                        jnp.asarray(np.asarray(shared_pages, np.int32)),
                        jnp.asarray(consumed, jnp.int32))
            else:
                consumed, seed_kv = self._seed_from_prefix_cache(
                    req.prompt_token_ids, chunk)
                if consumed:
                    k_np, v_np = seed_kv[0], seed_kv[1]
                    kb = np.zeros(cache1.k.shape, k_np.dtype)
                    vb = np.zeros_like(kb)
                    kb[:, :, :consumed] = k_np[:, :, :consumed]
                    vb[:, :, :consumed] = v_np[:, :, :consumed]
                    ksb = vsb = None
                    if cache1.k_scale is not None:
                        ks_np, vs_np = seed_kv[2], seed_kv[3]
                        ksb = np.zeros(cache1.k_scale.shape, np.float32)
                        vsb = np.zeros_like(ksb)
                        ksb[:, :, :consumed] = ks_np[:, :, :consumed]
                        vsb[:, :, :consumed] = vs_np[:, :, :consumed]
                        ksb = jnp.asarray(ksb)
                        vsb = jnp.asarray(vsb)
                    cache1 = KVCache(jnp.asarray(kb), jnp.asarray(vb),
                                     jnp.asarray(consumed, jnp.int32),
                                     ksb, vsb)
            a = self._admitting = _Admission(req, free, bucket, consumed,
                                             cache1, chunk,
                                             shared_pages=shared_pages,
                                             new_pages=new_pages)
            self.tracer.admitted(req.request_id)
            self.flight.record(
                "admit_start", step=self._step_idx,
                request_id=req.request_id, slot=free, bucket=bucket,
                prompt_len=len(req.prompt_token_ids),
                prefix_seeded=consumed)
            # chaos: admission failures are attributable to ONE request
            # (self._admitting is set), exercising the requeue/
            # quarantine blame path in _on_step_failure
            self.faults.raise_point("admit", self._step_idx)

        if a.req.request_id in self._abort:      # aborted mid-admission
            self._abort.discard(a.req.request_id)
            self._finish_admission_abort(a)
            return

        plen = len(a.req.prompt_token_ids)
        chunk = a.chunk
        padded = np.zeros((1, chunk), np.int32)
        part = a.req.prompt_token_ids[a.consumed:a.consumed + chunk]
        padded[0, :len(part)] = part
        self.faults.raise_point("prefill", self._step_idx)
        logits, a.cache1 = self._prefill(
            self.params, jnp.asarray(padded), a.cache1)
        start = a.consumed
        a.consumed += chunk

        if a.consumed >= plen:
            if self._paged:
                self.cache = self._paged_insert(a, plen)
            else:
                self._remember_prefix(a.req.prompt_token_ids, a.cache1)
                self.cache = self._insert(self.cache, a.cache1,
                                          a.slot_idx, plen)
            s = self.slots[a.slot_idx]
            s.req = a.req
            self._setup_slot_sampler(s)
            first, lp = self._sample_admission(
                logits[:, plen - 1 - start], s)
            s.generated = [int(first)]
            s.last_token = int(first)
            s.active = True
            self._obs_admission_complete(a.req.request_id)
            self._emit(s, lp)
            self._check_done(a.slot_idx)
            self._admitting = None

    # -- paged KV bookkeeping (kv_page_size > 0) ----------------------------

    def _bt(self):
        """Device mirror of the host block tables, refreshed only when
        a row changed — steady-state decode reuses the resident array
        (no per-token H2D of page indices)."""
        if self._bt_dirty:
            self._bt_dev = jnp.asarray(self._bt_np)
            self._bt_dirty = False
        return self._bt_dev

    def _paged_admit(self, req: Request, chunk: int):
        """Page-side half of admission start: radix longest-prefix
        match, then an all-or-nothing grab of every page the sequence
        can EVER need (prompt + max_tokens, capped at max_seq) — the
        decode path never allocates, so a running sequence cannot
        deadlock against an admission for pages. Returns ``(consumed,
        shared_pages, new_pages)`` or None after requeueing the request
        (pool exhausted even after evicting idle radix leaves)."""
        ce = self.cfg_engine
        prompt = req.prompt_token_ids
        plen = len(prompt)
        ps = self._page_size
        consumed = 0
        shared: List[int] = []
        owned = False
        mig = (self._migration_pages.pop(req.resume_id, None)
               if req.resume_id is not None else None)
        if mig is not None:
            # migrated-in sequence: the imported pages arrive at
            # refcount 1 (owned by the staging stash) and that
            # reference BECOMES the slot's — no incref below. Only the
            # aligned prefix is consumable (the same chunk/page
            # alignment as a radix hit); tail pages holding the
            # re-prefilled remainder give their reference back.
            req.resume_id = None         # claim is one-shot
            pages_m, kv_imported, _ = mig
            align = max(chunk, ps)
            consumed = min(kv_imported, plen - 1)
            consumed -= consumed % align
            keep = consumed // ps
            shared = pages_m[:keep]
            for p in pages_m[keep:]:
                self.pool.decref(p)
            owned = True
            self._mig_inc("claimed")
            self.flight.record(
                "migration_claim", step=self._step_idx,
                request_id=req.request_id, consumed=consumed,
                n_pages=keep)
        elif self.radix is not None:
            matched, pages = self.radix.match(prompt)
            # the seeded length must stay aligned to both the prefill
            # chunk and the page size (powers of two: lcm == max), and
            # the final prompt token must run to produce logits
            align = max(chunk, ps)
            consumed = min(matched, plen - 1)
            consumed -= consumed % align
            shared = pages[:consumed // ps]
        want = min(plen + req.params.max_tokens, ce.max_seq)
        n_new = -(-want // ps) - len(shared)
        new = self.pool.alloc(n_new)
        if new is None and self.radix is not None:
            # reclaim idle radix leaves (LRU-first; a page a live slot
            # maps is never an eviction candidate) and retry once
            self.radix.evict(n_new - self.pool.num_free)
            new = self.pool.alloc(n_new)
        if new is None:
            if owned:
                # give the claimed pages back; the deferred re-admission
                # re-prefills from tokens (the claim was one-shot)
                for p in shared:
                    self.pool.decref(p)
            self.waiting.appendleft(req)
            self._deferred_admissions += 1
            self._m_deferred.labels("pages").inc()
            if not self._deferred_streak:
                self._deferred_streak = True
                self.flight.record(
                    "admit_deferred", step=self._step_idx,
                    request_id=req.request_id, reason="pages",
                    needed_pages=n_new, free_pages=self.pool.num_free)
            return None
        if not owned:
            for p in shared:
                self.pool.incref(p)      # the slot's own reference
        return consumed, shared, new

    def _paged_insert(self, a: _Admission, plen: int):
        """Completion half of a paged admission: write the slot's
        block-table row (shared prefix pages first, then the private
        pages), scatter the private cache1 rows into their pages, and
        publish the prompt's pages — including the partial tail page,
        the future copy-on-write target — to the radix tree."""
        idx = a.slot_idx
        ps = self._page_size
        shared = a.shared_pages or []
        row = list(shared) + list(a.new_pages or [])
        self._bt_np[idx, :] = 0
        self._bt_np[idx, :len(row)] = row
        self._bt_dirty = True
        # per-token scatter coordinates: positions already resident in
        # shared pages must NOT be rewritten (a concurrent reader of
        # those pages stays byte-identical), and chunk padding past the
        # allocated pages has nowhere to live — both go to the null page
        cap = min(a.cache1.k.shape[2], self.cfg_engine.max_seq)
        write_row = np.zeros((self._pages_per_seq,), np.int64)
        write_row[:len(row)] = row
        write_row[:len(shared)] = NULL_PAGE
        t = np.arange(cap)
        phys = write_row[t // ps].astype(np.int32)
        off = (t % ps).astype(np.int32)
        cache = self._insert_paged(
            self.cache, a.cache1, jnp.asarray(phys), jnp.asarray(off),
            jnp.asarray(idx, jnp.int32), jnp.asarray(plen, jnp.int32))
        if self.radix is not None:
            n_prompt_pages = -(-plen // ps)
            self.radix.insert(
                a.req.prompt_token_ids,
                [int(p) for p in self._bt_np[idx, :n_prompt_pages]])
        return cache

    def _cow_step(self, active: List[int]) -> None:
        """Copy-on-write barrier before a paged decode: any active slot
        whose write page (the page holding the position this step
        appends to) is shared gets a private copy first. All copies
        ride ONE fixed-shape jit call — pairs padded to max_batch with
        null->null self-copies — so a CoW step costs one extra
        dispatch, never one per slot."""
        if self.pool.num_shared == 0:
            return
        ps = self._page_size
        pairs: List[Tuple[int, int, int, int]] = []
        for i in active:
            s = self.slots[i]
            wpos = len(s.req.prompt_token_ids) + len(s.generated) - 1
            lp = wpos // ps
            if lp >= self._pages_per_seq:
                continue          # at capacity; the append masks out
            page = int(self._bt_np[i, lp])
            if page == NULL_PAGE or self.pool.refcount(page) <= 1:
                continue
            fresh = self.pool.alloc(1)
            if fresh is None and self.radix is not None:
                self.radix.evict(1)
                fresh = self.pool.alloc(1)
            if fresh is None:
                # pool dry: surrender the prompt's radix path instead.
                # A shared WRITE page is always the prompt's partial
                # tail — referenced by exactly this slot and its radix
                # node (match never returns partial pages) — so the
                # drop makes it private and the append proceeds in
                # place without a copy.
                if self.radix is not None:
                    self.radix.drop(s.req.prompt_token_ids)
                continue
            pairs.append((i, lp, page, fresh[0]))
        if not pairs:
            return
        srcs = np.zeros((self.cfg_engine.max_batch,), np.int32)
        dsts = np.zeros((self.cfg_engine.max_batch,), np.int32)
        for j, (_, _, src, dst) in enumerate(pairs):
            srcs[j] = src
            dsts[j] = dst
        self.cache = self._cow_pages(self.cache, jnp.asarray(srcs),
                                     jnp.asarray(dsts))
        for i, lp, src, dst in pairs:
            self._bt_np[i, lp] = dst
            self.pool.decref(src)
        self._bt_dirty = True
        self.flight.record("cow_pages", step=self._step_idx,
                           n_pages=len(pairs))

    def _release_slot_pages(self, idx: int) -> None:
        """Drop the slot's block-table references (finish, preempt,
        quarantine). Pages the radix tree still references stay
        resident for future prefix hits; the rest free immediately."""
        if not self._paged:
            return
        row = self._bt_np[idx]
        for p in row[row != NULL_PAGE]:
            self.pool.decref(int(p))
        row[:] = 0
        self._bt_dirty = True

    def _release_admission_pages(self,
                                 a: Optional[_Admission]) -> None:
        """Failed/aborted/expired mid-admission: give back the pages
        reserved at admission start (the block-table row was never
        written, so the slot path cannot double-release them)."""
        if not self._paged or a is None:
            return
        for p in (a.shared_pages or []) + (a.new_pages or []):
            self.pool.decref(p)
        a.shared_pages = None
        a.new_pages = None

    def _paged_snapshot(self) -> dict:
        """JSON-ready paged-KV state for /v1/stats and /v1/memory."""
        d = {
            "page_size": self._page_size,
            "num_pages": self._num_pages,
            "pages_used": self.pool.num_used,
            "pages_shared": self.pool.num_shared,
            "pages_free": self.pool.num_free,
            "pool_exhausted_total": self.pool.exhausted_total,
            "kv_bytes_per_page": self._kv_bytes_per_page,
        }
        if self.radix is not None:
            d["radix"] = self.radix.snapshot()
        return d

    # -- KV handoff (disaggregated prefill/decode, serving/api_server) ------

    def export_prefix_snapshot(self, prompt: List[int]):
        """Host-materialized KV planes for this exact prompt's prefix
        snapshot, or None when nothing is cached for it. Planes are
        ``(k, v)`` or ``(k, v, k_scale, v_scale)`` numpy arrays shaped
        ``[L, 1, keep, H, D]`` (scales ``[L, 1, keep, H]``) — the
        prefix-cache entry format, which is also the handoff wire
        format. Safe from HTTP handler threads: one dict get plus
        materialization of the entry's own planes; no engine-owned
        structure is mutated (the materialized copy is NOT written
        back — the engine loop re-materializes on its next touch)."""
        entry = self._prefix_cache.get(tuple(prompt))
        if entry is None:
            return None
        return self._materialize(entry)

    def stage_handoff(self, prompt: List[int], planes) -> None:
        """Queue a remote prefill's KV snapshot for injection into the
        prefix cache. Called from HTTP handler threads BEFORE the
        corresponding add_request; the engine loop drains the inbox at
        the top of _admission_step, so the planes are visible to
        _seed_from_prefix_cache before the request that shipped them
        can be selected for admission. Only the thread-safe deque
        append happens here."""
        self._handoff_in.append((tuple(prompt), tuple(planes)))

    def _drain_handoffs(self) -> None:
        """Engine-loop half of stage_handoff: move staged snapshots
        into the prefix cache (+ hash index). Staged entries are
        bounded separately from prefix_cache_entries — a decode-role
        replica typically runs with the local prefix cache disabled,
        and remote snapshots must not accumulate without bound."""
        if not self._handoff_in:
            return
        ce = self.cfg_engine
        cap = (ce.handoff_cache_entries if ce.handoff_cache_entries >= 0
               else 2 * ce.max_batch)
        if self._paged or cap == 0:
            # paged engines share KV through device pages (host-DRAM
            # snapshots have no splice path into the arena); cap 0
            # disables handoff retention outright — either way the
            # staged planes must not accumulate
            self._handoff_in.clear()
            return
        while True:
            try:
                key, entry = self._handoff_in.popleft()
            except IndexError:
                break
            if key in self._prefix_cache:
                self._prefix_cache.pop(key)      # refresh LRU position
            else:
                self._prefix_index_add(key)
            self._prefix_cache[key] = entry
            self._handoff_keys.append(key)
            self._m_handoff_staged.inc()
            seed_shape = tuple(entry[0].shape)
            self.flight.record("handoff_staged", step=self._step_idx,
                               prompt_len=len(key),
                               seed_tokens=seed_shape[2])
        # bound retention by the EXPLICIT handoff knob, never by
        # prefix_cache_entries: prefix_cache_entries == 0 means the
        # operator turned local prefix caching OFF, and the old
        # max(entries, 2B) floor silently re-enabled it here
        while len(self._handoff_keys) > cap:
            old = self._handoff_keys.popleft()
            self._drop_prefix(list(old))

    # -- live sequence migration (zero-loss drains/restarts/scale-downs) ----
    #
    # Source side (this replica is being drained/retired): an HTTP
    # sender thread calls request_migration(rid); the engine loop
    # suspends the slot mid-decode and exports its complete resumable
    # state (KV planes, tokens, sampler stream, cum-logprob, QoS/
    # deadline/trace) into take_export(rid). After the target's
    # /v1/internal/migrate_in returns 200 the sender calls
    # finish_migrated() (the request finishes here with reason
    # "migrated"); after the retry ladder fails it calls resume_local()
    # and the sequence re-admits HERE, re-seeded from the exported
    # planes, so a dead target costs a requeue — never the tokens.
    #
    # Target side: stage_migration(state) parks the state under its
    # resume_id; the engine loop imports the KV (paged: fresh pages +
    # arena scatter, slab: prefix-cache staging) and the resumed
    # request's admission claims it — the bounded tail re-prefill is
    # the same byte-identical invariant preempt-resume relies on.
    # Unclaimed state expires after _migration_ttl (a lost commit-ack
    # means the source resumed locally; the stale copy must die
    # unclaimed or the sequence would run twice).

    def request_migration(self, request_id: str) -> None:
        """Ask the engine loop to suspend + export one mid-decode
        request. Thread-safe; poll take_export for the state."""
        self._migrate_req.add(request_id)

    def take_export(self, request_id: str) -> Optional[dict]:
        """The exported state (planes are host numpy — the API layer
        wire-encodes them), ``{"unexportable": True}`` when the request
        was not mid-decode, or None while the export is pending."""
        with self._lock:
            return self._migration_out.pop(request_id, None)

    def export_sequence(self, request_id: str,
                        timeout_sec: float = 5.0) -> Optional[dict]:
        """Blocking convenience over request_migration/take_export for
        senders that can wait: returns the resumable state, or None
        when the request is not mid-decode here (or the engine loop
        never got to it) — the caller leaves the request alone then.
        On timeout the export is cancelled (resume_local), so a late
        export can never leave the sequence suspended forever."""
        self.request_migration(request_id)
        deadline = time.monotonic() + timeout_sec
        while time.monotonic() < deadline:
            st = self.take_export(request_id)
            if st is not None:
                return None if st.get("unexportable") else st
            time.sleep(0.002)
        self.resume_local(request_id)
        return None

    def finish_migrated(self, request_id: str, target: str,
                        resume_id: str) -> None:
        """Commit ack from the sender thread: the target replica owns
        the sequence now. The engine loop delivers the "migrated"
        finish (so the HTTP handler can emit the resume marker) —
        nothing is re-emitted, nothing is recomputed."""
        self._migration_done.append((request_id, target, resume_id))

    def resume_local(self, request_id: str) -> None:
        """Every transfer attempt failed (or the export timed out):
        cancel the export and re-admit the sequence locally, re-seeded
        from its own exported planes. Safe to call at any point of the
        export lifecycle, from any thread, more than once."""
        self._migrate_req.discard(request_id)
        self._migration_fail.append(request_id)

    def stage_migration(self, state: dict) -> str:
        """Target-side intake (HTTP handler threads): park a migrated
        sequence's state for the resumed request to claim, and queue
        its KV planes for the engine loop to import. Returns the
        resume_id the source's client must present (X-Resume-Id)."""
        resume_id = state.get("resume_id")
        if not resume_id:
            raise ValueError("migration state carries no resume_id")
        with self._lock:
            self._migration_staged[str(resume_id)] = (state,
                                                      time.monotonic())
        self._migration_in.append(state)
        return str(resume_id)

    # ISSUE-facing aliases: the tentpole API names
    import_sequence = stage_migration

    def claim_migration(self, resume_id: str) -> Optional[dict]:
        """One-shot claim of staged state by the resumed request's
        HTTP handler; None when nothing is staged under resume_id (the
        request then proceeds as a fresh replay — full recompute, but
        correct)."""
        with self._lock:
            ent = self._migration_staged.pop(str(resume_id), None)
        return None if ent is None else ent[0]

    def resume_migrated_request(self, request_id: str, state: dict,
                                trace=None) -> None:
        """Admit a claimed migrated sequence as a resumable request:
        prompt = source prompt + generated-so-far, generation resumes
        at the source's offset with the source's sampler stream,
        cum-logprob, QoS/tenant, and deadline. Raises like add_request
        (EngineDraining / RequestShed / ValueError)."""
        fields = {f.name for f in dataclasses.fields(SamplingParams)}
        params = SamplingParams(**{
            k: v for k, v in (state.get("params") or {}).items()
            if k in fields})
        params = dataclasses.replace(
            params,
            stop_token_ids=tuple(params.stop_token_ids or ()))
        gen = list(state.get("generated") or [])
        full = list(state.get("prompt_token_ids") or []) + gen
        self.add_request(
            request_id, full, params, trace=trace,
            resume={
                "generated_offset":
                    int(state.get("generated_offset") or 0) + len(gen),
                "cum_logprob": float(state.get("cum_logprob") or 0.0),
                "dev_seed": state.get("dev_seed"),
                "resume_id": state.get("resume_id"),
                "deadline": state.get("deadline"),
            })

    def active_request_ids(self,
                           qos: Optional[str] = None) -> List[str]:
        """Request ids currently resident in decode slots — the
        migratable set, optionally filtered to one QoS class (the
        brownout ladder migrates only batch-QoS sequences off an
        overloaded replica). A snapshot; safe from any thread."""
        out = []
        for s in self.slots:
            r = s.req
            if s.active and r is not None and (
                    qos is None or (r.params.qos or None) == qos):
                out.append(r.request_id)
        return out

    def migration_snapshot(self) -> dict:
        """The /v1/stats "migration" block: flat counters the router's
        stats poll turns into per-replica deltas, plus live staging
        depth."""
        with self._lock:
            staged = len(self._migration_staged)
        d = dict(self._mig)
        d["staged"] = staged
        d["pending_out"] = len(self._migration_meta)
        d["wants_migration"] = bool(
            getattr(self.overload, "wants_migration", False))
        if self._paged:
            d["pool"] = {
                "exported_pages_total": self.pool.exported_pages_total,
                "imported_pages_total": self.pool.imported_pages_total,
                "import_exhausted_total":
                    self.pool.import_exhausted_total,
            }
        return d

    def _mig_inc(self, outcome: str) -> None:
        self._mig[outcome] += 1
        self._m_migrations.labels(outcome).inc()

    def _export_slot(self, idx: int) -> None:
        """Engine-loop half of request_migration: gather the slot's KV
        off the device, capture every resumable field, then tear the
        slot down preempt-style (pages released, pos reset) WITHOUT
        requeueing — the sequence is in limbo until the sender commits
        (finish_migrated) or gives up (resume_local)."""
        s = self.slots[idx]
        req = s.req
        rid = req.request_id
        t0 = time.perf_counter()
        plen = len(req.prompt_token_ids)
        gen = list(s.generated)
        # the last sampled token has not been fed back yet — the cache
        # holds plen + len(gen) - 1 positions (the same invariant
        # preempt-resume's bounded tail re-prefill relies on)
        kv_len = plen + len(gen) - 1
        resume_id = f"{rid}-m{self._step_idx}"
        state = {
            "version": 1,
            "resume_id": resume_id,
            "request_id": rid,
            "prompt_token_ids": [int(t) for t in req.prompt_token_ids],
            "generated": [int(t) for t in gen],
            "generated_offset": int(req.generated_offset),
            "kv_len": int(kv_len),
            "dev_seed": int(s.dev_seed),
            "cum_logprob": float(s.cum_logprob),
            "deadline": req.deadline,
            "params": dataclasses.asdict(req.params),
            "trace": list(req.trace) if req.trace is not None else None,
            "kv_cache_dtype": self.kv_cache_dtype,
            "paged": self._paged,
        }
        try:
            if self._paged:
                ps = self._page_size
                n_pages = -(-kv_len // ps)
                pages = [int(p) for p in self._bt_np[idx, :n_pages]]
                state["page_manifest"] = self.pool.export_pages(pages)
                dev = gather_pages_dense(
                    self.cache.k, self.cache.v,
                    jnp.asarray(np.asarray(pages, np.int32)),
                    cache_ks=self.cache.k_scale,
                    cache_vs=self.cache.v_scale)
                # audited: a rare-path migration pulls this sequence's
                # 2-4 planes once — not a per-token sync
                planes = tuple(
                    np.ascontiguousarray(np.asarray(p)[:, :, :kv_len])  # graftlint: disable=step-host-sync
                    for p in dev)
            else:
                c = self.cache
                srcs = (c.k, c.v) + ((c.k_scale, c.v_scale)
                                     if c.k_scale is not None else ())
                planes = tuple(
                    np.ascontiguousarray(  # graftlint: disable=step-host-sync
                        np.asarray(p[:, idx:idx + 1, :kv_len]))  # graftlint: disable=step-host-sync
                    for p in srcs)
        except Exception as e:
            # export must never kill the step loop: leave the sequence
            # running (the sender times out; the request finishes here)
            self.flight.record("migration_export_failed",
                               step=self._step_idx, request_id=rid,
                               **exception_fields(e))
            self._mig_inc("failed")
            with self._lock:
                self._migration_out[rid] = {"unexportable": True}
            return
        state["planes"] = planes
        resumed = dataclasses.replace(
            req,
            prompt_token_ids=list(req.prompt_token_ids) + gen,
            generated_offset=req.generated_offset + len(gen),
            resumed_cum_logprob=s.cum_logprob,
            resume_dev_seed=int(s.dev_seed))
        s.req = None
        s.active = False
        s.generated = []
        s.counts = None
        s.counts_out = None
        self._release_slot_pages(idx)
        self.cache = dataclasses.replace(
            self.cache, pos=self.cache.pos.at[idx].set(0))
        self._migration_meta[rid] = {
            "resumed": resumed, "planes": planes, "kv_len": kv_len,
            "t0": t0, "n_generated": resumed.generated_offset}
        with self._lock:
            self._migration_out[rid] = state
        self._mig_inc("exported")
        self.flight.record(
            "migration_export", step=self._step_idx, request_id=rid,
            resume_id=resume_id, slot=idx, kv_len=kv_len,
            n_generated=resumed.generated_offset)

    def _migration_step(self) -> bool:
        """Engine-loop migration work: sweep export requests, deliver
        commit finishes, re-admit failed sends. Returns True when any
        migration work happened (counts as a working step)."""
        did = False
        if self._migrate_req:
            for rid in list(self._migrate_req):
                self._migrate_req.discard(rid)
                idx = next(
                    (i for i, s in enumerate(self.slots)
                     if s.active and s.req is not None
                     and s.req.request_id == rid), None)
                if idx is None:
                    # not mid-decode here (queued, admitting, CP lane,
                    # already finished, unknown): nothing to move —
                    # tell the sender so it leaves the request alone
                    self._mig_inc("unexportable")
                    with self._lock:
                        self._migration_out[rid] = {
                            "unexportable": True}
                    continue
                self._export_slot(idx)
                did = True
        while self._migration_done:
            try:
                rid, target, resume_id = self._migration_done.popleft()
            except IndexError:
                break
            meta = self._migration_meta.pop(rid, None)
            with self._lock:
                self._migration_out.pop(rid, None)
            self._abort.discard(rid)
            if meta is None:
                continue             # raced with resume_local: resolved
            self._mig_inc("committed")
            self._mig["migrated_tokens_total"] += meta["n_generated"]
            self._m_migrated_tokens.inc(meta["n_generated"])
            self._m_migration_ms.observe(
                (time.perf_counter() - meta["t0"]) * 1000.0)
            self._push_output(
                rid, RequestOutput(rid, [], True, "migrated"),
                score=meta["resumed"].resumed_cum_logprob,
                length=meta["n_generated"])
            self._obs_finish(rid, "migrated",
                             n_generated=meta["n_generated"])
            self.flight.record(
                "migration_commit", step=self._step_idx,
                request_id=rid, target=target, resume_id=resume_id,
                n_generated=meta["n_generated"])
            did = True
        while self._migration_fail:
            try:
                rid = self._migration_fail.popleft()
            except IndexError:
                break
            meta = self._migration_meta.pop(rid, None)
            with self._lock:
                self._migration_out.pop(rid, None)
            if meta is None:
                continue             # never exported / already resolved
            self._mig_inc("failed")
            resumed = meta["resumed"]
            if rid in self._abort:
                # client hung up while the transfer was failing
                self._abort.discard(rid)
                self._push_output(rid, RequestOutput(rid, [], True,
                                                     "abort"))
                self._obs_finish(rid, "abort",
                                 n_generated=meta["n_generated"])
                did = True
                continue
            if not self._reseed_local(resumed, meta):
                # no staged copy: the resume's prefill recomputes the
                # generated-so-far tail from tokens
                self._mig["recomputed_tokens_total"] += \
                    meta["n_generated"]
                self._m_recomputed_tokens.inc(meta["n_generated"])
            self.waiting.append(resumed)
            self._mig_inc("local_resume")
            self.tracer.preempted(rid)
            self.flight.record(
                "migration_local_resume", step=self._step_idx,
                request_id=rid, n_generated=meta["n_generated"])
            did = True
        return did

    def _reseed_local(self, resumed: Request, meta: dict) -> bool:
        """Failed migration: put the exported KV back (paged:
        self-import into fresh pages; slab: prefix-cache staging) so
        the local resume is a cache splice, not a recompute. False when
        nothing could be staged."""
        planes = meta.get("planes")
        kv_len = int(meta.get("kv_len") or 0)
        if planes is None or kv_len <= 0:
            return False
        if self._paged:
            resume_id = (f"{resumed.request_id}"
                         f"-local{self._step_idx}")
            if not self._import_planes(resume_id, planes, kv_len):
                return False
            resumed.resume_id = resume_id
            return True
        key = tuple(resumed.prompt_token_ids[:kv_len])
        self._handoff_in.append((key, tuple(planes)))
        return True

    def _import_planes(self, resume_id: str, planes,
                       kv_len: int) -> bool:
        """Scatter host KV planes into freshly imported arena pages and
        stash them under resume_id for _paged_admit to claim. Engine
        thread only. False when the pool cannot hold the sequence —
        the resume then re-prefills from tokens (correct, just slower)."""
        ps = self._page_size
        n = -(-kv_len // ps)
        pages = self.pool.import_pages(n)
        if pages is None and self.radix is not None:
            self.radix.evict(n - self.pool.num_free)
            pages = self.pool.import_pages(n)
        if pages is None:
            self.flight.record(
                "migration_import_exhausted", step=self._step_idx,
                resume_id=resume_id, needed_pages=n,
                free_pages=self.pool.num_free)
            return False
        cap = n * ps
        t = np.arange(cap)
        row = np.asarray(pages, np.int64)
        phys = jnp.asarray(row[t // ps].astype(np.int32))
        off = jnp.asarray((t % ps).astype(np.int32))
        c = self.cache
        names = ("k", "v", "k_scale", "v_scale")
        upd = {}
        for name, plane in zip(names, planes):
            arena = getattr(c, name)
            if arena is None:
                continue
            # audited: plane arrived as host bytes off the wire — this
            # asarray is dtype/view normalization, not a device pull
            plane = np.asarray(plane)  # graftlint: disable=step-host-sync
            buf = np.zeros((plane.shape[0], cap) + plane.shape[3:],
                           plane.dtype)
            buf[:, :kv_len] = plane[:, 0, :kv_len]
            upd[name] = arena.at[:, phys, off].set(
                jnp.asarray(buf).astype(arena.dtype))
        self.cache = dataclasses.replace(c, **upd)
        self._migration_pages[resume_id] = (pages, kv_len,
                                            time.monotonic())
        return True

    def _drain_migrations(self) -> None:
        """Engine-loop half of stage_migration: import staged KV, and
        expire unclaimed staging (state AND pages) past the TTL."""
        now = time.monotonic()
        with self._lock:
            dead = [r for r, (_, ts) in
                    self._migration_staged.items()
                    if now - ts > self._migration_ttl]
            for r in dead:
                self._migration_staged.pop(r, None)
        for r in dead:
            self.flight.record("migration_stage_expired",
                               step=self._step_idx, resume_id=r)
        if self._migration_pages:
            for r in [r for r, (_, _, ts) in
                      self._migration_pages.items()
                      if now - ts > self._migration_ttl]:
                pages, _, _ = self._migration_pages.pop(r)
                for p in pages:
                    self.pool.decref(p)
        while self._migration_in:
            try:
                state = self._migration_in.popleft()
            except IndexError:
                break
            planes = state.pop("planes", None)
            resume_id = state.get("resume_id")
            kv_len = int(state.get("kv_len") or 0)
            if planes is None or kv_len <= 0:
                continue
            if state.get("kv_cache_dtype") not in (None,
                                                   self.kv_cache_dtype):
                # mixed-dtype fleet: the quantized codes don't splice —
                # the resume re-prefills from tokens instead
                self.flight.record(
                    "migration_dtype_skew", step=self._step_idx,
                    resume_id=resume_id,
                    theirs=state.get("kv_cache_dtype"),
                    ours=self.kv_cache_dtype)
                continue
            ok = False
            if self._paged:
                ok = self._import_planes(str(resume_id), planes, kv_len)
            else:
                full = (list(state.get("prompt_token_ids") or [])
                        + list(state.get("generated") or []))
                if len(full) > kv_len:
                    self._handoff_in.append(
                        (tuple(full[:kv_len]), tuple(planes)))
                    ok = True
            if ok:
                self._mig_inc("imported")
                self.flight.record(
                    "migration_import", step=self._step_idx,
                    resume_id=resume_id, kv_len=kv_len,
                    request_id=state.get("request_id"))

    @staticmethod
    def _materialize(entry):
        """Pending device slices -> host numpy (cheap if the async copy
        already landed). device_get can hand back non-contiguous views on
        some backends; force contiguity before keeping them around.
        Entries are (k, v) or, for scaled dtypes, (k, v, k_scale,
        v_scale)."""
        if not isinstance(entry[0], np.ndarray):
            # audited: the "loop" is over the 2-4 planes of ONE entry
            # whose async copy already landed — one pull per plane is
            # the sanctioned pattern, not a per-token sync
            entry = tuple(np.ascontiguousarray(np.asarray(x))  # graftlint: disable=step-host-sync
                          for x in entry)
        return entry

    def _seed_from_prefix_cache(self, prompt: List[int], chunk: int):
        """(consumed, entry) for the longest usable cached prefix —
        rounded DOWN to a chunk multiple (continuation chunks must stay
        chunk-aligned) and capped at plen-1 (the final token must run to
        produce sampling logits). (0, None) on miss.

        Lookup goes through the bucketed prefix-hash index: only chunk
        multiples are usable, so probe the candidate lengths directly
        (longest first), O(max_seq/chunk) hashes independent of how many
        entries the cache holds. A hash hit is verified against the
        stored key before use — a collision degrades to a miss at that
        length, never to a wrong seed."""
        if not self._prefix_cache:
            return 0, None
        best = 0
        best_key = None
        if self._prefix_g and chunk % self._prefix_g == 0:
            pt = tuple(prompt)
            top = chunk * ((len(prompt) - 1) // chunk)
            for length in range(top, 0, -chunk):
                key = self._prefix_index.get(length, {}).get(
                    hash(pt[:length]))
                if key is not None and key[:length] == pt[:length]:
                    best, best_key = length, key
                    break
        else:
            # non-divisible bucket/chunk configuration: linear scan
            for stored in self._prefix_cache:
                n = 0
                for a, b in zip(stored, prompt):
                    if a != b:
                        break
                    n += 1
                if n > best:
                    best, best_key = n, stored
            best = min(best, len(prompt) - 1)
            best -= best % chunk
        if best <= 0:
            return 0, None
        entry = self._materialize(self._prefix_cache[best_key])
        self._prefix_cache[best_key] = entry
        # snapshots are truncated to prefix_cache_max_tokens; never seed
        # past what was actually stored
        best = min(best, entry[0].shape[2])
        best -= best % chunk
        if best <= 0:
            return 0, None
        return best, entry

    def _prefix_index_add(self, key: Tuple[int, ...]) -> None:
        g = self._prefix_g
        if not g:
            return
        for length in range(g, len(key) + 1, g):
            self._prefix_index.setdefault(length, {})[
                hash(key[:length])] = key

    def _prefix_index_drop(self, key: Tuple[int, ...]) -> None:
        g = self._prefix_g
        if not g:
            return
        for length in range(g, len(key) + 1, g):
            d = self._prefix_index.get(length)
            if d is not None and d.get(hash(key[:length])) == key:
                del d[hash(key[:length])]
                if not d:
                    del self._prefix_index[length]

    def _remember_prefix(self, prompt: List[int], cache1: KVCache) -> None:
        """Snapshot the prompt's (truncated) KV for later prefix reuse.

        The snapshot is taken as device slices with an ASYNC host copy
        started immediately — step() is not stalled by a blocking D2H of
        the whole prompt KV; materialization happens on the next cache
        touch, by when the copy has usually landed."""
        ce = self.cfg_engine
        if ce.prefix_cache_entries <= 0:
            return
        key = tuple(prompt)
        entry = self._prefix_cache.pop(key, None)
        if entry is None:
            keep = min(len(prompt), ce.prefix_cache_max_tokens)
            planes = [cache1.k[:, :, :keep], cache1.v[:, :, :keep]]
            if cache1.k_scale is not None:
                planes += [cache1.k_scale[:, :, :keep],
                           cache1.v_scale[:, :, :keep]]
            for p in planes:
                try:
                    p.copy_to_host_async()
                except Exception:
                    pass                  # backend without async copies
            entry = tuple(planes)
            self._prefix_index_add(key)
        self._prefix_cache[key] = entry          # (re-)insert most-recent
        while len(self._prefix_cache) > ce.prefix_cache_entries:
            old = next(iter(self._prefix_cache))
            self._prefix_cache.pop(old)
            self._prefix_index_drop(old)

    def reset_prefix_cache(self) -> None:
        self._prefix_cache.clear()
        self._prefix_index.clear()
        if self.radix is not None:
            self.radix.clear()

    def _drop_prefix(self, prompt: List[int]) -> None:
        """Evict one prompt's KV snapshot (cancellation/quarantine).
        In paged mode the snapshot IS the prompt's radix path — drop
        purges it bottom-up, stopping at nodes other prompts share."""
        if self.radix is not None:
            self.radix.drop(prompt)
        key = tuple(prompt)
        if self._prefix_cache.pop(key, None) is not None:
            self._prefix_index_drop(key)

    def _finish_admission_abort(self, a: _Admission) -> None:
        self._release_admission_pages(a)
        self._push_output(a.req.request_id, RequestOutput(
            a.req.request_id, [], True, "abort"))
        self._obs_finish(a.req.request_id, "abort")
        self._admitting = None

    def _setup_slot_sampler(self, s: _Slot) -> None:
        """Per-request sampler state at admission: penalty counts over the
        prompt, a seeded generator, and whether logprobs are tracked
        (explicitly requested, or needed to rank best_of candidates)."""
        p = s.req.params
        # unseeded: one persistent stream. Seeded: the stream is re-derived
        # PER TOKEN from (seed, absolute position) in _sample_host, so a
        # preempt-resume replays identically to an uninterrupted run.
        s.rng = np.random.default_rng() if p.seed is None else None
        # device-sampler stream: user seed folded to 31 bits, the
        # migrated-in stream carried over verbatim (an unseeded resume
        # must continue the SOURCE's stream or its continuation
        # diverges from the unmigrated run), or a fresh nonce per
        # admission (unseeded non-resumed requests promise no replay)
        if p.seed is not None:
            s.dev_seed = int(p.seed) & 0x7FFFFFFF
        elif s.req.resume_dev_seed is not None:
            s.dev_seed = int(s.req.resume_dev_seed) & 0x7FFFFFFF
        else:
            s.dev_seed = int(np.random.default_rng().integers(1 << 31))
        s.cum_logprob = s.req.resumed_cum_logprob
        # rank scores are only consumed when best_of oversamples (> n);
        # don't pay the per-token host log-softmax otherwise
        link = self._children.get(s.req.request_id)
        need_rank = False
        if link is not None:
            fo = self._fanouts.get(link[0])
            need_rank = fo is not None and fo.best_of > fo.n
        s.n_logprobs = (-1 if p.logprobs is None and not need_rank
                        else (p.logprobs or 0))
        if p.needs_counts:
            v = self.cfg.vocab_size
            s.counts = np.zeros((v,), np.int32)
            np.add.at(s.counts, np.asarray(s.req.prompt_token_ids,
                                           np.int64), 1)
            s.counts_out = np.zeros((v,), np.int32)
            if s.req.generated_offset:
                # preempt-resume: the prompt tail IS earlier output
                np.add.at(s.counts_out, np.asarray(
                    s.req.prompt_token_ids[-s.req.generated_offset:],
                    np.int64), 1)
        else:
            s.counts = None
            s.counts_out = None

    def _sample_admission(self, lg_dev, s: _Slot
                          ) -> Tuple[int, Optional[LogprobEntry]]:
        """First token after an (re)admission prefill (lg_dev: [1, V] on
        device). Simple slots draw from the SAME device stream as decode
        steps — without this, a seeded request's resume-recompute token
        came from the host stream and diverged from an uninterrupted
        run (caught by test_seeded_sampling_survives_preemption)."""
        p = s.req.params
        if s.counts is None and s.n_logprobs < 0:
            pos = s.req.generated_offset     # position 0 of this resume
            tok = int(np.asarray(self._sample_device(
                lg_dev,
                jnp.asarray([p.temperature], jnp.float32),
                jnp.asarray([p.top_k], jnp.int32),
                jnp.asarray([p.top_p], jnp.float32),
                jnp.asarray([s.dev_seed], jnp.int32),
                jnp.asarray([pos], jnp.int32)))[0])
            return tok, None
        return self._sample_host(np.asarray(lg_dev)[0], s)

    def _sample_host(self, logits: np.ndarray, s: _Slot
                     ) -> Tuple[int, Optional[LogprobEntry]]:
        """Sample one token for a slot: penalties -> (logprobs) ->
        temperature/top-k/top-p (the reference's BigDLSampler role plus the
        native sampler's repeat-penalty, ggml/model/llama/llama.py:566-620).
        """
        p = s.req.params
        # single D2H pull: np.asarray lands the row on the host in one
        # copy even if a caller hands us a device array, so every
        # float(ls[...]) below (cum_logprob, top-k logprobs) is pure
        # numpy indexing — not one device sync per token
        lg = np.asarray(logits, np.float64)
        if s.counts is not None:
            if p.repetition_penalty != 1.0:
                pen = np.where(lg > 0, lg / p.repetition_penalty,
                               lg * p.repetition_penalty)
                lg = np.where(s.counts > 0, pen, lg)
            if p.frequency_penalty != 0.0 or p.presence_penalty != 0.0:
                # output-token counts only (vllm count-penalty semantics)
                lg = (lg - s.counts_out * p.frequency_penalty
                      - (s.counts_out > 0) * p.presence_penalty)

        entry = None
        if s.n_logprobs >= 0:
            # distribution AFTER penalties, BEFORE temperature (the
            # model's adjusted distribution; also the best_of rank score)
            ls = lg - (np.max(lg) + np.log(
                np.sum(np.exp(lg - np.max(lg)))))
        if p.temperature <= 0.0:
            tok = int(np.argmax(lg))
        else:
            t = lg / p.temperature
            if p.top_k > 0:
                kth = np.sort(t)[-p.top_k]
                t = np.where(t < kth, -np.inf, t)
            if p.top_p < 1.0:
                order = np.argsort(t)[::-1]
                probs = np.exp(t[order] - np.max(t))
                probs /= probs.sum()
                cum = np.cumsum(probs)
                cut = int(np.searchsorted(cum, p.top_p)) + 1
                mask = np.full_like(t, -np.inf)
                mask[order[:cut]] = t[order[:cut]]
                t = mask
            probs = np.exp(t - np.max(t[np.isfinite(t)]))
            probs = np.where(np.isfinite(t), probs, 0.0)
            probs /= probs.sum()
            if s.rng is not None:
                rng = s.rng
            else:
                # stateless seeded draw keyed by absolute token position
                pos = s.req.generated_offset + len(s.generated)
                rng = np.random.default_rng((p.seed, pos))
            tok = int(rng.choice(len(probs), p=probs))

        if s.n_logprobs >= 0:
            s.cum_logprob += float(ls[tok])
            top: List[Tuple[int, float]] = []
            if s.n_logprobs > 0:
                idx = np.argpartition(ls, -s.n_logprobs)[-s.n_logprobs:]
                idx = idx[np.argsort(ls[idx])[::-1]]
                top = [(int(i), float(ls[i])) for i in idx]
            entry = LogprobEntry(tok, float(ls[tok]), top)
        if s.counts is not None:
            s.counts[tok] += 1
            s.counts_out[tok] += 1
        return tok, entry

    def _push_output(self, rid: str, out: RequestOutput,
                     score: Optional[float] = None,
                     length: int = 0) -> None:
        """Deliver an output, routing n/best_of children to their parent.

        Streaming children (best_of == n) pass through with their choice
        index; their per-choice finishes are demoted to finished=False (a
        choice ending is not the request ending) and ONE synthetic
        finished output closes the parent when the last child lands.
        Oversampled children (best_of > n) buffer until all candidates
        finish, then the n best by mean logprob are re-emitted as choices
        0..n-1."""
        link = self._children.get(rid)
        if link is None:
            with self._lock:
                self._outputs.setdefault(rid, []).append(out)
            return
        pid, idx = link
        fo = self._fanouts[pid]
        out = dataclasses.replace(out, request_id=pid, index=idx)
        stream = fo.best_of == fo.n
        if out.finished:
            fo.done += 1
            fo.scores[idx] = score if score is not None else -np.inf
            fo.lengths[idx] = length
            if stream:
                out = dataclasses.replace(out, finished=False)
        if stream:
            with self._lock:
                self._outputs.setdefault(pid, []).append(out)
        else:
            fo.buffered.setdefault(idx, []).append(out)
        if fo.done == fo.best_of:
            self._finish_fanout(fo)

    def _finish_fanout(self, fo: _Fanout) -> None:
        outs: List[RequestOutput] = []
        if fo.best_of > fo.n:
            mean = {i: fo.scores[i] / max(fo.lengths.get(i, 1), 1)
                    for i in fo.scores}
            ranked = sorted(mean, key=lambda i: mean[i], reverse=True)
            for new_idx, child_idx in enumerate(ranked[:fo.n]):
                for o in fo.buffered.get(child_idx, []):
                    # only the synthetic closer below finishes the parent
                    outs.append(dataclasses.replace(
                        o, index=new_idx, finished=False))
        # the closer carries NO finish_reason: choice-level reasons were
        # already delivered (demoted finishes), and a reason here would
        # clobber choice 0's real one in aggregating clients
        outs.append(RequestOutput(fo.parent_id, [], True, None))
        with self._lock:
            self._outputs.setdefault(fo.parent_id, []).extend(outs)
        for i in range(fo.best_of):
            self._children.pop(f"{fo.parent_id}#{i}", None)
            self._abort.discard(f"{fo.parent_id}#{i}")   # no leaks
        self._fanouts.pop(fo.parent_id, None)

    # -- observability hooks ------------------------------------------------

    def _obs_admission_complete(self, rid: str) -> None:
        """First token of an admission just sampled: close out the queue
        and prefill phases, record TTFT (first admission only — a
        preempt-resume already streamed its first token)."""
        span = self.tracer.get(rid)
        now = time.time()
        just_first = span is not None and span.t_first_token is None
        if span is not None and span.t_admitted is not None:
            qw = span.queue_wait_s
            if qw is not None and qw >= 0:
                self._m_phase.labels("queue").observe(qw)
                self._m_step_phase.labels("queue_wait").observe(qw)
            pf = max(now - span.t_admitted, 0.0)
            self._m_phase.labels("prefill").observe(pf)
            self._m_step_phase.labels("prefill").observe(pf)
            self._obs_prefill_perf(span.prompt_len, pf)
            if (span.trace_id is not None and just_first
                    and span.t_enqueued is not None):
                self.spans.record(
                    "queue_wait", span.trace_id,
                    parent_id=span.trace_span,
                    t_start=span.t_enqueued, t_end=span.t_admitted,
                    request_id=rid)
                self.spans.record(
                    "prefill", span.trace_id,
                    parent_id=span.trace_span,
                    t_start=span.t_admitted, t_end=now,
                    request_id=rid)
        self.tracer.first_token(rid)
        if just_first and span.ttft_s is not None:
            self._m_ttft.observe(span.ttft_s)
            meta = self._usage_meta.get(rid)
            if meta is not None:
                self.slo.observe_ttft(meta[1], span.ttft_s)
        self._m_admissions.inc()
        self.flight.record("admit_complete", step=self._step_idx,
                           request_id=rid)

    def _obs_finish(self, rid: str, reason: str,
                    n_generated: int = 0) -> None:
        span = self.tracer.finish(rid, reason, n_generated=n_generated)
        if span is not None:
            d = span.decode_s
            if d is not None and d >= 0:
                self._m_phase.labels("decode").observe(d)
            if span.trace_id is not None:
                if (span.t_first_token is not None
                        and span.t_finished is not None):
                    self.spans.record(
                        "decode", span.trace_id,
                        parent_id=span.trace_span,
                        t_start=span.t_first_token,
                        t_end=span.t_finished, request_id=rid)
                self.spans.record(
                    "engine.request", span.trace_id,
                    span_id=span.trace_span,
                    parent_id=span.trace_parent,
                    t_start=span.t_arrival,
                    t_end=span.t_finished or time.time(),
                    request_id=rid, finish_reason=reason,
                    n_generated=n_generated,
                    preemptions=span.n_preemptions)
        self._m_finished.labels(reason).inc()
        self._finish_times.append(time.time())   # drain-rate window
        meta = self._usage_meta.pop(rid, None)
        if meta is not None:
            tenant, qos = meta
            self.slo.observe_finish(qos, reason)
            self.usage.record_finish(
                rid, tenant, qos,
                prompt_tokens=span.prompt_len if span is not None else 0,
                generated_tokens=n_generated,
                finish_reason=reason,
                queue_wait_s=(span.queue_wait_s
                              if span is not None else None),
                ttft_s=span.ttft_s if span is not None else None,
                tpot_s=span.tpot_s if span is not None else None,
                preemptions=(span.n_preemptions
                             if span is not None else 0))
        self.flight.record("finish", step=self._step_idx, request_id=rid,
                           reason=reason, n_generated=n_generated)

    def _update_gauges(self) -> None:
        self._m_occupancy.set(sum(1 for s in self.slots if s.active))
        self._m_queue_depth.set(len(self.waiting) + len(self._cp_waiting))
        # brownout ladder: one pressure sample per working step (the
        # overload_storm fault overrides the measured signal here)
        self._update_brownout()
        tq: Dict[str, int] = {}
        for q in (self.waiting, self._cp_waiting):
            for r in q:
                t = getattr(r.params, "tenant", None) or "default"
                tq[t] = tq.get(t, 0) + 1
        for t in self.overload.tenants:
            self._m_tenant_queued.labels(t).set(tq.get(t, 0))
        # hbm gauges: the ledger throttles its own device poll
        # ($BIGDL_TPU_MEMORY_POLL_SEC), so per-step publish is cheap
        self.ledger.publish(self.registry)
        if self._paged:
            # page gauges + host-int -> counter mirrors (delta-inc so
            # shared registries and engine restarts never double-count)
            self.pool.publish(self.registry)
            d = self.pool.exhausted_total - self._pub_pool_exhausted
            if d:
                self._m_pool_exhausted.inc(d)
                self._pub_pool_exhausted += d
            if self.radix is not None:
                r, pub = self.radix, self._pub_radix
                hits_d = r.hits - pub["hits"]
                miss_d = (r.lookups - pub["lookups"]) - hits_d
                if hits_d:
                    self._m_radix_lookups.labels("hit").inc(hits_d)
                if miss_d:
                    self._m_radix_lookups.labels("miss").inc(miss_d)
                lt_d = r.lookup_tokens - pub["lookup_tokens"]
                ht_d = r.hit_tokens - pub["hit_tokens"]
                if lt_d:
                    self._m_radix_tokens.labels("looked_up").inc(lt_d)
                if ht_d:
                    self._m_radix_tokens.labels("hit").inc(ht_d)
                pub.update(lookups=r.lookups, hits=r.hits,
                           lookup_tokens=r.lookup_tokens,
                           hit_tokens=r.hit_tokens)

    def memory_snapshot(self) -> dict:
        """The `GET /v1/memory` dict: ledger static report + live
        device stats + budget math, plus the engine's own admission
        accounting."""
        snap = self.ledger.snapshot()
        snap["engine"] = {
            "kv_cache_dtype": self.kv_cache_dtype,
            "kv_bytes_per_slot": self._kv_bytes_per_slot,
            "admissions_deferred": self._deferred_admissions,
            "hbm_budget_fraction": self.ledger.budget_fraction,
            "next_admission_cost_bytes": (
                self._admission_cost(
                    len(self.waiting[0].prompt_token_ids))
                if self.waiting else None),
        }
        if self._paged:
            snap["engine"]["paged"] = self._paged_snapshot()
        return snap

    def _overload_snapshot(self) -> dict:
        """The stats_snapshot "overload" block: controller state plus
        the engine-side load measurements it feeds on."""
        ov = self.overload.snapshot()
        ov["queue_bytes"] = self._queue_bytes()
        ov["tpot_ewma_ms"] = round(self._tpot_ewma * 1000.0, 3)
        ov["drain_rate_rps"] = round(self._drain_rate(), 3)
        return ov

    def stats_snapshot(self) -> dict:
        """JSON-ready engine state for `GET /v1/stats`: live occupancy,
        queue depths, metric summaries, recent request spans, and the
        jit compile table."""
        from bigdl_tpu.observability.compile_watch import compile_table

        return {
            "slots": {"total": len(self.slots),
                      "active": sum(1 for s in self.slots if s.active)},
            "queue_depth": len(self.waiting),
            "cp_queue_depth": len(self._cp_waiting),
            "admitting": self._admitting is not None,
            "stall_steps": self._stall_steps,
            "engine_steps": self._step_idx,
            "dispatch_overhead_ms": round(
                self._dispatch_ewma * 1000.0, 3),
            # compact live-perf subset for the router's poll loop; the
            # full attribution lives at GET /v1/perf
            "perf": {
                "roofline_util_decode": (
                    self._last_perf["roofline_util"]
                    if self._last_perf else None),
                "decode_ideal_ms": (
                    self._last_perf["decode_ideal_ms"]
                    if self._last_perf else None),
                "roofline_mfu_prefill": (
                    self._last_prefill_perf["mfu"]
                    if self._last_prefill_perf else None),
                "sentinel_tripped": (
                    self.sentinel.tripped
                    if self.sentinel is not None else None),
                "sentinel_trips": (
                    self.sentinel.snapshot()["trips"]
                    if self.sentinel is not None else 0),
            },
            # compact live-quality subset for the router's poll loop;
            # the full view (attribution table, probe history) lives at
            # GET /v1/quality
            "quality": {
                "qtype": self.qtype,
                "token_nll": (self._last_quality["token_nll"]
                              if self._last_quality else None),
                "entropy": (self._last_quality["entropy"]
                            if self._last_quality else None),
                "top1_margin": (self._last_quality["top1_margin"]
                                if self._last_quality else None),
                "probe_nll": (self._last_probe["nll"]
                              if self._last_probe else None),
                "sentinel_tripped": (
                    self.qsentinel.tripped
                    if self.qsentinel is not None else None),
                "sentinel_trips": (
                    self.qsentinel.snapshot()["trips"]
                    if self.qsentinel is not None else 0),
            } if self._use_quality else None,
            "paged": self._paged_snapshot() if self._paged else None,
            "migration": self.migration_snapshot(),
            "metrics": self.registry.summary(),
            "requests": self.tracer.snapshot(),
            "compile_table": compile_table(),
            "memory": self.memory_snapshot(),
            "overload": self._overload_snapshot(),
            "slo": self.slo.snapshot(),
            "usage": self.usage.snapshot(),
            "robustness": {
                "step_heartbeat_age_sec": round(
                    self.step_heartbeat_age(), 3),
                "compiles_in_progress": compiles_in_progress(),
                "draining": self._draining,
                "drain_deadline": self._drain_deadline,
                "faults_enabled": self.faults.enabled,
                "step_retries": self._retry_total,
                "consecutive_failures": self._consec_failures,
                "request_deadline_ms": self._request_deadline_ms,
                "slot_crashes": {
                    s.req.request_id: s.req.crashes
                    for s in self.slots
                    if s.active and s.req.crashes > 0},
            },
        }

    # -- live roofline + perf-regression sentinel ---------------------------

    def _perf_observe(self, wall_s: float, n_active: int,
                      seq_len: int) -> None:
        """Fold one decode step into the live roofline gauges and the
        sentinel. Called from step() with the FULL step wall time; cost
        is a handful of float ops + three gauge sets (the fastpath
        dispatch-count test asserts it adds no device dispatches)."""
        decode_ms = wall_s * 1e3
        if decode_ms <= 0:
            return
        costs = roofline.decode_costs(
            self.cfg, self._weight_bytes, seq_len,
            self.kv_cache_dtype, batch=n_active)
        ideal_ms = costs["ideal_ms"]
        hbm_bytes = costs["hbm_bytes"]
        flops = costs["flops"]
        util = round(ideal_ms / decode_ms, 4)
        self._m_roofline.labels("decode").set(util)
        self._m_decode_ideal.set(round(ideal_ms, 6))
        self._last_perf = {
            "decode_ms": round(decode_ms, 3),
            "decode_ideal_ms": round(ideal_ms, 6),
            "roofline_util": util,
            "hbm_bytes": int(hbm_bytes),
            "flops": int(flops),
            "seq_len": seq_len,
            "batch": n_active,
            "step": self._step_idx,
        }
        if self.sentinel is not None:
            self.sentinel.observe(
                decode_ms=decode_ms, roofline_util=util,
                dispatch_ms=self._dispatch_ewma * 1e3)

    def _obs_prefill_perf(self, prompt_len: int, prefill_s: float) -> None:
        """Prefill-side roofline gauge (MFU), fed from the admission
        observability hook."""
        if prefill_s <= 0 or prompt_len <= 0:
            return
        peak_tflops, _ = roofline.chip_peaks()
        flops = roofline.prefill_costs(self.cfg, prompt_len)["flops"]
        mfu = round(flops / prefill_s / (peak_tflops * 1e12), 4)
        self._m_roofline.labels("prefill").set(mfu)
        self._last_prefill_perf = {
            "prompt_len": prompt_len,
            "prefill_ms": round(prefill_s * 1e3, 3),
            "mfu": mfu,
            "flops": int(flops),
        }

    def _on_perf_trip(self, info: dict) -> None:
        """Sentinel tripped: counter + flight event + postmortem + a
        bounded profiler auto-capture into the postmortem dir, all
        best-effort (a perf regression must never become an outage)."""
        try:
            for mt in info.get("metrics", ()):
                self._m_perf_regress.labels(mt).inc()
            self.flight.record(
                "perf_regression", step=self._step_idx,
                metrics=list(info.get("metrics", ())),
                ewma=info.get("ewma"), baseline=info.get("baseline"),
                threshold=info.get("threshold"))
            self.write_postmortem("perf_regression")
            self._start_auto_capture(info)
        except Exception:
            pass

    def _on_perf_recover(self, info: dict) -> None:
        try:
            self.flight.record(
                "perf_recovered", step=self._step_idx,
                metrics=list(info.get("metrics", ())),
                ewma=info.get("ewma"), baseline=info.get("baseline"))
            self._auto_capture_dir = None
        except Exception:
            pass

    def _start_auto_capture(self, info: dict) -> None:
        """Bounded jax.profiler capture at the moment of the slowdown:
        at most BIGDL_TPU_PROFILER_MAX_SEC into a per-trip subdir of
        the postmortem dir (skipped when no dir is configured or a
        capture is already live), annotated onto any live traces."""
        from bigdl_tpu.utils.profiling import start_profiler

        base = os.environ.get("BIGDL_TPU_POSTMORTEM_DIR")
        if not base:
            return
        cap_dir = os.path.abspath(os.path.join(
            base, f"perf_capture_step{self._step_idx}"))
        try:
            out = start_profiler(cap_dir,
                                 capture_id=f"perf-{self._step_idx}")
        except Exception:
            return      # capture live elsewhere, bad env, profiler err
        self._auto_capture_dir = cap_dir
        self.flight.record(
            "perf_auto_capture", step=self._step_idx,
            log_dir=cap_dir, max_sec=out.get("max_sec"))
        # stitch the capture onto live traces: one span per distinct
        # trace id among active slots, so the fleet timeline shows
        # WHERE the profiler evidence lives
        now = time.time()
        for s in self.slots:
            if s.active and s.req is not None and s.req.trace is not None:
                self.spans.record(
                    "perf_auto_capture", s.req.trace[0],
                    t_start=now, t_end=now, step=self._step_idx,
                    request_id=s.req.request_id, log_dir=cap_dir,
                    metrics=list(info.get("metrics", ())))

    def perf_snapshot(self) -> dict:
        """JSON-ready live-performance view for ``GET /v1/perf``:
        per-phase roofline attribution, the sentinel state, and the
        compile table's top offenders by analytical bytes moved."""
        peak_tflops, peak_gbps = roofline.chip_peaks()
        return {
            "decode": dict(self._last_perf) if self._last_perf else None,
            "prefill": (dict(self._last_prefill_perf)
                        if self._last_prefill_perf else None),
            "tpot_ewma_ms": round(self._tpot_ewma * 1e3, 3),
            "dispatch_overhead_ms": round(self._dispatch_ewma * 1e3, 3),
            "weight_bytes": self._weight_bytes,
            "model_flops_per_token": roofline.model_flops_per_token(
                self.cfg),
            "kv_cache_dtype": self.kv_cache_dtype,
            "peak_bf16_tflops": peak_tflops,
            "peak_hbm_gbps": peak_gbps,
            "sentinel": (self.sentinel.snapshot()
                         if self.sentinel is not None else None),
            "top_offenders": top_offenders(8),
        }

    # -- live quality telemetry + QualitySentinel ---------------------------

    def _host_quality_rows(self, logits: np.ndarray,
                           q_meta) -> np.ndarray:
        """Host-side twin of the fused quality block: chosen-token
        logprob / entropy / top-1 margin per q_meta row, computed from
        the [B, V] logits array the complex rows already pulled. Only
        runs when that pull happened anyway — never adds a transfer."""
        out = np.zeros((logits.shape[0], 3), np.float32)
        for i, tok, _, _ in q_meta:
            row = logits[i].astype(np.float64)
            mx = float(row.max())
            ex = np.exp(row - mx)
            z = float(ex.sum())
            lp = row - mx - np.log(z)
            p = ex / z
            top2 = np.partition(row, -2)[-2:]
            out[i, 0] = lp[tok]
            out[i, 1] = -float((p * lp).sum())
            out[i, 2] = float(top2[-1] - top2[-2])
        return out

    def _quality_observe(self, qrows_np: np.ndarray, q_meta) -> None:
        """Fold one decode step's quality rows into the histograms,
        the compact snapshot, and the QualitySentinel. Pure host float
        work — the fastpath dispatch-count test asserts it adds no
        device dispatches. The ``_np`` suffix declares the host-mirror
        contract: callers pass an already-pulled numpy array, never a
        device buffer (graftlint's step-host-sync rule audits this)."""
        lps: List[float] = []
        ents: List[float] = []
        margins: List[float] = []
        for i, tok, repeat, qos in q_meta:
            lp = float(qrows_np[i, 0])
            ent = float(qrows_np[i, 1])
            margin = float(qrows_np[i, 2])
            lbl = (self.qtype, self.kv_cache_dtype, qos)
            self._m_q_logprob.labels(*lbl).observe(lp)
            self._m_q_entropy.labels(*lbl).observe(ent)
            self._m_q_margin.labels(*lbl).observe(margin)
            if (self.eos_token_id is not None
                    and tok == self.eos_token_id):
                self._m_q_eos.labels(*lbl).inc()
            if repeat:
                self._m_q_repeat.labels(*lbl).inc()
            lps.append(lp)
            ents.append(ent)
            margins.append(margin)
        n = len(lps)
        if not n:
            return
        mean_lp = sum(lps) / n
        self._last_quality = {
            # NLL (= -logprob) keeps every sentinel metric positive,
            # which the multiplicative threshold machinery requires
            "token_nll": round(-mean_lp, 4),
            "entropy": round(sum(ents) / n, 4),
            "top1_margin": round(sum(margins) / n, 4),
            "batch": n,
            "step": self._step_idx,
        }
        if self.qsentinel is not None:
            self.qsentinel.observe(
                token_nll=-mean_lp, entropy=sum(ents) / n,
                top1_margin=sum(margins) / n)

    def _on_quality_trip(self, info: dict) -> None:
        """QualitySentinel tripped: counter + flight event +
        postmortem + bounded profiler auto-capture, all best-effort
        (a quality regression must never become an outage)."""
        try:
            for mt in info.get("metrics", ()):
                self._m_q_regress.labels(mt).inc()
            self.flight.record(
                "quality_regression", step=self._step_idx,
                metrics=list(info.get("metrics", ())),
                ewma=info.get("ewma"), baseline=info.get("baseline"),
                threshold=info.get("threshold"))
            self.write_postmortem("quality_regression")
            self._start_auto_capture(info)
        except Exception:
            pass

    def _on_quality_recover(self, info: dict) -> None:
        try:
            self.flight.record(
                "quality_recovered", step=self._step_idx,
                metrics=list(info.get("metrics", ())),
                ewma=info.get("ewma"), baseline=info.get("baseline"))
            self._auto_capture_dir = None
        except Exception:
            pass

    def _maybe_quality_probe(self) -> None:
        """Run the teacher-forced NLL probe every
        ``quality_probe_steps`` decode steps (0 = off, the default, so
        the pure-decode dispatch-count invariant holds untouched)."""
        p = self._quality_probe_steps
        if not self._use_quality or not p or self._step_idx % p:
            return
        try:
            self._quality_probe()
        except Exception:
            pass        # the probe is telemetry, never load-bearing

    def _quality_probe(self) -> None:
        """Teacher-forced NLL over the golden probe prompts: one extra
        dispatch on its own fresh 4-row cache, scored against the
        SERVING weights — so silent numeric corruption (logit_drift)
        moves this number even when byte-level canaries cannot see it.
        When fault clauses are live the probe applies the same
        column-0 drift bias the decode path applies (mask + bias enter
        as traced values, so fault state never forces a recompile)."""
        v = self.cfg.vocab_size
        prompts = np.asarray(
            [[t % v for t in p] for p in GOLDEN_PROBE_PROMPTS],
            np.int32)
        n, w = prompts.shape
        if self._quality_probe_fn is None:
            fwd = self.family.forward

            @functools.partial(tracked_jit, "engine_quality_probe",
                               registry=self.registry)
            def probe(params, toks, cache, drift_mask, drift_bias):
                logits, _ = fwd(params, self.cfg, toks, cache)
                lg = logits.astype(jnp.float32)
                lg = lg.at[:, :, 0].add(
                    jnp.where(drift_mask, drift_bias, 0.0)[:, None])
                lp = jax.nn.log_softmax(lg, axis=-1)
                chosen = jnp.take_along_axis(
                    lp[:, :-1, :],
                    toks[:, 1:, None].astype(jnp.int32), axis=-1)[..., 0]
                return -jnp.mean(chosen)

            self._quality_probe_fn = probe
        mask = np.zeros((n,), bool)
        bias = 0.0
        if self.faults.enabled:
            rows, b = self.faults.drift_rows(self._step_idx,
                                             list(range(n)))
            if rows:
                mask[rows] = True
                bias = float(b)
        cache = self.family.new_cache(self.cfg, n, w, False)
        nll_dev = self._quality_probe_fn(
            self.params, jnp.asarray(prompts), cache,
            jnp.asarray(mask), jnp.asarray(bias, jnp.float32))
        nll = float(np.asarray(nll_dev))
        self._m_q_probe_nll.set(round(nll, 4))
        self._last_probe = {
            "nll": round(nll, 4),
            "step": self._step_idx,
            "prompts": int(n),
            "tokens_per_prompt": int(w),
        }
        if self.qsentinel is not None:
            self.qsentinel.observe(probe_nll=nll)

    def quality_snapshot(self) -> dict:
        """JSON-ready quality view for ``GET /v1/quality``: the
        load-time quantization-error attribution table, the live
        decode telemetry, the latest golden probe, and the
        QualitySentinel state."""
        return {
            "enabled": self._use_quality,
            "qtype": self.qtype,
            "kv_cache_dtype": self.kv_cache_dtype,
            "attribution": (self.quality_report.to_doc()
                            if self.quality_report is not None
                            else None),
            "live": (dict(self._last_quality)
                     if self._last_quality else None),
            "probe": dict(self._last_probe) if self._last_probe else None,
            "probe_period_steps": self._quality_probe_steps,
            "golden_nll_allowance": golden_nll_allowance(self.qtype),
            "sentinel": (self.qsentinel.snapshot()
                         if self.qsentinel is not None else None),
        }

    def _config_fingerprint(self) -> dict:
        out = dataclasses.asdict(self.cfg_engine)
        out["kv_cache_dtype_resolved"] = self.kv_cache_dtype
        out["family"] = getattr(self.family, "name",
                                type(self.family).__name__)
        out["eos_token_id"] = self.eos_token_id
        out["request_deadline_ms_resolved"] = self._request_deadline_ms
        out["drain_timeout_sec_resolved"] = self._drain_timeout_sec
        out["fault_spec_active"] = self.faults.enabled
        return out

    def postmortem(self, reason: str = "on_demand",
                   error: Optional[BaseException] = None) -> dict:
        """The postmortem dict (flight tail, span tail, metrics
        snapshot, compile table, config + env fingerprint) — what
        `GET /v1/debug/dump` serves and crash dumps write."""
        return build_postmortem(
            reason, flight=self.flight, tracer=self.tracer,
            registry=self.registry, config=self._config_fingerprint(),
            memory=self._memory_best_effort(), error=error)

    def _memory_best_effort(self) -> Optional[dict]:
        """memory_snapshot() for dump paths: a failing snapshot must
        not mask the failure being dumped."""
        try:
            return self.memory_snapshot()
        except Exception as e:
            return {"error": repr(e)}

    def write_postmortem(self, reason: str,
                         error: Optional[BaseException] = None,
                         directory: Optional[str] = None):
        """Write the postmortem JSON to `directory` (default
        $BIGDL_TPU_POSTMORTEM_DIR); returns the path or None. Never
        raises."""
        return _write_postmortem_file(
            reason, directory=directory, flight=self.flight,
            tracer=self.tracer, registry=self.registry,
            config=self._config_fingerprint(),
            memory=self._memory_best_effort(), error=error)

    def _finish(self, idx: int, reason: str,
                error: Optional[dict] = None) -> None:
        s = self.slots[idx]
        if s.req is None:
            return
        gen_len = s.req.generated_offset + len(s.generated)
        if reason in ("abort", "error"):
            # a cancelled client's snapshot is dead weight; a poisoned
            # request's snapshot must never seed a future admission
            self._drop_prefix(s.req.prompt_token_ids)
        self._push_output(
            s.req.request_id,
            RequestOutput(s.req.request_id, [], True, reason, error=error),
            score=s.cum_logprob, length=gen_len)
        self._obs_finish(s.req.request_id, reason, n_generated=gen_len)
        s.req = None
        s.active = False
        s.generated = []
        s.counts = None
        s.counts_out = None
        # release the slot's pages (paged) and reset its position so
        # the idle row stops deepening; KVCache and PagedKVCache are
        # both dataclasses, so replace() covers either store
        self._release_slot_pages(idx)
        self.cache = dataclasses.replace(
            self.cache, pos=self.cache.pos.at[idx].set(0))

    def _emit(self, s: _Slot, lp: Optional[LogprobEntry] = None) -> None:
        want_lp = s.req.params.logprobs is not None and lp is not None
        self._push_output(
            s.req.request_id,
            RequestOutput(s.req.request_id, [s.last_token], False,
                          logprobs=[lp] if want_lp else None))
        self._m_tokens.inc()
        # post-paid tenant token-rate accounting: future admissions of
        # a tenant in debt shed with 429 until its bucket refills
        self.overload.note_generated(s.req.params.tenant or "default",
                                     1, time.monotonic())

    def _check_done(self, idx: int) -> bool:
        s = self.slots[idx]
        p = s.req.params
        tok = s.last_token
        if (not p.ignore_eos and self.eos_token_id is not None
                and tok == self.eos_token_id):
            self._finish(idx, "stop")
            return True
        if tok in p.stop_token_ids:
            self._finish(idx, "stop")
            return True
        if s.req.generated_offset + len(s.generated) >= p.max_tokens:
            self._finish(idx, "length")
            return True
        plen = len(s.req.prompt_token_ids)
        if plen + len(s.generated) + 1 >= self.cfg_engine.max_seq:
            self._finish(idx, "length")
            return True
        return False

    # -- context-parallel overflow lane -------------------------------------

    def _cp_finish(self, reason: str) -> None:
        a = self._cp_active
        s = a.slot
        gen_len = s.req.generated_offset + len(s.generated)
        self._push_output(
            s.req.request_id,
            RequestOutput(s.req.request_id, [], True, reason),
            score=s.cum_logprob, length=gen_len)
        self._obs_finish(s.req.request_id, reason, n_generated=gen_len)
        self._cp_active = None

    def _cp_check_done(self) -> None:
        a = self._cp_active
        s = a.slot
        p = s.req.params
        tok = s.last_token
        if (not p.ignore_eos and self.eos_token_id is not None
                and tok == self.eos_token_id):
            return self._cp_finish("stop")
        if tok in p.stop_token_ids:
            return self._cp_finish("stop")
        if s.req.generated_offset + len(s.generated) >= p.max_tokens:
            return self._cp_finish("length")
        if a.pos >= a.alloc:      # next token has no cache row left
            return self._cp_finish("length")

    def _cp_step(self) -> bool:
        """Advance the context-parallel lane by at most one unit of work
        per engine step — ONE prefill chunk (so a cp_max_seq-scale
        admission never stalls the batched streams for more than a
        chunk, the same contract as the slot lane's chunked admission)
        or ONE decode token. Returns True if any CP work was done."""
        import jax.numpy as jnp

        from bigdl_tpu.parallel.cp import (cp_decode_step, cp_empty_cache,
                                           cp_prefill_chunk)

        a = self._cp_active
        adm = self._cp_admitting
        if a is None and adm is None:
            while self._cp_waiting:
                req = self._cp_waiting.popleft()
                if req.request_id in self._abort:
                    self._abort.discard(req.request_id)
                    self._push_output(req.request_id, RequestOutput(
                        req.request_id, [], True, "abort"))
                    self._obs_finish(req.request_id, "abort")
                    continue
                break
            else:
                return False
            n = self._cp_mesh.shape[self._cp_axis]
            ids = req.prompt_token_ids
            want = len(ids) + req.params.max_tokens + 1
            alloc = min(-(-want // n) * n, self.cfg_engine.cp_max_seq)
            cache = cp_empty_cache(self.cfg, 1, alloc, self._cp_mesh,
                                   self._cp_axis,
                                   kv_cache_dtype=self.kv_cache_dtype)
            adm = self._cp_admitting = _CPAdmitting(req, cache, 0, alloc)
            self.tracer.admitted(req.request_id)

        if adm is not None:
            if adm.req.request_id in self._abort:
                self._abort.discard(adm.req.request_id)
                self._push_output(adm.req.request_id, RequestOutput(
                    adm.req.request_id, [], True, "abort"))
                self._obs_finish(adm.req.request_id, "abort")
                self._cp_admitting = None
                return True
            ids = adm.req.prompt_token_ids
            plen = len(ids)
            c = self._chunk
            part = ids[adm.consumed:adm.consumed + c]
            padded = np.zeros((1, c), np.int32)
            padded[0, :len(part)] = part
            lg, adm.cache = cp_prefill_chunk(
                self.params, self.cfg, jnp.asarray(padded), adm.cache,
                adm.consumed, min(plen - 1, adm.consumed + c - 1),
                self._cp_mesh, self._cp_axis)
            adm.consumed += len(part)
            if adm.consumed < plen:
                return True
            slot = _Slot()
            slot.req = adm.req
            self._setup_slot_sampler(slot)
            tok, lp = self._sample_host(np.asarray(lg)[0], slot)
            slot.generated = [int(tok)]
            slot.last_token = int(tok)
            slot.active = True
            self._cp_active = _CPActive(slot, adm.cache, plen, adm.alloc)
            self._cp_admitting = None
            self._obs_admission_complete(slot.req.request_id)
            self._emit(slot, lp)
            self._cp_check_done()
            return True

        s = a.slot
        if s.req.request_id in self._abort:
            self._abort.discard(s.req.request_id)
            self._cp_finish("abort")
            return True
        lg, a.cache = cp_decode_step(
            self.params, self.cfg,
            jnp.asarray([s.last_token], jnp.int32), a.cache, a.pos,
            self._cp_mesh, self._cp_axis)
        a.pos += 1
        tok, lp = self._sample_host(np.asarray(lg)[0], s)
        s.last_token = int(tok)
        s.generated.append(int(tok))
        self._emit(s, lp)
        self._cp_check_done()
        return True

    def _preempt(self) -> None:
        """Starvation relief: evict the LATEST-arrived running sequence by
        recompute (reference scheduler's PreemptionMode.RECOMPUTE,
        vllm/core/scheduler.py:52-66). Its tokens so far become the prompt
        of a resumed request appended at the BACK of the queue — starved
        requests admit into the freed slot first (round-robin under
        pressure), and the prompt-prefix cache (when enabled) makes the
        recompute prefill cheap. Nothing already streamed is re-emitted."""
        victim = max((i for i, s in enumerate(self.slots) if s.active),
                     key=lambda i: self.slots[i].req.arrival, default=None)
        if victim is None:
            return
        s = self.slots[victim]
        req = s.req
        resumed = dataclasses.replace(
            req,
            prompt_token_ids=list(req.prompt_token_ids) + list(s.generated),
            generated_offset=req.generated_offset + len(s.generated),
            resumed_cum_logprob=s.cum_logprob)
        s.req = None
        s.active = False
        s.generated = []
        s.counts = None
        s.counts_out = None
        self._release_slot_pages(victim)
        self.cache = dataclasses.replace(
            self.cache, pos=self.cache.pos.at[victim].set(0))
        self.waiting.append(resumed)
        self._m_preemptions.inc()
        self.tracer.preempted(resumed.request_id)
        self.flight.record(
            "preempt", step=self._step_idx,
            request_id=resumed.request_id, slot=victim,
            n_generated=resumed.generated_offset)

    # -- robustness: quarantine, retries, deadlines, drain ------------------

    def _on_fault_fired(self, kind: str, point: str, step: int) -> None:
        """FaultInjector.on_fire: count + breadcrumb every injection."""
        self._m_faults.labels(kind).inc()
        self.flight.record("fault_injected", step=step, kind=kind,
                           point=point)

    def _fail_request(self, rid: str, reason: str,
                      error: Optional[dict] = None) -> None:
        """Fail a request that is NOT resident in a slot (queued or
        mid-admission): deliver the finished output and close its
        span."""
        self._push_output(rid, RequestOutput(rid, [], True, reason,
                                             error=error))
        self._obs_finish(rid, reason)

    def _quarantine_slot(self, idx: int, reason: str,
                         error: Optional[BaseException] = None) -> None:
        """Blast-radius isolation: fail ONE resident request with a
        structured error while every other slot keeps decoding. Its
        prefix snapshot is dropped (a poisoned prompt must not seed
        future admissions), a `quarantined` flight event and counter
        fire, and a postmortem dump captures the evidence."""
        s = self.slots[idx]
        rid = s.req.request_id
        self._m_quarantined.labels(reason).inc()
        fields = exception_fields(error) if error is not None else {}
        self.flight.record("quarantined", step=self._step_idx,
                           request_id=rid, slot=idx, reason=reason,
                           crashes=s.req.crashes, **fields)
        self._finish(idx, "error", error=self._quarantine_error(
            reason, rid, error))
        self.write_postmortem("request_quarantined", error=error)

    def _quarantine_request(self, req: Request, reason: str,
                            error: Optional[BaseException] = None) -> None:
        """Quarantine a non-resident request (its admission keeps
        crashing before it ever reaches a slot)."""
        self._m_quarantined.labels(reason).inc()
        fields = exception_fields(error) if error is not None else {}
        self.flight.record("quarantined", step=self._step_idx,
                           request_id=req.request_id, slot=None,
                           reason=reason, crashes=req.crashes, **fields)
        self._drop_prefix(req.prompt_token_ids)
        self._fail_request(req.request_id, "error",
                           error=self._quarantine_error(
                               reason, req.request_id, error))
        self.write_postmortem("request_quarantined", error=error)

    @staticmethod
    def _quarantine_error(reason: str, rid: str,
                          error: Optional[BaseException]) -> dict:
        out = {"reason": reason, "request_id": rid}
        if error is not None:
            out["type"] = type(error).__name__
            out["message"] = str(error)[:200]
        return out

    def begin_drain(self, timeout_sec: Optional[float] = None) -> None:
        """Graceful drain (SIGTERM path): stop admitting NEW requests
        (add_request raises EngineDraining -> API 503 + Retry-After),
        let in-flight work finish, and fail whatever remains at the
        drain deadline with reason "drain_timeout" (-> API 504)."""
        if self._draining:
            return
        self._draining = True
        t = (timeout_sec if timeout_sec is not None
             else self._drain_timeout_sec)
        self._drain_deadline = time.time() + max(t, 0.0)
        self._m_draining.set(1)
        self.flight.record(
            "drain_start", step=self._step_idx, timeout_sec=t,
            queue_depth=len(self.waiting) + len(self._cp_waiting),
            occupancy=sum(1 for s in self.slots if s.active))

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def drained(self) -> bool:
        return self._draining and not self.has_unfinished()

    def drain_retry_after_sec(self) -> int:
        """Seconds a 503'd client should wait before retrying: the
        remaining drain window (a fresh replica should be up by then)."""
        if self._drain_deadline is None:
            return 1
        return max(1, int(self._drain_deadline - time.time()) + 1)

    def _drain_expire(self) -> None:
        """Drain deadline reached with work still in flight: fail every
        remaining request with reason "drain_timeout" so clients get a
        definitive 504 instead of a cut socket."""
        self.flight.record(
            "drain_timeout", step=self._step_idx,
            queue_depth=len(self.waiting) + len(self._cp_waiting),
            occupancy=sum(1 for s in self.slots if s.active))
        self.write_postmortem("drain_timeout")
        for q in (self.waiting, self._cp_waiting):
            for r in q:
                self._fail_request(r.request_id, "drain_timeout")
            q.clear()
        if self._admitting is not None:
            self._release_admission_pages(self._admitting)
            self._fail_request(self._admitting.req.request_id,
                               "drain_timeout")
            self._admitting = None
        if self._cp_admitting is not None:
            self._fail_request(self._cp_admitting.req.request_id,
                               "drain_timeout")
            self._cp_admitting = None
        for i, s in enumerate(self.slots):
            if s.active:
                self._finish(i, "drain_timeout")
        if self._cp_active is not None:
            self._cp_finish("drain_timeout")
        # suspended migrations whose sender never resolved them: the
        # drain window is closed — fail them too (the sender's late
        # commit/resume finds no meta and no-ops)
        self._migrate_req.clear()
        self._migration_done.clear()
        self._migration_fail.clear()
        for rid, meta in list(self._migration_meta.items()):
            self._migration_meta.pop(rid, None)
            with self._lock:
                self._migration_out.pop(rid, None)
            self._push_output(rid, RequestOutput(
                rid, [], True, "drain_timeout"))
            self._obs_finish(rid, "drain_timeout",
                             n_generated=meta["n_generated"])

    def _expire_deadlines(self) -> None:
        """Per-step deadline enforcement across every lane a request
        can live in: waiting queues, (CP) admission, resident slots,
        and the CP pseudo-slot. Reason "deadline" -> API 504."""
        now = time.time()

        def expired(req: Request) -> bool:
            return req.deadline is not None and now >= req.deadline

        for q in (self.waiting, self._cp_waiting):
            if any(expired(r) for r in q):
                keep = [r for r in q if not expired(r)]
                for r in q:
                    if expired(r):
                        self._fail_request(r.request_id, "deadline")
                q.clear()
                q.extend(keep)
        a = self._admitting
        if a is not None and expired(a.req):
            self._release_admission_pages(a)
            self._fail_request(a.req.request_id, "deadline")
            self._admitting = None
        ca = self._cp_admitting
        if ca is not None and expired(ca.req):
            self._fail_request(ca.req.request_id, "deadline")
            self._cp_admitting = None
        for i, s in enumerate(self.slots):
            if s.active and expired(s.req):
                self._finish(i, "deadline")
        if self._cp_active is not None \
                and expired(self._cp_active.slot.req):
            self._cp_finish("deadline")

    def _on_step_failure(self, e: Exception) -> bool:
        """Recovery path for a failed step(): record + dump, attribute
        blame, quarantine crash-looping requests, and retry with
        exponential backoff while the consecutive-failure budget lasts.
        Re-raises when the budget is exhausted with no one to blame (a
        systemic failure, not a poisoned request)."""
        ce = self.cfg_engine
        self._consec_failures += 1
        attempt = self._consec_failures
        self.flight.record("step_exception", step=self._step_idx,
                           error=repr(e), attempt=attempt,
                           **exception_fields(e))
        self.write_postmortem("engine_step_exception", error=e)
        blamed = False
        a = self._admitting
        if a is not None:
            # mid-admission failures are attributable to ONE request:
            # drop the (possibly corrupt) private cache and retry it
            # from scratch at the FRONT of the queue (FCFS kept) until
            # its crash budget runs out, then quarantine it
            self._release_admission_pages(a)
            self._admitting = None
            a.req.crashes += 1
            if a.req.crashes > ce.max_slot_crashes:
                self._quarantine_request(a.req, "crash_loop", error=e)
            else:
                self.waiting.appendleft(a.req)
            blamed = True
        else:
            suspects = [i for i, s in enumerate(self.slots) if s.active]
            for i in suspects:
                self.slots[i].req.crashes += 1
            over = [i for i in suspects
                    if self.slots[i].req.crashes >= ce.max_slot_crashes]
            if over:
                # a batched decode failure cannot name its culprit; peel
                # ONE suspect per round (latest arrival, mirroring the
                # preemption victim policy) — repeated failures bisect
                # the batch down to the poisoned request while every
                # cleared slot keeps decoding
                victim = max(over,
                             key=lambda i: self.slots[i].req.arrival)
                self._quarantine_slot(victim, "crash_loop", error=e)
                blamed = True
        if blamed:
            # blame assigned and state changed: fresh retry budget
            self._consec_failures = 0
        elif attempt > ce.max_step_retries:
            raise                        # the active exception (e)
        self._retry_total += 1
        self._m_retries.inc()
        backoff_s = min(ce.retry_backoff_ms * (2 ** (attempt - 1)),
                        2000.0) / 1000.0
        self.flight.record("step_retry", step=self._step_idx,
                           attempt=attempt,
                           backoff_ms=round(backoff_s * 1000.0, 3))
        if backoff_s > 0:
            time.sleep(backoff_s)
        return True

    def step(self) -> bool:
        """One engine iteration (reference LLMEngine.step): advance the
        (chunked) admission by one chunk, then run one batched decode
        step. Returns True if any work was done.

        A step that raises records the exception into the flight
        recorder (with error type + truncated message) and writes a
        postmortem dump (when $BIGDL_TPU_POSTMORTEM_DIR is set), then
        enters the bounded-retry/quarantine path (_on_step_failure) —
        transient failures back off and retry, attributable ones
        quarantine the culprit request, and only budget exhaustion
        with no one to blame propagates out of step()."""
        self._step_idx += 1
        # liveness heartbeat, stamped BEFORE the fault hooks: a step
        # that hangs (replica_hang, a wedged tunnel) leaves this stale,
        # which is what the API server's /health wedge check reads
        self._last_step_ts = time.monotonic()
        # sentinel wall clock from step() ENTRY: everything a client
        # experiences per token — fault sleeps, scheduler work, the
        # decode itself — belongs in the regression signal, so the
        # timer brackets the whole step, not just the device call
        t_step0 = time.perf_counter()
        self._pending_perf = None
        try:
            self.faults.raise_point("step", self._step_idx)
            if self.has_unfinished():
                # process-granularity faults (replica_crash/_hang) only
                # fire on steps with live work: the chaos harness wants
                # a replica dying MID-REQUEST, not on an idle spin
                self.faults.process_point("step", self._step_idx)
            ms = self.faults.sleep_ms("step", self._step_idx)
            if ms > 0:
                time.sleep(ms / 1000.0)
            did = self._step_inner()
        except Exception as e:
            return self._on_step_failure(e)
        self._consec_failures = 0
        # burn-rate evaluation: throttled to the spec's eval_sec, runs
        # on idle steps too so alerts recover without traffic
        self.slo.maybe_evaluate()
        if self._pending_perf is not None:
            n_active, seq_len = self._pending_perf
            self._pending_perf = None
            self._perf_observe(time.perf_counter() - t_step0,
                               n_active, seq_len)
        # periodic teacher-forced NLL probe (off by default: probe
        # period 0 keeps the pure-decode dispatch count untouched)
        if did:
            self._maybe_quality_probe()
        return did

    def _step_inner(self) -> bool:
        # aborts
        for i, s in enumerate(self.slots):
            if s.active and s.req.request_id in self._abort:
                self._abort.discard(s.req.request_id)
                self._finish(i, "abort")

        # queued aborts: sweep the waiting queues every step so an
        # abandoned client's request frees its queue slot NOW — not
        # when it finally reaches the queue front (under a storm that
        # could be minutes of a dead request occupying bounded-queue
        # capacity and inflating every wait estimate)
        if self._abort and (self.waiting or self._cp_waiting):
            for q in (self.waiting, self._cp_waiting):
                if not any(r.request_id in self._abort for r in q):
                    continue
                keep = []
                for r in q:
                    if r.request_id in self._abort:
                        self._abort.discard(r.request_id)
                        self._push_output(r.request_id, RequestOutput(
                            r.request_id, [], True, "abort"))
                        self._obs_finish(r.request_id, "abort")
                    else:
                        keep.append(r)
                q.clear()
                q.extend(keep)

        # live migration: suspend + export requested sequences, finish
        # committed ones, re-admit failed ones (serving/api_server
        # drives the other half from its sender threads)
        mig_did = self._migration_step()

        # per-request deadlines (skip the scan entirely until the first
        # deadline-carrying request arrives)
        if self._any_deadline:
            self._expire_deadlines()

        # graceful drain: past the deadline, fail whatever is left so
        # blocked clients get a definitive 504 instead of a cut socket
        if (self._draining and self._drain_deadline is not None
                and time.time() >= self._drain_deadline
                and self.has_unfinished()):
            self._drain_expire()

        # starvation guard: requests queued while every slot grinds a
        # long generation eventually preempt the newest running sequence
        ce = self.cfg_engine
        if (ce.preempt_after_steps > 0 and self.waiting
                and self._admitting is None
                and all(s.active for s in self.slots)):
            self._stall_steps += 1
            if self._stall_steps >= ce.preempt_after_steps:
                self._m_stall_trips.inc()
                self.flight.record(
                    "stall_guard_trip", step=self._step_idx,
                    stall_steps=self._stall_steps,
                    queue_depth=len(self.waiting))
                # a trip means admission starved for preempt_after_steps
                # consecutive steps — dump the evidence while it is hot
                self.write_postmortem("stall_guard_trip")
                self._preempt()
                self._stall_steps = 0
        else:
            self._stall_steps = 0

        # context-parallel lane: one token (or one admission) per step
        cp_did = False
        if self._cp_mesh is not None:
            cp_did = self._cp_step()

        # admission: at most ONE prefill chunk per step — a long prompt
        # admits across several steps while decodes keep flowing
        self._admission_step()

        active = [i for i, s in enumerate(self.slots) if s.active]
        if not active:
            did = cp_did or mig_did or self._admitting is not None
            if did:
                self._m_steps.inc()
                self._flight_step("admit" if self._admitting is not None
                                  else "cp", 0)
            self._update_gauges()
            return did

        t_decode0 = time.perf_counter()
        t_wall0 = time.time()
        tokens = np.zeros((self.cfg_engine.max_batch,), np.int32)
        for i in active:
            tokens[i] = self.slots[i].last_token
        # mean live cache depth for the roofline sample, captured while
        # every active slot's request is still attached (_check_done
        # frees finishing slots before the step timing lands)
        perf_seq_len = max(1, sum(
            len(self.slots[i].req.prompt_token_ids)
            + len(self.slots[i].generated)
            for i in active) // len(active))

        def simple(s: _Slot) -> bool:
            # no penalty counts, no logprobs: the device sampler covers
            # it (any temperature / top-k / top-p / seed)
            return s.counts is None and s.n_logprobs < 0

        def gather_params(rows):
            b = self.cfg_engine.max_batch
            temps = np.zeros((b,), np.float32)
            top_ks = np.zeros((b,), np.int32)
            top_ps = np.ones((b,), np.float32)
            seeds = np.zeros((b,), np.int32)
            poss = np.zeros((b,), np.int32)
            for i in rows:
                s = self.slots[i]
                p = s.req.params
                temps[i] = p.temperature
                top_ks[i] = p.top_k
                top_ps[i] = p.top_p
                seeds[i] = s.dev_seed
                poss[i] = s.req.generated_offset + len(s.generated)
            return temps, top_ks, top_ps, seeds, poss

        # resident fast path: when every active slot is device-samplable
        # and no fault clause is live (poison_rows edits logits on the
        # host side), forward + health + sampling run as ONE dispatch —
        # the [B, V] logits never exist outside the executable
        resident = (decode_resident_enabled()
                    and not self._paged
                    and not self.faults.enabled
                    and all(simple(self.slots[i]) for i in active))
        toks = None
        finite_host = None
        logits_dev = None
        qrows = None        # [B, 3] chosen_lp/entropy/top1_margin (f32)
        if resident:
            temps, top_ks, top_ps, seeds, poss = gather_params(active)
            all_greedy = all(
                self.slots[i].req.params.temperature <= 0.0
                for i in active)
            toks_dev, finite_dev, self.cache, qrows_dev = \
                self._decode_resident(
                    self.params, jnp.asarray(tokens), self.cache,
                    jnp.asarray(temps), jnp.asarray(top_ks),
                    jnp.asarray(top_ps), jnp.asarray(seeds),
                    jnp.asarray(poss), all_greedy=all_greedy,
                    with_quality=self._use_quality)
            # dispatch vs device split: dispatch-return time is pure
            # host work (trace + transfer enqueue); the blocked wait on
            # the step result is device compute — the same two-sided
            # measurement bench.py uses for tunnel_overhead_ms
            t_dispatch = time.perf_counter()
            jax.block_until_ready(toks_dev)  # graftlint: disable=step-host-sync
            toks = np.asarray(toks_dev)
            finite_host = np.asarray(finite_dev)
            if qrows_dev is not None:
                qrows = np.asarray(qrows_dev)
        elif self._paged:
            # CoW barrier first (shared write pages get private
            # copies), then one block-table-driven decode dispatch
            self._cow_step(active)
            logits_dev, self.cache = self._decode_paged(
                self.params, jnp.asarray(tokens), self.cache,
                self._bt())
            t_dispatch = time.perf_counter()
            jax.block_until_ready(logits_dev)  # graftlint: disable=step-host-sync
        else:
            logits_dev, self.cache = self._decode(
                self.params, jnp.asarray(tokens), self.cache)
            t_dispatch = time.perf_counter()
            jax.block_until_ready(logits_dev)  # graftlint: disable=step-host-sync
        dispatch_s = t_dispatch - t_decode0
        device_s = time.perf_counter() - t_dispatch
        self._m_step_phase.labels("dispatch").observe(dispatch_s)
        self._m_step_phase.labels("device").observe(device_s)

        # fault injection: poison selected rows with NaN AFTER the
        # decode — other rows' values are untouched, so healthy
        # neighbors stay byte-identical to a fault-free run (the
        # resident path is gated off whenever fault clauses exist)
        if not resident:
            bad = self.faults.poison_rows(self._step_idx, active)
            if bad:
                logits_dev = logits_dev.at[jnp.asarray(bad)].set(jnp.nan)
            # logit_drift: a finite bias on ONE vocab column of the
            # drifted rows — argmax changes (silent wrong tokens at
            # full speed) while the isfinite health check below stays
            # green; only a golden-canary replay can notice
            drows, dbias = self.faults.drift_rows(self._step_idx, active)
            if drows:
                logits_dev = logits_dev.at[
                    jnp.asarray(drows), 0].add(dbias)

        # per-slot logits health check: a NaN/Inf row fails ONE request
        # (quarantine, structured error) while the rest of the batch
        # keeps decoding — blast-radius isolation for numeric blowups
        if ce.logits_health_check:
            finite = (finite_host if finite_host is not None
                      else np.asarray(self._health(logits_dev)))
            sick = [i for i in active if not bool(finite[i])]
            if sick:
                for i in sick:
                    self._quarantine_slot(i, "nan_logits")
                active = [i for i in active if i not in sick]
            if not active:
                self._m_steps.inc()
                self._flight_step("decode", 0)
                self._update_gauges()
                return True

        simple_rows = [i for i in active if simple(self.slots[i])]
        complex_rows = [i for i in active if not simple(self.slots[i])]
        if resident:
            pass          # tokens already sampled inside the fused step
        elif simple_rows and all(
                self.slots[i].req.params.temperature <= 0.0
                for i in simple_rows):
            # all-greedy fast path: one fused argmax, no sampling-param
            # transfers (the default-traffic hot path)
            toks = np.asarray(self._argmax(logits_dev))
        elif simple_rows:
            temps, top_ks, top_ps, seeds, poss = gather_params(
                simple_rows)
            # runs for EVERY batch containing a simple slot (not only
            # all-simple ones): a seeded request must sample from the
            # same stream whether or not a penalties/logprobs request
            # happens to share the batch
            toks = np.asarray(self._sample_device(
                logits_dev, jnp.asarray(temps), jnp.asarray(top_ks),
                jnp.asarray(top_ps), jnp.asarray(seeds),
                jnp.asarray(poss)))
        logits = np.asarray(logits_dev) if complex_rows else None

        def pick(i):
            if simple(self.slots[i]):
                return int(toks[i]), None
            return self._sample_host(logits[i], self.slots[i])

        # collect traced requests BEFORE _check_done: a finishing
        # request's slot is freed (req=None, tracer entry closed)
        # inside it, and its final step still belongs on the timeline
        # — so capture the parent span id now, not at record time
        traced: Dict[str, Tuple[str, Optional[str]]] = {}
        step_qos: List[str] = []    # per-slot QoS for the SLO TPOT feed
        # (slot, tok, is_repeat, qos) captured BEFORE _check_done can
        # free the slot — the quality-telemetry feed for this step
        q_meta: List[Tuple[int, int, bool, str]] = []
        for i in active:
            s = self.slots[i]
            tok, lp = pick(i)
            repeat = bool(s.generated) and s.generated[-1] == tok
            s.last_token = tok
            s.generated.append(tok)
            r = s.req
            if r is not None:
                step_qos.append(r.params.qos or "standard")
                if self._use_quality:
                    q_meta.append((i, tok, repeat,
                                   r.params.qos or "standard"))
            if r is not None and r.trace is not None:
                sp = self.tracer.get(r.request_id)
                traced.setdefault(
                    r.trace[0],
                    (r.request_id,
                     sp.trace_span if sp is not None else None))
            self._emit(s, lp)
            self._check_done(i)
        # live quality telemetry: resident steps hand over the fused
        # [B, 3] block (zero extra dispatches); host-sampled steps
        # reuse the logits array that the complex rows already pulled.
        # Simple-row non-resident batches keep their logits on-device
        # — telemetry never adds a transfer the step didn't make.
        if q_meta:
            if qrows is None and logits is not None:
                qrows = self._host_quality_rows(logits, q_meta)
            if qrows is not None:
                self._quality_observe(qrows, q_meta)
        # one batched step advances EVERY active stream one token, so
        # step wall time IS each stream's time-per-output-token
        dt = time.perf_counter() - t_decode0
        self._m_tpot.observe(dt)
        # every active stream advanced one token this step, so the
        # step wall time is each stream's TPOT sample for its QoS class
        for q in step_qos:
            self.slo.observe_tpot(q, dt)
        # EWMA + observed floor feed the queue-wait admission test and
        # the brownout latency-inflation signal
        self._tpot_ewma = stats_ewma(self._tpot_ewma or None, dt)
        if self._tpot_floor is None or self._tpot_ewma < self._tpot_floor:
            self._tpot_floor = self._tpot_ewma
        self._dispatch_ewma = stats_ewma(
            self._dispatch_ewma or None, dispatch_s)
        # stage the roofline/sentinel sample for step() to finalize
        # with the FULL step wall time (fault sleeps happen before this
        # method's timing bracket)
        if active:
            self._pending_perf = (len(active), perf_seq_len)
        # one decode_step span per distinct trace among active slots
        for tid, (rid, parent_sid) in traced.items():
            self.spans.record(
                "decode_step", tid,
                parent_id=parent_sid,
                t_start=t_wall0, t_end=t_wall0 + dt,
                step=self._step_idx, request_id=rid,
                dispatch_ms=round(dispatch_s * 1000.0, 3),
                device_ms=round(device_s * 1000.0, 3))
        self._m_steps.inc()
        self._flight_step("decode", len(active))
        self._update_gauges()
        return True

    def _flight_step(self, phase: str, n_active: int) -> None:
        """One structured flight-recorder event per working step: what
        the engine was doing, with how many streams, against what
        backlog — the per-step breadcrumb trail a postmortem replays."""
        self.flight.record(
            "step", step=self._step_idx, phase=phase,
            occupancy=n_active, queue_depth=len(self.waiting),
            cp_queue_depth=len(self._cp_waiting),
            admitting=self._admitting is not None,
            stall_steps=self._stall_steps)

    # -- convenience: blocking one-shot generation --------------------------

    def generate(self, prompts: List[List[int]],
                 params: Optional[SamplingParams] = None) -> List[List[int]]:
        """Batch-generate (the reference's offline `LLM.generate` analog)."""
        ids = [f"gen-{i}" for i in range(len(prompts))]
        for rid, p in zip(ids, prompts):
            self.add_request(rid, p, params)
        done: Dict[str, List[int]] = {rid: [] for rid in ids}
        finished: set = set()
        while len(finished) < len(ids):
            if not self.step():
                time.sleep(0.001)
            for rid in ids:
                for out in self.get_outputs(rid):
                    done[rid].extend(out.new_token_ids)
                    if out.finished:
                        finished.add(rid)
        return [done[rid] for rid in ids]
