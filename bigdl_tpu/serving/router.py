"""Multi-replica serving tier: a thin HTTP front over N supervised
engine replicas.

PR 5 made a single engine survive bad requests (quarantine, deadlines,
drain); this module moves one failure domain up and makes the SERVICE
survive a bad engine *process*. The reference's L6 serving layer (vLLM
behind FastChat workers) leaves replication and failover to an external
orchestrator; for a TPU-native stack serving heavy traffic we build
that tier in-tree, on the drain/health/flight-recorder substrate the
engine already provides:

- **Replica supervisor** — spawns N ``api_server`` subprocesses, probes
  ``/health`` every ``$BIGDL_TPU_ROUTER_HEALTH_SEC``, restarts crashed
  replicas with exponential backoff, SIGKILLs hung ones (a live process
  whose ``/health`` stops answering — or answers "wedged" off the
  engine's step-loop heartbeat), and quarantines a replica that flaps
  past ``$BIGDL_TPU_ROUTER_CRASH_BUDGET`` deaths inside the crash
  window (the replica-granularity mirror of PR 5's per-request blame).
- **Write-ahead request journal** — every admitted request is recorded
  (raw body: prompt + sampling params, plus the assigned replica)
  BEFORE the first byte is forwarded. When a replica dies mid-flight
  its non-streaming requests are transparently REPLAYED on a healthy
  replica (byte-identical for greedy sampling, since replicas share
  weights); streaming requests get a structured SSE error event with a
  ``retry_after`` hint instead of a dropped socket.
- **Per-replica circuit breakers** — consecutive transport failures
  trip the breaker (routing skips the replica), a cooldown later it
  half-opens (one trial request), success closes it. Plus one optional
  HEDGED retry (``$BIGDL_TPU_ROUTER_HEDGE_MS``): a non-streaming
  request with no response past the hedge latency fires one duplicate
  on a second replica and the first answer wins — the loser's
  connection close triggers the engine's client-disconnect abort, so
  the wasted work frees its slot immediately.
- **Rolling restart** — ``POST /v1/admin/rolling_restart`` drains
  replicas one at a time through PR 5's SIGTERM drain (in-flight work
  finishes, new work is re-routed; a request that races the drain gets
  the replica's 503 and is transparently re-routed), then respawns and
  waits healthy before moving on: a config/weight rollout drops zero
  requests and serves zero 5xx.
- **Prefix-affinity routing** — consistent hash over the prompt prefix
  so shared-system-prompt traffic lands where its prefix-cache entry
  already lives, falling back to least-loaded (live ``/v1/stats``
  occupancy) when the affinity target is down, tripped, or full.

Observability: ``bigdl_tpu_router_*`` metric families (per-replica
state gauge, failover/replay/hedge/breaker-trip/restart counters,
routed-request latency histogram), router events in a flight recorder,
and ``GET /v1/router/stats`` — the JSON snapshot bench embeds. Every
admitted completion gets a W3C-style ``traceparent`` (generated here or
accepted from the client; observability/disttrace.py) forwarded on each
replica hop; ``GET /v1/trace/{trace_id}`` returns the stitched
clock-skew-adjusted fleet timeline and ``GET /v1/traces`` lists recent
slow traces.

Run: ``python -m bigdl_tpu.serving.router --model PATH --replicas 2``
(or ``--tiny-random`` for the checkpoint-free chaos/bench mode).
"""

from __future__ import annotations

import base64
import collections
import dataclasses
import hashlib
import http.client
import itertools
import json
import os
import queue
import socket
import subprocess
import sys
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from bigdl_tpu.observability.disttrace import (SpanRecorder,
                                               make_traceparent,
                                               merge_timeline,
                                               new_span_id, new_trace_id,
                                               parse_traceparent,
                                               trace_sampled)
from bigdl_tpu.observability.flight import FlightRecorder
from bigdl_tpu.observability.metrics import MetricsRegistry
from bigdl_tpu.robustness.faults import FaultInjector

ROUTER_HEALTH_ENV = "BIGDL_TPU_ROUTER_HEALTH_SEC"
ROUTER_REPLICAS_ENV = "BIGDL_TPU_ROUTER_REPLICAS"
ROUTER_HEDGE_ENV = "BIGDL_TPU_ROUTER_HEDGE_MS"
ROUTER_CRASH_BUDGET_ENV = "BIGDL_TPU_ROUTER_CRASH_BUDGET"
ROUTER_JOURNAL_ENV = "BIGDL_TPU_ROUTER_JOURNAL"

# replica lifecycle states -> bigdl_tpu_router_replica_state gauge codes
STARTING = "starting"
HEALTHY = "healthy"
UNHEALTHY = "unhealthy"
DRAINING = "draining"
BACKOFF = "backoff"
QUARANTINED = "quarantined"
RETIRED = "retired"                    # scaled down; never respawned
STATE_CODES = {STARTING: 0, HEALTHY: 1, UNHEALTHY: 2, DRAINING: 3,
               BACKOFF: 4, QUARANTINED: 5, RETIRED: 6}

#: fleet roles (api_server.REPLICA_ROLES): decode replicas are reserved
#: for KV-handoff decode work and only take client traffic when nothing
#: else is routable
ROLES = ("mixed", "prefill", "decode")


def resolve_router_health_sec(value: Optional[str] = None) -> float:
    """Health-probe interval in seconds (default 1.0). Raises
    ``ValueError`` on a non-positive or non-numeric value — env_check
    surfaces it; the router falls back to the default."""
    raw = value if value is not None else os.environ.get(
        ROUTER_HEALTH_ENV, "")
    if not raw:
        return 1.0
    sec = float(raw)                   # ValueError propagates
    if sec <= 0:
        raise ValueError(
            f"{ROUTER_HEALTH_ENV} must be positive, got {raw!r}")
    return sec


def resolve_router_replicas(value: Optional[str] = None) -> int:
    """Replica count (default 2, must be >= 1)."""
    raw = value if value is not None else os.environ.get(
        ROUTER_REPLICAS_ENV, "")
    if not raw:
        return 2
    n = int(raw)                       # ValueError propagates
    if n < 1:
        raise ValueError(
            f"{ROUTER_REPLICAS_ENV} must be >= 1, got {raw!r}")
    return n


def resolve_router_hedge_ms(value: Optional[str] = None) -> float:
    """Hedged-retry latency threshold in ms (default 0 = hedging off)."""
    raw = value if value is not None else os.environ.get(
        ROUTER_HEDGE_ENV, "")
    if not raw:
        return 0.0
    ms = float(raw)                    # ValueError propagates
    if ms < 0:
        raise ValueError(
            f"{ROUTER_HEDGE_ENV} must be >= 0 (0 disables), got {raw!r}")
    return ms


def resolve_router_canary_sec(value: Optional[str] = None) -> float:
    """Canary-probe sweep interval (default 0 = canaries off);
    delegates to serving/canary.resolve_canary_sec."""
    from bigdl_tpu.serving.canary import resolve_canary_sec
    return resolve_canary_sec(value)


def resolve_router_journal(value: Optional[str] = None) -> Optional[str]:
    """Durable request-journal path (default None = in-memory only).
    Must be absolute: a relative path silently journals into whatever
    cwd the supervisor happened to start from, which is exactly where
    a crash-recovery replay would then fail to find it."""
    raw = value if value is not None else os.environ.get(
        ROUTER_JOURNAL_ENV, "")
    if not raw:
        return None
    if not os.path.isabs(raw):
        raise ValueError(
            f"{ROUTER_JOURNAL_ENV} must be an absolute path, "
            f"got {raw!r}")
    return raw


def resolve_router_crash_budget(value: Optional[str] = None) -> int:
    """Deaths inside the crash window before a replica is quarantined
    (default 3, must be >= 1)."""
    raw = value if value is not None else os.environ.get(
        ROUTER_CRASH_BUDGET_ENV, "")
    if not raw:
        return 3
    n = int(raw)                       # ValueError propagates
    if n < 1:
        raise ValueError(
            f"{ROUTER_CRASH_BUDGET_ENV} must be >= 1, got {raw!r}")
    return n


@dataclasses.dataclass
class RouterConfig:
    """Knobs for the serving tier. ``None`` fields defer to their env
    variables (resolver fallbacks apply on bad values via env_check)."""
    replicas: Optional[int] = None          # $BIGDL_TPU_ROUTER_REPLICAS
    health_sec: Optional[float] = None      # $BIGDL_TPU_ROUTER_HEALTH_SEC
    hedge_ms: Optional[float] = None        # $BIGDL_TPU_ROUTER_HEDGE_MS
    crash_budget: Optional[int] = None      # $BIGDL_TPU_ROUTER_CRASH_BUDGET
    canary_sec: Optional[float] = None      # $BIGDL_TPU_CANARY_SEC
    health_timeout_sec: float = 2.0    # per-probe HTTP timeout
    unhealthy_after: int = 3           # probe failures before hang-kill
    crash_window_sec: float = 60.0     # deaths inside count to the budget
    backoff_base_sec: float = 0.25     # restart backoff: base * 2^deaths
    backoff_max_sec: float = 30.0
    breaker_threshold: int = 3         # consecutive failures to trip
    breaker_cooldown_sec: float = 2.0  # open -> half-open delay
    affinity_tokens: int = 32          # prompt prefix hashed for affinity
    max_replays: int = 2               # failover replays per request
    connect_timeout_sec: float = 5.0
    forward_timeout_sec: float = 600.0  # backstop; deaths close the socket
    spawn_timeout_sec: float = 180.0   # replica boot -> healthy
    drain_exit_timeout_sec: float = 60.0  # SIGTERM -> exit before SIGKILL
    # how long a request WAITS for a routable replica before 503ing:
    # losing the last healthy replica usually means its replacement is
    # seconds away (backoff + respawn), and giving up instantly would
    # drop exactly the requests the replay journal exists to save
    no_replica_wait_sec: float = 30.0
    # per-index fleet roles ("prefill" / "decode" / "mixed"); shorter
    # than the replica count -> the rest default to "mixed". A prefill
    # replica gets X-Handoff-Targets on its non-streaming forwards and
    # ships KV to a decode replica (api_server /v1/internal/kv_handoff)
    roles: Optional[List[str]] = None
    # decode targets named per handoff (ordered least-loaded)
    handoff_fanout: int = 3
    # durable JSONL journal path (None defers to
    # $BIGDL_TPU_ROUTER_JOURNAL; unset env = in-memory only)
    journal_path: Optional[str] = None
    # POST /v1/admin/migrate_out budget per drained replica: covers
    # exporting + shipping every in-flight sequence, so it scales with
    # max_batch, not one request
    migrate_admin_timeout_sec: float = 30.0
    # brownout level-3 relief: how often one overloaded replica may be
    # asked to push batch-QoS sequences to an idle peer, and how many
    # sequences per nudge
    brownout_migrate_interval_sec: float = 5.0
    brownout_migrate_batch: int = 2

    def resolve(self) -> "RouterConfig":
        out = dataclasses.replace(self)
        if out.replicas is None:
            try:
                out.replicas = resolve_router_replicas()
            except ValueError:
                out.replicas = 2          # env_check reports it
        if out.health_sec is None:
            try:
                out.health_sec = resolve_router_health_sec()
            except ValueError:
                out.health_sec = 1.0
        if out.hedge_ms is None:
            try:
                out.hedge_ms = resolve_router_hedge_ms()
            except ValueError:
                out.hedge_ms = 0.0
        if out.crash_budget is None:
            try:
                out.crash_budget = resolve_router_crash_budget()
            except ValueError:
                out.crash_budget = 3
        if out.canary_sec is None:
            try:
                out.canary_sec = resolve_router_canary_sec()
            except ValueError:
                out.canary_sec = 0.0      # env_check reports it
        if out.journal_path is None:
            try:
                out.journal_path = resolve_router_journal()
            except ValueError:
                out.journal_path = None   # env_check reports it
        return out


class ReplicaLost(RuntimeError):
    """The replica's connection failed mid-request (death, hang-kill,
    connection refused). The failover/replay path catches this."""


class NoReplica(RuntimeError):
    """No routable replica (all down, draining, or breaker-open)."""


@dataclasses.dataclass
class JournalEntry:
    """One admitted request in the write-ahead journal: everything
    needed to replay it on another replica (the raw JSON body IS the
    prompt + SamplingParams), plus failover bookkeeping."""
    rid: str
    path: str
    body: bytes
    stream: bool
    key: int                           # affinity hash
    replica: Optional[int] = None      # currently assigned replica idx
    generation: int = 0                # that replica's spawn generation
    replays: int = 0
    hedged: bool = False
    admitted_at: float = dataclasses.field(default_factory=time.monotonic)
    tenant: Optional[str] = None       # X-Tenant-Id to forward
    # distributed-trace context (observability/disttrace.py):
    # (trace_id, client_parent_span_id or None, router_span_id) — None
    # when the trace was tail-sampled out, so no header is forwarded
    trace: Optional[Tuple[str, Optional[str], str]] = None
    # last observed live-migration hop ({"resume_id", "target"}) — a
    # recovered journal uses it to tell "crashed mid-migration" (fall
    # back to byte-identical replay of the original body) from a plain
    # in-flight request
    migrated: Optional[dict] = None


class RequestJournal:
    """Write-ahead journal of in-flight requests. `admit` happens
    BEFORE the first forward; `complete` removes the entry once the
    client has its answer (or its structured error).

    With ``path`` set every mutation is also appended to a durable
    JSONL file (one fsync-free ``write+flush`` per record — the
    trailing record of a kill -9 may be TORN, which recovery detects
    and skips). Startup recovery replays the complete records:
    admitted-but-never-completed entries come back as
    :attr:`recovered` (their raw bodies replayable byte-identically
    for greedy/seeded sampling), torn or garbled lines are counted in
    :attr:`torn_records`, never trusted. A record only counts as
    committed once its terminating newline hit the file."""

    def __init__(self, path: Optional[str] = None):
        self._entries: Dict[str, JournalEntry] = {}
        self._lock = threading.Lock()
        self.path = path
        self._fh = None
        self.torn_records = 0
        self.recovered: List[JournalEntry] = []
        if path:
            self.recovered, self.torn_records = self._recover(path)
            # truncate after recovery: the recovered entries are the
            # router's to replay; carrying dead records forward would
            # re-recover them after every restart
            self._fh = open(path, "wb")
            for e in self.recovered:
                self._append({
                    "op": "admit", "rid": e.rid, "path": e.path,
                    "body": base64.b64encode(e.body).decode("ascii"),
                    "stream": e.stream, "key": e.key,
                    "tenant": e.tenant, "recovered": True})

    @staticmethod
    def _recover(path: str) -> Tuple[List[JournalEntry], int]:
        try:
            with open(path, "rb") as fh:
                data = fh.read()
        except FileNotFoundError:
            return [], 0
        if not data:
            return [], 0
        lines = data.split(b"\n")
        tail = lines.pop()              # b"" when the file ends clean
        torn = 1 if tail.strip() else 0  # kill -9 mid-append
        live: Dict[str, JournalEntry] = {}
        for line in lines:
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
                op = rec["op"]
                rid = str(rec["rid"])
            except (ValueError, KeyError, TypeError):
                torn += 1               # mid-file garbage: skip, count
                continue
            if op == "admit":
                try:
                    body = base64.b64decode(rec.get("body") or "")
                except (ValueError, TypeError):
                    torn += 1
                    continue
                live[rid] = JournalEntry(
                    rid=rid, path=str(rec.get("path") or
                                      "/v1/completions"),
                    body=body, stream=bool(rec.get("stream")),
                    key=int(rec.get("key") or 0),
                    tenant=rec.get("tenant"))
            elif op == "complete":
                live.pop(rid, None)
            elif op == "migrate":
                e = live.get(rid)
                if e is not None:
                    e.migrated = {"resume_id": rec.get("resume_id"),
                                  "target": rec.get("target")}
        return list(live.values()), torn

    def _append(self, rec: dict) -> None:
        """One JSONL record; caller holds (or IS inside) _lock. The
        newline is the commit marker — a torn write is detected by its
        absence (or the half-written JSON in front of it)."""
        # audited: every caller holds _lock (see docstring), so this
        # read cannot race the locked writers the checker found
        fh = self._fh  # graftlint: disable=lock-guarded-unlocked
        if fh is None:
            return
        try:
            fh.write(json.dumps(rec).encode() + b"\n")
            fh.flush()
        except (OSError, ValueError):
            pass                         # journal loss never 500s traffic

    def admit(self, entry: JournalEntry) -> None:
        with self._lock:
            self._entries[entry.rid] = entry
            self._append({
                "op": "admit", "rid": entry.rid, "path": entry.path,
                "body": base64.b64encode(entry.body).decode("ascii"),
                "stream": entry.stream, "key": entry.key,
                "tenant": entry.tenant})

    def assign(self, rid: str, replica: int, generation: int) -> None:
        with self._lock:
            e = self._entries.get(rid)
            if e is not None:
                e.replica = replica
                e.generation = generation

    def record_migration(self, rid: str, resume_id: Optional[str],
                         target: Optional[str]) -> None:
        """The request's sequence moved mid-decode: journal the hop
        BEFORE the continuation forward, so a router crash between
        commit and continuation recovers to 'replay the original
        body' (slower, byte-identical) instead of a lost request."""
        with self._lock:
            e = self._entries.get(rid)
            if e is not None:
                e.migrated = {"resume_id": resume_id, "target": target}
            self._append({"op": "migrate", "rid": rid,
                          "resume_id": resume_id, "target": target})

    def complete(self, rid: str) -> None:
        with self._lock:
            self._entries.pop(rid, None)
            self._append({"op": "complete", "rid": rid})

    def depth(self) -> int:
        with self._lock:
            return len(self._entries)

    def inflight_on(self, replica: int) -> List[JournalEntry]:
        with self._lock:
            return [e for e in self._entries.values()
                    if e.replica == replica]

    def snapshot(self) -> dict:
        return {"path": self.path, "depth": self.depth(),
                "torn_records": self.torn_records,
                "recovered": len(self.recovered)}

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


class Replica:
    """Supervisor-side view of one engine replica process."""

    def __init__(self, idx: int, port: int, role: str = "mixed"):
        self.idx = idx
        self.port = port
        self.role = role                 # mixed | prefill | decode
        self.proc: Any = None            # Popen-like handle
        self.state = STARTING
        self.generation = 0              # bumped per (re)spawn
        self.started_at = 0.0
        self.probe_failures = 0
        self.restarts = 0                # lifetime respawns
        self.deaths: collections.deque = collections.deque(maxlen=32)
        self.backoff_until = 0.0
        self.last_exit: Optional[str] = None
        self.planned_restart = False     # rolling restart owns the proc
        self.inflight: set = set()       # router-assigned request ids
        self.occupancy = 0.0             # active/total slots (probed)
        self.queue_depth = 0
        self.brownout = 0                # engine brownout level (probed)
        self.tenants: dict = {}          # per-tenant counters (probed)
        # autoscaler load signals (probed from /v1/stats)
        self.tpot_ewma_ms = 0.0          # decode-step latency EWMA
        self.headroom_frac: Optional[float] = None  # HBM ledger headroom
        # last probed handoff counter block + the spawn generation it
        # belongs to (a respawn resets the replica's counters to zero)
        self.handoff: dict = {}
        self.handoff_gen = -1
        # live-migration counter block probed from /v1/stats
        # ("migration" + summed "wire_rejects"), same per-generation
        # delta discipline as handoff
        self.migration: Optional[dict] = None
        self.migration_counts: dict = {}
        self.migration_gen = -1
        # last brownout level-3 migrate nudge (rate limit)
        self.last_brownout_migrate = 0.0
        # compact live-perf block (roofline util, sentinel state)
        # probed from /v1/stats; feeds the router perf aggregate
        self.perf: Optional[dict] = None
        # compact live-quality block (token NLL, probe NLL,
        # QualitySentinel state) probed from /v1/stats; feeds the
        # router's fleet quality aggregate
        self.quality: Optional[dict] = None
        # compact SLO block (active alerts, worst burn rate) probed
        # from /v1/stats; feeds the router's fleet SLO aggregate
        self.slo: Optional[dict] = None
        # circuit breaker
        self.breaker = "closed"          # closed | open | half_open
        self.breaker_failures = 0
        self.breaker_open_until = 0.0

    @property
    def pid(self) -> Optional[int]:
        return getattr(self.proc, "pid", None)

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def snapshot(self) -> dict:
        return {
            "idx": self.idx, "port": self.port, "pid": self.pid,
            "state": self.state, "role": self.role,
            "generation": self.generation,
            "restarts": self.restarts, "last_exit": self.last_exit,
            "probe_failures": self.probe_failures,
            "breaker": self.breaker,
            "breaker_failures": self.breaker_failures,
            "inflight": len(self.inflight),
            "occupancy": self.occupancy,
            "queue_depth": self.queue_depth,
            "brownout": self.brownout,
            "tpot_ewma_ms": self.tpot_ewma_ms,
            "headroom_frac": self.headroom_frac,
            "handoff": dict(self.handoff),
            "migration": (dict(self.migration)
                          if self.migration else None),
            "perf": dict(self.perf) if self.perf else None,
            "slo": dict(self.slo) if self.slo else None,
            "quality": dict(self.quality) if self.quality else None,
        }


def _retry_after_headers(data: bytes) -> tuple:
    """Rebuild the Retry-After header from a buffered shed/drain
    response body (the replica's header was consumed with the
    buffered read; its JSON error block carries the same value)."""
    try:
        ra = json.loads(data).get("error", {}).get("retry_after")
        if ra:
            return (("Retry-After", str(int(ra))),)
    except (ValueError, AttributeError, TypeError):
        pass
    return ()


def _free_port(host: str = "127.0.0.1") -> int:
    s = socket.socket()
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port


class Router:
    """Supervises N replicas and routes OpenAI-API traffic to them.

    ``replica_cmd`` is the subprocess argv with a ``{port}`` placeholder
    (default: ``api_server`` with the flags the CLI assembled); tests
    inject ``spawn(idx, port) -> Popen-like`` to control the processes
    entirely."""

    def __init__(self, replica_cmd: Optional[List[str]] = None,
                 spawn: Optional[Callable[[int, int], Any]] = None,
                 config: Optional[RouterConfig] = None,
                 ports: Optional[List[int]] = None,
                 host: str = "127.0.0.1",
                 registry: Optional[MetricsRegistry] = None,
                 flight: Optional[FlightRecorder] = None,
                 spawn_env: Optional[Dict[str, str]] = None):
        if replica_cmd is None and spawn is None:
            raise ValueError("pass replica_cmd (argv with a {port} "
                             "placeholder) or a spawn(idx, port) factory")
        self.cfg = (config or RouterConfig()).resolve()
        self.host = host
        self._replica_cmd = replica_cmd
        self._spawn_fn = spawn
        self._spawn_env = spawn_env
        ports = list(ports) if ports else [
            _free_port(host) for _ in range(self.cfg.replicas)]
        if len(ports) != self.cfg.replicas:
            raise ValueError(f"got {len(ports)} ports for "
                             f"{self.cfg.replicas} replicas")
        roles = list(self.cfg.roles or [])
        for ro in roles:
            if ro not in ROLES:
                raise ValueError(f"unknown replica role {ro!r} "
                                 f"(choices: {', '.join(ROLES)})")
        self.replicas = [
            Replica(i, p, role=(roles[i] if i < len(roles) else "mixed"))
            for i, p in enumerate(ports)]
        self.journal = RequestJournal(self.cfg.journal_path)
        # chaos for the router's OWN fleet-internal HTTP clients
        # (net_latency@point= / net_drop@point=, robustness/faults.py);
        # off unless $BIGDL_TPU_FAULTS carries a scoped clause
        self.faults = FaultInjector.from_env()
        self._fault_step = itertools.count(1)
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.flight = flight if flight is not None else FlightRecorder()
        # one traceparent per admitted request (generated here, or
        # accepted from the client) stitches router + replica spans
        # into the GET /v1/trace/{id} timeline
        self.spans = SpanRecorder(service="router")
        self._lock = threading.Lock()
        self._stop = False
        self._wake = threading.Event()
        self._supervisor: Optional[threading.Thread] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._admin_lock = threading.Lock()
        self._rolling = False
        # attached by Autoscaler(router); stats_snapshot embeds its
        # decision log when present
        self.autoscaler: Any = None

        # plain counters mirror the metric families so bench JSON and
        # stats_snapshot() embed them without a registry scrape.
        # Incremented from the supervisor thread AND HTTP handler
        # threads: Counter's += is a read-modify-write, so every
        # touch goes through _count()/counts_snapshot() under _lock
        self.counts = collections.Counter()
        self._g_state = self.registry.gauge(
            "bigdl_tpu_router_replica_state",
            "replica lifecycle state (0 starting, 1 healthy, 2 "
            "unhealthy, 3 draining, 4 backoff, 5 quarantined)",
            ["replica"])
        self._c_failovers = self.registry.counter(
            "bigdl_tpu_router_failovers_total",
            "in-flight requests whose replica died under them")
        self._c_replays = self.registry.counter(
            "bigdl_tpu_router_replays_total",
            "non-streaming requests replayed on another replica")
        self._c_hedges = self.registry.counter(
            "bigdl_tpu_router_hedges_total",
            "hedged duplicate requests fired past the latency threshold")
        self._c_trips = self.registry.counter(
            "bigdl_tpu_router_breaker_trips_total",
            "circuit-breaker open transitions", ["replica"])
        self._c_restarts = self.registry.counter(
            "bigdl_tpu_router_restarts_total",
            "replica respawns (crash recovery + rolling restarts)",
            ["replica"])
        self._c_requests = self.registry.counter(
            "bigdl_tpu_router_requests_total",
            "routed requests by replica and response code",
            ["replica", "code"])
        self._h_latency = self.registry.histogram(
            "bigdl_tpu_router_request_seconds",
            "end-to-end routed request latency")
        self._c_canary_probes = self.registry.counter(
            "bigdl_tpu_router_canary_probes_total",
            "golden-canary correctness probes sent to replicas")
        self._c_canary_fail = self.registry.counter(
            "bigdl_tpu_router_canary_failures_total",
            "canary byte mismatches (each quarantines its replica)",
            ["replica"])

        # golden-canary prober (serving/canary.py): periodic greedy
        # probes through each healthy replica; byte mismatch vs the
        # recorded golden quarantines the replica via canary_mismatch.
        # Off unless canary_sec > 0 ($BIGDL_TPU_CANARY_SEC).
        from bigdl_tpu.serving.canary import CanaryProber
        self.canary = CanaryProber(self, self.cfg.canary_sec or 0.0)

        # journal recovery surfaces its findings once, at boot: torn
        # trailing records (kill -9 mid-append) are counted and
        # skipped, complete-but-unfinished admits come back for replay
        if self.journal.torn_records:
            self._count("journal_torn_records",
                        self.journal.torn_records)
            self.flight.record("journal_torn",
                               records=self.journal.torn_records,
                               path=self.journal.path)
        if self.journal.recovered:
            self._count("journal_recovered",
                        len(self.journal.recovered))
            self.flight.record(
                "journal_recovered",
                entries=len(self.journal.recovered),
                migrated_inflight=sum(
                    1 for e in self.journal.recovered
                    if e.migrated is not None),
                path=self.journal.path)

    # -- lifecycle ----------------------------------------------------------

    def start(self, wait_healthy: bool = True) -> None:
        for r in self.replicas:
            self._respawn(r, initial=True)
        self._supervisor = threading.Thread(target=self._supervise,
                                            daemon=True)
        self._supervisor.start()
        self.canary.start()
        if wait_healthy:
            deadline = time.monotonic() + self.cfg.spawn_timeout_sec
            while time.monotonic() < deadline:
                if any(r.state == HEALTHY for r in self.replicas):
                    return
                time.sleep(0.05)
            raise RuntimeError(
                "no replica became healthy within "
                f"{self.cfg.spawn_timeout_sec:.0f}s; last exits: "
                f"{[r.last_exit for r in self.replicas]}")

    def shutdown(self) -> None:
        self._stop = True
        self.canary.stop()
        self._wake.set()
        if self._httpd is not None:
            self._httpd.shutdown()
        if self._supervisor is not None:
            self._supervisor.join(timeout=5)
        for r in self.replicas:
            if r.proc is None:
                continue
            try:
                r.proc.terminate()
            except Exception:
                pass
        deadline = time.monotonic() + 5.0
        for r in self.replicas:
            if r.proc is None:
                continue
            while r.proc.poll() is None and time.monotonic() < deadline:
                time.sleep(0.05)
            if r.proc.poll() is None:
                try:
                    r.proc.kill()
                except Exception:
                    pass
        self.journal.close()

    def _spawn(self, idx: int, port: int, role: str = "mixed"):
        if self._spawn_fn is not None:
            return self._spawn_fn(idx, port)
        cmd = [a.replace("{port}", str(port)) for a in self._replica_cmd]
        env = dict(os.environ)
        if self._spawn_env:
            env.update(self._spawn_env)
        # the replica process learns its fleet role from the env the
        # api_server CLI resolves ($BIGDL_TPU_REPLICA_ROLE) — role
        # flips go through a drain-respawn, never a live mutation
        env["BIGDL_TPU_REPLICA_ROLE"] = role
        return subprocess.Popen(cmd, env=env,
                                stdout=subprocess.DEVNULL)

    def _respawn(self, r: Replica, initial: bool = False) -> None:
        r.generation += 1
        r.proc = self._spawn(r.idx, r.port, r.role)
        r.started_at = time.monotonic()
        r.probe_failures = 0
        r.breaker = "closed"
        r.breaker_failures = 0
        self._set_state(r, STARTING)
        if not initial:
            r.restarts += 1
            self._count("restarts")
            # replica idx is bounded by fleet size — audited
            self._c_restarts.labels(str(r.idx)).inc()  # graftlint: disable=metric-label-cardinality
        self.flight.record("replica_spawn", replica=r.idx, port=r.port,
                           pid=r.pid, generation=r.generation)

    def _set_state(self, r: Replica, state: str) -> None:
        if r.state != state:
            self.flight.record("replica_state", replica=r.idx,
                               prev=r.state, state=state)
        r.state = state
        # replica idx is bounded by fleet size — audited
        self._g_state.labels(str(r.idx)).set(  # graftlint: disable=metric-label-cardinality
            STATE_CODES[state])

    # -- supervision --------------------------------------------------------

    def _supervise(self) -> None:
        while not self._stop:
            try:
                self._tick()
            except Exception:
                import traceback

                traceback.print_exc()   # the supervisor must survive
            self._wake.wait(timeout=self.cfg.health_sec)
            self._wake.clear()

    def _tick(self) -> None:
        now = time.monotonic()
        for r in list(self.replicas):    # add_replica appends live
            if r.state in (QUARANTINED, RETIRED) or r.planned_restart:
                continue
            if r.state == BACKOFF:
                if now >= r.backoff_until:
                    self._respawn(r)
                continue
            if r.proc is not None and r.proc.poll() is not None:
                self._handle_death(
                    r, f"exit code {r.proc.returncode}")
                continue
            self._probe(r, now)

    @staticmethod
    def _fault_point(path: str) -> str:
        """Chaos scope for one fleet-internal HTTP call: the point=
        label net_latency / net_drop clauses select on."""
        if "/migrate" in path:
            return "migrate"
        if "/kv_handoff" in path:
            return "handoff"
        if path.startswith("/v1/admin"):
            return "admin"
        if path.startswith("/v1/completions") \
                or path.startswith("/v1/chat/"):
            return "canary"              # only the prober posts these
        return "stats"                   # /health, /v1/stats, spans

    def _net_fault(self, path: str) -> None:
        """Apply injected network chaos to one internal client call:
        sleep the scoped latency, then fail as a connection reset when
        a scoped drop fires (the caller's OSError handling — probe
        failure accounting, stats-poll skip, migrate fallback — is
        exactly the machinery under test)."""
        if not self.faults.enabled:
            return
        point = self._fault_point(path)
        step = next(self._fault_step)
        d = self.faults.net_delay_ms(point, step)
        if d > 0:
            time.sleep(d / 1000.0)
        if self.faults.net_dropped(point, step):
            raise OSError(
                f"injected connection reset (net_drop@{point})")

    def _http_get(self, port: int, path: str,
                  timeout: float) -> Tuple[int, bytes]:
        self._net_fault(path)
        conn = http.client.HTTPConnection(self.host, port,
                                          timeout=timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    def _http_post(self, port: int, path: str, doc: dict,
                   timeout: float) -> Tuple[int, bytes]:
        self._net_fault(path)
        body = json.dumps(doc).encode()
        conn = http.client.HTTPConnection(self.host, port,
                                          timeout=timeout)
        try:
            conn.request("POST", path, body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    def _probe(self, r: Replica, now: float) -> None:
        try:
            status, body = self._http_get(r.port, "/health",
                                          self.cfg.health_timeout_sec)
        except OSError:
            status, body = -1, b""
        if status == 200:
            r.probe_failures = 0
            if r.state != HEALTHY:
                self._set_state(r, HEALTHY)
            self._poll_stats(r)
            return
        detail = ""
        if status == 503:
            try:
                detail = json.loads(body).get("status", "")
            except (ValueError, AttributeError):
                detail = ""
        if detail == "draining":
            # expected while the replica finishes in-flight work
            # (rolling restart, operator SIGTERM); not a failure
            self._set_state(r, DRAINING)
            return
        # refused / timed out / wedged: the process may be alive but
        # the service is not there
        r.probe_failures += 1
        if r.state == STARTING:
            if now - r.started_at > self.cfg.spawn_timeout_sec:
                self._kill_hung(r, "never became healthy")
            return
        if r.state == HEALTHY:
            self._set_state(r, UNHEALTHY)
        if r.probe_failures >= self.cfg.unhealthy_after:
            self._kill_hung(
                r, f"hung ({r.probe_failures} probe failures"
                   f"{', ' + detail if detail else ''})")

    def canary_probe(self) -> None:
        """One canary probe was sent (counter hook for CanaryProber)."""
        self._count("canary_probes")
        self._c_canary_probes.inc()

    def canary_mismatch(self, r: Replica, kind: str, prompt_idx: int,
                        expected: str, got: str) -> None:
        """A golden-canary byte mismatch on replica ``r`` — a
        CORRECTNESS failure: the replica answers fast and healthy but
        wrong, so it is quarantined through the same supervisor path a
        crash loop takes (no restarts — wrong weights respawn wrong)
        and its process is terminated so in-flight requests fail over
        to byte-correct neighbors instead of finishing wrong."""
        self._count("canary_failures")
        # replica idx is bounded by fleet size — audited
        self._c_canary_fail.labels(str(r.idx)).inc()  # graftlint: disable=metric-label-cardinality
        self.flight.record(
            "canary_mismatch", replica=r.idx, kind=kind,
            prompt_idx=prompt_idx, expected=expected[:200],
            got=got[:200])
        if r.state == QUARANTINED:
            return                     # already isolated this sweep
        self._count("quarantined")
        self._set_state(r, QUARANTINED)
        self.flight.record("replica_quarantined", replica=r.idx,
                           reason="canary_mismatch", kind=kind)
        try:
            if r.proc is not None:
                r.proc.terminate()
        except Exception:
            pass

    def _kill_hung(self, r: Replica, reason: str) -> None:
        """A live-but-unresponsive replica (replica_hang, wedged step
        loop) is killed so its sockets break and in-flight requests can
        fail over — then handled exactly like a crash."""
        self.flight.record("replica_hung", replica=r.idx, reason=reason)
        try:
            if r.proc is not None:
                r.proc.kill()
                r.proc.wait(timeout=5)
        except Exception:
            pass
        self._handle_death(r, reason)

    def _handle_death(self, r: Replica, reason: str) -> None:
        now = time.monotonic()
        r.last_exit = reason
        r.deaths.append(now)
        orphaned = self.journal.inflight_on(r.idx)
        self.flight.record("replica_death", replica=r.idx, reason=reason,
                           inflight=len(orphaned))
        recent = [t for t in r.deaths
                  if now - t <= self.cfg.crash_window_sec]
        if len(recent) >= self.cfg.crash_budget:
            # crash loop: stop feeding it restarts — the replica-level
            # mirror of the engine's per-request crash-budget quarantine
            self._count("quarantined")
            self._set_state(r, QUARANTINED)
            self.flight.record("replica_quarantined", replica=r.idx,
                               deaths_in_window=len(recent),
                               window_sec=self.cfg.crash_window_sec)
            return
        backoff = min(self.cfg.backoff_max_sec,
                      self.cfg.backoff_base_sec * (2 ** (len(recent) - 1)))
        r.backoff_until = now + backoff
        self._set_state(r, BACKOFF)
        self.flight.record("replica_backoff", replica=r.idx,
                           backoff_sec=round(backoff, 3))

    def _poll_stats(self, r: Replica) -> None:
        """Occupancy for least-loaded fallback routing, plus the
        autoscaler's load signals (brownout, queue depth, tpot EWMA,
        ledger headroom) and the replica's handoff counters (turned
        into fleet-level deltas); best-effort."""
        try:
            status, body = self._http_get(r.port, "/v1/stats",
                                          self.cfg.health_timeout_sec)
            if status != 200:
                return
            doc = json.loads(body)
            slots = doc.get("slots", {})
            total = max(int(slots.get("total", 1)), 1)
            r.occupancy = float(slots.get("active", 0)) / total
            r.queue_depth = int(doc.get("queue_depth", 0))
            ov = doc.get("overload") or {}
            r.brownout = int(ov.get("brownout_level", 0))
            r.tenants = ov.get("tenants") or {}
            r.tpot_ewma_ms = float(ov.get("tpot_ewma_ms", 0.0))
            hr = (doc.get("memory") or {}).get("headroom") or {}
            hb, lim = hr.get("headroom_bytes"), hr.get("bytes_limit")
            r.headroom_frac = (float(hb) / float(lim)
                               if isinstance(hb, (int, float))
                               and isinstance(lim, (int, float))
                               and lim else None)
            ho = doc.get("handoff") or {}
            ho = {k: int(v) for k, v in ho.items()
                  if isinstance(v, (int, float))}
            # per-generation deltas: a respawned replica restarts its
            # counters at zero, so only compare within one generation
            prev = r.handoff if r.handoff_gen == r.generation else {}
            for key in ("retries", "fallbacks"):
                d = ho.get(key, 0) - prev.get(key, 0)
                if d > 0:
                    self._count(f"handoff_{key}", d)
            r.handoff = ho
            r.handoff_gen = r.generation
            mig = doc.get("migration")
            r.migration = mig if isinstance(mig, dict) else None
            mg = r.migration or {}
            wr = doc.get("wire_rejects") or {}
            cur = {
                "migration_committed":
                    int(mg.get("committed", 0) or 0),
                "migration_failed": int(mg.get("failed", 0) or 0),
                "migration_local_resume":
                    int(mg.get("local_resume", 0) or 0),
                "migration_imported":
                    int(mg.get("imported", 0) or 0),
                "migration_claimed": int(mg.get("claimed", 0) or 0),
                "migrated_tokens_total":
                    int(mg.get("migrated_tokens_total", 0) or 0),
                "recomputed_tokens_total":
                    int(mg.get("recomputed_tokens_total", 0) or 0),
                "wire_rejects": sum(
                    int(v) for v in wr.values()
                    if isinstance(v, (int, float))),
            }
            prevm = (r.migration_counts
                     if r.migration_gen == r.generation else {})
            for key, v in cur.items():
                d = v - prevm.get(key, 0)
                if d > 0:
                    self._count(key, d)
            r.migration_counts = cur
            r.migration_gen = r.generation
            self._maybe_brownout_migrate(r)
            perf = doc.get("perf")
            r.perf = perf if isinstance(perf, dict) else None
            quality = doc.get("quality")
            r.quality = quality if isinstance(quality, dict) else None
            slo = doc.get("slo")
            if isinstance(slo, dict):
                # compact fleet view; the full per-replica document
                # stays one proxy hop away at GET /v1/slo
                r.slo = {
                    "alerts_active": int(slo.get("alerts_active") or 0),
                    "alerts_total": int(slo.get("alerts_total") or 0),
                    "burn_rate_max": float(
                        slo.get("burn_rate_max") or 0.0),
                }
        except (OSError, ValueError):
            pass

    # -- circuit breaker ----------------------------------------------------

    def _breaker_failure(self, r: Replica) -> None:
        r.breaker_failures += 1
        if r.breaker == "half_open" or (
                r.breaker == "closed"
                and r.breaker_failures >= self.cfg.breaker_threshold):
            r.breaker = "open"
            r.breaker_open_until = (time.monotonic()
                                    + self.cfg.breaker_cooldown_sec)
            self._count("breaker_trips")
            # replica idx is bounded by fleet size — audited
            self._c_trips.labels(str(r.idx)).inc()  # graftlint: disable=metric-label-cardinality
            self.flight.record("breaker_open", replica=r.idx,
                               failures=r.breaker_failures)

    def _breaker_success(self, r: Replica) -> None:
        r.breaker_failures = 0
        if r.breaker != "closed":
            self.flight.record("breaker_close", replica=r.idx,
                               was=r.breaker)
            r.breaker = "closed"

    def _routable(self, r: Replica) -> bool:
        if r.state != HEALTHY or r.planned_restart:
            return False
        if r.breaker == "open":
            if time.monotonic() < r.breaker_open_until:
                return False
            # cooldown elapsed: half-open, admit a trial request
            r.breaker = "half_open"
            self.flight.record("breaker_half_open", replica=r.idx)
        return True

    # -- routing ------------------------------------------------------------

    def _affinity_key(self, body: dict) -> int:
        prompt = body.get("prompt")
        if prompt is None:
            msgs = body.get("messages") or []
            prompt = "\x00".join(
                f"{m.get('role', '')}:{m.get('content', '')}"
                for m in msgs)
        if isinstance(prompt, list):
            prefix = prompt[:self.cfg.affinity_tokens]
        else:
            prefix = str(prompt)[:self.cfg.affinity_tokens * 4]
        digest = hashlib.sha1(repr(prefix).encode()).digest()
        return int.from_bytes(digest[:8], "big")

    def _pick(self, key: int, exclude=()) -> Replica:
        """Prefix-affinity first: the consistent-hash target takes the
        request when it is routable and has a free slot (its prefix
        cache already holds this prompt family's entry); otherwise the
        least-loaded routable replica. Decode-role replicas are
        reserved for handoff decode work — they take client traffic
        only when NO other replica is routable (degraded fleet beats a
        503)."""
        n = len(self.replicas)
        candidates = [r for r in self.replicas
                      if r.idx not in exclude and self._routable(r)]
        if not candidates:
            raise NoReplica()
        front = [r for r in candidates if r.role != "decode"]
        if front:
            candidates = front
        affinity = self.replicas[key % n]
        # a browned-out replica is degrading service to protect itself:
        # prefix affinity is not worth routing INTO the pressure, and
        # the least-loaded fallback prefers the lowest brownout level
        if affinity in candidates and affinity.occupancy < 1.0 \
                and affinity.brownout == 0:
            return affinity
        return min(candidates,
                   key=lambda r: (r.brownout, r.occupancy,
                                  r.queue_depth, len(r.inflight), r.idx))

    def _pick_wait(self, key: int, exclude: Dict[int, int],
                   deadline: float) -> Replica:
        """``_pick`` that RIDES OUT a replica gap: with every replica
        momentarily unroutable (the last healthy one just died and its
        replacement is mid-spawn), keep polling until ``deadline``
        instead of failing the request. ``exclude`` maps replica idx ->
        the GENERATION that failed us: a respawned process at the same
        index is a new generation and gets forgiven, while the dead
        instance stays excluded even during the window where the
        supervisor still believes it healthy (state is probe-delayed;
        generation only moves on respawn)."""
        while True:
            try:
                return self._pick(key, exclude)
            except NoReplica:
                stale = [i for i, gen in exclude.items()
                         if self.replicas[i].generation != gen]
                for i in stale:
                    del exclude[i]
                if stale:
                    continue
                if time.monotonic() >= deadline:
                    raise
                time.sleep(min(0.05, self.cfg.health_sec))

    def retry_after_hint(self) -> int:
        """Seconds until a fresh replica is plausibly routable."""
        return max(1, int(round(2 * self.cfg.health_sec)))

    @staticmethod
    def _tenant_of(headers) -> Optional[str]:
        """Same identity derivation as the replica api_server (explicit
        X-Tenant-Id, else a stable API-key hash) so router-fronted and
        direct traffic land in the same per-tenant buckets."""
        tid = headers.get("X-Tenant-Id")
        if tid:
            return str(tid)[:64]
        auth = headers.get("Authorization")
        if auth:
            return "key-" + hashlib.sha256(
                auth.encode("utf-8", "replace")).hexdigest()[:12]
        return None

    # -- forwarding ---------------------------------------------------------

    def _handoff_targets(self, prefill: Replica) -> List[str]:
        """host:port decode candidates for a prefill replica's KV
        handoff, ordered least-loaded. Decode-role replicas first;
        with none routable, mixed replicas stand in (the prefill
        replica itself is never a target)."""
        cands = [r for r in self.replicas
                 if r is not prefill and self._routable(r)]
        pool = [r for r in cands if r.role == "decode"] \
            or [r for r in cands if r.role == "mixed"]
        pool.sort(key=lambda r: (r.brownout, r.occupancy,
                                 r.queue_depth, len(r.inflight), r.idx))
        return [f"{self.host}:{r.port}"
                for r in pool[:max(1, self.cfg.handoff_fanout)]]

    # -- live migration -----------------------------------------------------

    def _migrate_peers(self, r: Replica) -> List[str]:
        """host:port targets for replica ``r``'s in-flight sequences:
        every OTHER routable replica, least-loaded first."""
        peers = [x for x in self.replicas
                 if x is not r and self._routable(x)]
        peers.sort(key=lambda x: (x.brownout, x.occupancy,
                                  x.queue_depth, len(x.inflight),
                                  x.idx))
        return [f"{self.host}:{x.port}" for x in peers]

    def _migrate_off(self, r: Replica, reason: str,
                     qos: Optional[str] = None,
                     max_sequences: Optional[int] = None) -> dict:
        """Ask replica ``r`` to export its mid-decode sequences to
        healthy peers (POST /v1/admin/migrate_out) ahead of a planned
        disruption. Best-effort by design: a refused or failed call
        falls back to the plain SIGTERM drain — in-flight work
        finishes locally, zero 5xx, just not zero recompute if the
        process then dies."""
        targets = self._migrate_peers(r)
        out: dict = {"requested": False, "migrated": 0, "failed": 0}
        if not targets or not r.alive():
            return out
        doc: dict = {"targets": targets}
        if qos:
            doc["qos"] = qos
        if max_sequences:
            doc["max_sequences"] = int(max_sequences)
        try:
            status, body = self._http_post(
                r.port, "/v1/admin/migrate_out", doc,
                self.cfg.migrate_admin_timeout_sec)
            out["requested"] = True
            out["status"] = status
            try:
                res = json.loads(body)
            except ValueError:
                res = {}
            out["migrated"] = int(res.get("migrated", 0) or 0)
            out["failed"] = int(res.get("failed", 0) or 0)
            out["skipped"] = int(res.get("skipped", 0) or 0)
            self._count("migrations_requested")
            if out["migrated"]:
                self._count("sequences_migrated", out["migrated"])
            if out["failed"]:
                self._count("sequences_migrate_failed", out["failed"])
        except OSError as e:
            out["error"] = str(e)[:200]
        self.flight.record("migrate_off", replica=r.idx,
                           reason=reason, qos=qos, **out)
        return out

    def _maybe_brownout_migrate(self, r: Replica) -> None:
        """Brownout ladder, fleet rung: a replica that reached level 3
        is already degrading everyone it serves — when an idle peer
        exists, push a few batch-QoS sequences over instead of letting
        them starve behind the interactive tier. Rate-limited per
        replica; interactive traffic never moves this way (its KV is
        hot here; migration is for work that tolerates the hop)."""
        wants = bool((r.migration or {}).get("wants_migration")) \
            or r.brownout >= 3
        if not wants:
            return
        now = time.monotonic()
        if now - r.last_brownout_migrate \
                < self.cfg.brownout_migrate_interval_sec:
            return
        if not any(x is not r and self._routable(x)
                   and x.brownout == 0 for x in self.replicas):
            return                       # nowhere cooler to go
        r.last_brownout_migrate = now
        self._count("brownout_migrations")
        self._migrate_off(r, "brownout", qos="batch",
                          max_sequences=self.cfg.brownout_migrate_batch)

    def _replica_at(self, target: str) -> Optional[Replica]:
        """The replica serving ``host:port``, or None. State is NOT
        checked: a migration target just acked a stage, which beats a
        probe-delayed lifecycle label; a dead process fails the
        forward and the caller falls back."""
        try:
            port = int(str(target).rsplit(":", 1)[-1])
        except ValueError:
            return None
        for r in self.replicas:
            if r.port == port and r.alive():
                return r
        return None

    @staticmethod
    def _migrated_of(data: bytes) -> Optional[dict]:
        """Parse a replica's mid-decode migration handoff body
        ({"object": "migration", "migrated": true, "resume_id",
        "target", ...}); None for a normal completion."""
        if b'"migrated"' not in data[:256]:
            return None
        try:
            doc = json.loads(data)
        except ValueError:
            return None
        if isinstance(doc, dict) and doc.get("migrated") is True:
            return doc
        return None

    def _continue_migrated(self, entry: JournalEntry,
                           mig: dict) -> Tuple[int, bytes]:
        """A replica exported ``entry``'s sequence mid-decode: finish
        the request by re-POSTing the journaled ORIGINAL body to the
        migration target with ``X-Resume-Id``. The target claims the
        staged KV state, resumes at the exact sampler position, and
        returns the FULL completion (it detokenizes pre + post tokens
        together), so the client response is byte-identical to an
        unmigrated run. Chained hops (the target itself drains) loop,
        bounded by fleet size. Raises ``ReplicaLost`` when the staged
        state's home is gone — the caller replays from the journal."""
        hops = 0
        while True:
            resume_id = mig.get("resume_id")
            target = str(mig.get("target") or "")
            self._count("migration_continuations")
            self.journal.record_migration(entry.rid, resume_id,
                                          target)
            self.flight.record(
                "migration_continue", rid=entry.rid,
                resume_id=resume_id, target=target,
                **({"trace_id": entry.trace[0]}
                   if entry.trace is not None else {}))
            if entry.trace is not None:
                self.spans.annotate(
                    entry.trace[0], "migration_continue",
                    parent_id=entry.trace[2], target=target,
                    resume_id=resume_id, request_id=entry.rid)
            rep = self._replica_at(target)
            if rep is None or not resume_id:
                raise ReplicaLost(
                    f"migration target {target!r} not reachable")
            hdrs = self._fwd_headers(entry)
            hdrs["X-Resume-Id"] = str(resume_id)
            rep.inflight.add(entry.rid)
            self.journal.assign(entry.rid, rep.idx, rep.generation)
            conn = http.client.HTTPConnection(
                self.host, rep.port,
                timeout=self.cfg.connect_timeout_sec)
            try:
                conn.request("POST", entry.path, body=entry.body,
                             headers=hdrs)
                conn.sock.settimeout(self.cfg.forward_timeout_sec)
                resp = conn.getresponse()
                status, data = resp.status, resp.read()
            except (OSError, http.client.HTTPException) as e:
                self._breaker_failure(rep)
                raise ReplicaLost(
                    f"migration target {target}: "
                    f"{type(e).__name__}: {e}") from e
            finally:
                rep.inflight.discard(entry.rid)
                conn.close()
            nxt = self._migrated_of(data) if status == 200 else None
            if nxt is None:
                self._breaker_success(rep)
                return status, data
            mig = nxt
            hops += 1
            if hops > len(self.replicas) + 1:
                raise ReplicaLost("migration continuation loop")

    def _fwd_headers(self, entry: JournalEntry,
                     r: Optional[Replica] = None) -> Dict[str, str]:
        """Headers for a replica forward: the client's tenant identity
        must survive the hop or every request lands in the replica's
        shared 'default' rate-limit bucket. A non-streaming forward to
        a prefill-role replica also names its decode candidates
        (X-Handoff-Targets) — the replica prefills, ships KV to the
        first target it can reach, and relays the decode's answer."""
        h = {"Content-Type": "application/json"}
        if entry.tenant:
            h["X-Tenant-Id"] = entry.tenant
        if entry.trace is not None:
            # the replica parents its engine spans under the ROUTER
            # span, not the client's — replays re-forward the same id,
            # so every attempt lands on one timeline
            h["traceparent"] = make_traceparent(entry.trace[0],
                                                entry.trace[2])
        if r is not None and r.role == "prefill" and not entry.stream:
            targets = self._handoff_targets(r)
            if targets:
                h["X-Handoff-Targets"] = ",".join(targets)
        return h

    def _forward_buffered(self, r: Replica, entry: JournalEntry
                          ) -> Tuple[int, bytes]:
        """POST the journaled body to one replica and buffer the full
        response. Raises ``ReplicaLost`` on any transport failure — a
        SIGKILLed process closes its sockets, so every death mode ends
        here rather than in a client-visible hang."""
        rid = entry.rid
        r.inflight.add(rid)
        conn = http.client.HTTPConnection(
            self.host, r.port, timeout=self.cfg.connect_timeout_sec)
        try:
            conn.request("POST", entry.path, body=entry.body,
                         headers=self._fwd_headers(entry, r))
            conn.sock.settimeout(self.cfg.forward_timeout_sec)
            resp = conn.getresponse()
            return resp.status, resp.read()
        except (OSError, http.client.HTTPException) as e:
            raise ReplicaLost(f"replica {r.idx}: "
                              f"{type(e).__name__}: {e}") from e
        finally:
            r.inflight.discard(rid)
            conn.close()

    def _forward_hedged(self, primary: Replica, entry: JournalEntry,
                        exclude: Dict[int, int]
                        ) -> Tuple[Replica, int, bytes]:
        """Primary forward, plus ONE duplicate on another replica when
        no response lands inside hedge_ms. First answer wins; the
        loser's closed connection triggers the replica engine's
        client-disconnect abort, freeing its slot."""
        hedge_ms = self.cfg.hedge_ms
        results: "queue.Queue" = queue.Queue()

        def run(rep: Replica):
            try:
                status, data = self._forward_buffered(rep, entry)
                results.put((rep, None, status, data))
            except ReplicaLost as e:
                results.put((rep, e, 0, b""))

        threading.Thread(target=run, args=(primary,), daemon=True).start()
        launched = 1
        if hedge_ms > 0 and not entry.stream:
            try:
                got = results.get(timeout=hedge_ms / 1000.0)
                results.put(got)       # not late: hand it back
            except queue.Empty:
                try:
                    second = self._pick(
                        entry.key, set(exclude) | {primary.idx})
                except NoReplica:
                    second = None
                if second is not None:
                    entry.hedged = True
                    self._count("hedges")
                    self._c_hedges.inc()
                    self.flight.record("hedge", rid=entry.rid,
                                       primary=primary.idx,
                                       hedge=second.idx)
                    if entry.trace is not None:
                        self.spans.annotate(
                            entry.trace[0], "hedge",
                            parent_id=entry.trace[2],
                            primary=primary.idx, hedge=second.idx,
                            request_id=entry.rid)
                    threading.Thread(target=run, args=(second,),
                                     daemon=True).start()
                    launched += 1
        err: Optional[ReplicaLost] = None
        err_rep = primary
        for _ in range(launched):
            rep, e, status, data = results.get()
            if e is None:
                return rep, status, data
            err, err_rep = e, rep
            self._breaker_failure(rep)
        raise ReplicaLost(str(err)) from err

    # -- request paths ------------------------------------------------------

    def route_buffered(self, entry: JournalEntry) -> Tuple[int, bytes]:
        """Non-streaming path: forward, and on replica loss REPLAY the
        journaled request on a healthy replica (up to max_replays).
        A replica's own 503 (drain race) re-routes without burning the
        replay budget — that is the rolling restart's zero-5xx leg."""
        t0 = time.monotonic()
        pick_deadline = t0 + self.cfg.no_replica_wait_sec
        exclude: Dict[int, int] = {}
        reroutes = 0
        while True:
            try:
                r = self._pick_wait(entry.key, exclude, pick_deadline)
            except NoReplica:
                return 503, json.dumps({"error": {
                    "message": "no healthy replica; retry shortly",
                    "type": "unavailable", "code": 503,
                    "retry_after": self.retry_after_hint()}}).encode()
            self.journal.assign(entry.rid, r.idx, r.generation)
            try:
                used, status, data = self._forward_hedged(
                    r, entry, exclude)
            except ReplicaLost as e:
                exclude[r.idx] = r.generation
                self._count("failovers")
                self._c_failovers.inc()
                self.flight.record(
                    "failover", rid=entry.rid, replica=r.idx,
                    error=str(e)[:200],
                    **({"trace_id": entry.trace[0]}
                       if entry.trace is not None else {}))
                if entry.trace is not None:
                    self.spans.annotate(
                        entry.trace[0], "failover",
                        parent_id=entry.trace[2], replica=r.idx,
                        request_id=entry.rid, error=str(e)[:120])
                if entry.replays < self.cfg.max_replays:
                    entry.replays += 1
                    self._count("replays")
                    self._c_replays.inc()
                    self.flight.record("replay", rid=entry.rid,
                                       attempt=entry.replays)
                    if entry.trace is not None:
                        self.spans.annotate(
                            entry.trace[0], "failover_replay",
                            parent_id=entry.trace[2],
                            attempt=entry.replays,
                            request_id=entry.rid)
                    continue
                return 502, json.dumps({"error": {
                    "message": "replica failed and replay budget is "
                               "spent", "type": "replica_lost",
                    "code": 502, "replays": entry.replays,
                    "retry_after": self.retry_after_hint()}}).encode()
            if status == 429:
                # per-tenant rate limit: every replica enforces the
                # same tenant budget, so re-routing would just evade
                # it — propagate verbatim (Retry-After preserved by
                # the handler), no replay burn, no breaker hit
                self._breaker_success(used)
                self._count("shed_429")
                self.flight.record("shed_429", rid=entry.rid,
                                   replica=used.idx,
                                   tenant=entry.tenant or "default")
                self._count("requests")
                # idx bounded by fleet size, status by HTTP codes
                self._c_requests.labels(
                    str(used.idx), str(status)).inc()  # graftlint: disable=metric-label-cardinality
                return status, data
            if status == 503:
                # the replica is shedding (drain race or overload):
                # someone else takes it; re-route burns no replay
                # budget — only when every replica shed does the 503
                # reach the client
                exclude[used.idx] = used.generation
                reroutes += 1
                self._count("rerouted_503")
                self.flight.record("reroute_503", rid=entry.rid,
                                   replica=used.idx)
                if reroutes <= len(self.replicas):
                    continue
                return 503, data
            if status == 200:
                mig = self._migrated_of(data)
                if mig is not None:
                    # the sequence moved mid-decode (drain, restart,
                    # scale-down, brownout): finish it on its new home
                    self._breaker_success(used)
                    try:
                        status, data = self._continue_migrated(
                            entry, mig)
                        # continuation served by the TARGET: count and
                        # return here so a rare target-side 5xx does
                        # not land on the source's breaker
                        self._count("requests")
                        # idx bounded by fleet size, status by HTTP
                        self._c_requests.labels(
                            str(used.idx), str(status)).inc()  # graftlint: disable=metric-label-cardinality
                        self._h_latency.observe(
                            time.monotonic() - t0)
                        return status, data
                    except ReplicaLost as e:
                        # the staged state died with its target: fall
                        # back to a full journal replay — recomputes
                        # the prefix, never wrong
                        self._count("migration_fallback_replays")
                        self.flight.record(
                            "migration_fallback", rid=entry.rid,
                            error=str(e)[:200])
                        if entry.replays < self.cfg.max_replays:
                            entry.replays += 1
                            self._count("replays")
                            self._c_replays.inc()
                            continue
                        return 502, json.dumps({"error": {
                            "message": "migration continuation failed "
                                       "and replay budget is spent",
                            "type": "replica_lost", "code": 502,
                            "retry_after":
                                self.retry_after_hint()}}).encode()
            if status >= 500:
                self._breaker_failure(used)
            else:
                self._breaker_success(used)
            self._count("requests")
            # idx bounded by fleet size, status by HTTP codes
            self._c_requests.labels(
                str(used.idx), str(status)).inc()  # graftlint: disable=metric-label-cardinality
            self._h_latency.observe(time.monotonic() - t0)
            return status, data

    # streaming handled in the HTTP handler (needs the client socket)

    # -- rolling restart ----------------------------------------------------

    def rolling_restart(self) -> dict:
        """Drain + respawn replicas ONE AT A TIME: stop routing to the
        replica, SIGTERM it (the api_server's drain finishes in-flight
        work, then the process exits), respawn, wait healthy, move on.
        Raises ``RuntimeError`` when already in progress."""
        if not self._admin_lock.acquire(blocking=False):
            raise RuntimeError("rolling restart already in progress")
        t0 = time.monotonic()
        results = []
        self._rolling = True
        self.flight.record("rolling_restart_begin",
                           replicas=len(self.replicas))
        try:
            for r in self.replicas:
                if r.state == QUARANTINED:
                    results.append({"replica": r.idx,
                                    "skipped": "quarantined"})
                    continue
                r.planned_restart = True   # the supervisor hands over
                self._set_state(r, DRAINING)
                step = {"replica": r.idx, "pid": r.pid}
                # live migration BEFORE the SIGTERM: mid-decode
                # sequences move to healthy peers (the in-flight
                # relays see the migrated marker and re-forward), so
                # the drain has nothing left to wait out and the
                # restart costs zero recomputed tokens — a refused or
                # failed migrate falls back to the plain drain
                step["migrate"] = self._migrate_off(
                    r, "rolling_restart")
                try:
                    if r.proc is not None and r.proc.poll() is None:
                        r.proc.terminate()     # SIGTERM -> drain
                        try:
                            r.proc.wait(
                                timeout=self.cfg.drain_exit_timeout_sec)
                        except Exception:
                            r.proc.kill()
                            r.proc.wait(timeout=5)
                            step["forced_kill"] = True
                    self._respawn(r)
                    if not self._wait_healthy(
                            r, self.cfg.spawn_timeout_sec):
                        step["error"] = ("replacement never became "
                                         "healthy")
                        results.append(step)
                        break
                    step["ok"] = True
                    results.append(step)
                finally:
                    r.planned_restart = False
            return {"rolling_restart": results,
                    "duration_s": round(time.monotonic() - t0, 3),
                    "ok": all(s.get("ok") or s.get("skipped")
                              for s in results)}
        finally:
            self._rolling = False
            self.flight.record("rolling_restart_end")
            self._admin_lock.release()

    def fleet_profiler(self, body: Optional[dict] = None) -> dict:
        """``POST /v1/admin/profiler``: fan a time-boxed jax.profiler
        capture out to every routable replica SIMULTANEOUSLY (the
        interesting regressions are fleet-synchronized: a noisy
        neighbor, a tunnel hiccup, a bad deploy hits every replica in
        the same second). Each replica captures into its own subdir of
        ``log_dir`` and auto-stops at ``duration_sec`` (clamped to
        ``BIGDL_TPU_PROFILER_MAX_SEC``) via the profiler watchdog — no
        stop fan-out needed. The whole capture is stitched to one fleet
        ``capture_id`` (a trace id), recorded as a router span so
        ``GET /v1/trace/{capture_id}`` shows who captured what.
        Raises ``RuntimeError`` when an admin operation is already in
        progress, ``ValueError`` on a bad duration."""
        body = body or {}
        duration = body.get("duration_sec")
        if duration is not None:
            try:
                duration = float(duration)
            except (TypeError, ValueError):
                raise ValueError(
                    f"duration_sec must be a positive number, got "
                    f"{body.get('duration_sec')!r}")
            if duration <= 0:
                raise ValueError(
                    f"duration_sec must be a positive number, got "
                    f"{duration}")
        log_dir = body.get("log_dir") or os.path.join(
            os.environ.get("BIGDL_TPU_POSTMORTEM_DIR") or "/tmp",
            "fleet_profiler")
        if not os.path.isabs(log_dir):
            raise ValueError(
                f"log_dir must be an absolute path, got {log_dir!r}")
        if not self._admin_lock.acquire(blocking=False):
            raise RuntimeError("an admin operation is already in "
                               "progress")
        try:
            capture_id = new_trace_id()
            t0 = time.time()
            targets = [r for r in self.replicas
                       if r.state == HEALTHY and r.alive()]
            self.flight.record("fleet_profiler_begin",
                               capture_id=capture_id,
                               replicas=[r.idx for r in targets],
                               log_dir=log_dir,
                               duration_sec=duration)
            # one thread per replica: the whole point is that every
            # replica's capture brackets the SAME wall-clock window
            # (profiler init can take seconds — serial fan-out would
            # stagger the windows by that much per replica)
            results = []
            for r in targets:
                sub = os.path.join(log_dir, capture_id,
                                   f"replica{r.idx}")
                results.append({"replica": r.idx, "port": r.port,
                                "log_dir": sub})

            def _start_one(r, row):
                doc = {"log_dir": row["log_dir"],
                       "capture_id": capture_id}
                if duration is not None:
                    doc["duration_sec"] = duration
                try:
                    status, raw = self._http_post(
                        r.port, "/v1/profiler/start", doc,
                        max(self.cfg.health_timeout_sec, 15.0))
                    row["status"] = status
                    try:
                        row["body"] = json.loads(raw)
                    except ValueError:
                        pass
                    row["ok"] = status == 200
                except OSError as e:
                    row["ok"] = False
                    row["error"] = str(e)

            threads = [threading.Thread(target=_start_one, args=tr,
                                        daemon=True)
                       for tr in zip(targets, results)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30.0)
            for r, row in zip(targets, results):
                row.setdefault("ok", False)
                self.spans.record(
                    "fleet_capture", capture_id,
                    t_start=t0, t_end=time.time(),
                    replica=r.idx, port=r.port,
                    log_dir=row["log_dir"], ok=row["ok"])
            started = sum(1 for row in results if row.get("ok"))
            self._count("fleet_profiler_captures", started)
            self.flight.record("fleet_profiler_end",
                               capture_id=capture_id, started=started,
                               replicas=len(results))
            return {"capture_id": capture_id, "log_dir": log_dir,
                    "duration_sec": duration, "replicas": results,
                    "started": started, "ok": started == len(results)
                    and bool(results)}
        finally:
            self._admin_lock.release()

    def _wait_healthy(self, r: Replica, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if r.proc is not None and r.proc.poll() is not None:
                return False
            try:
                status, _ = self._http_get(r.port, "/health",
                                           self.cfg.health_timeout_sec)
                if status == 200:
                    r.probe_failures = 0
                    self._set_state(r, HEALTHY)
                    return True
            except OSError:
                pass
            time.sleep(min(0.1, self.cfg.health_sec))
        return False

    # -- fleet mutation (autoscaler) ----------------------------------------
    #
    # All three mutators are called with self._admin_lock HELD by the
    # caller (the autoscaler tick) — the same lock rolling_restart
    # takes, so a scale decision can never race a rolling restart.
    # Replicas are NEVER removed from self.replicas (routing holds
    # positional idx lookups); a retired replica stays in the list in
    # the terminal RETIRED state, which the supervisor skips.

    def add_replica(self, role: str = "mixed") -> Replica:
        """Grow the fleet by one replica (scale-up). Returns the new
        Replica immediately (state STARTING); the supervisor's probe
        loop promotes it to HEALTHY once /health answers."""
        if role not in ROLES:
            raise ValueError(f"unknown replica role {role!r}")
        r = Replica(len(self.replicas), _free_port(self.host),
                    role=role)
        self._respawn(r, initial=True)
        self.replicas.append(r)
        self._count("autoscale_spawned")
        self.flight.record("replica_added", replica=r.idx,
                           port=r.port, role=role)
        return r

    def retire_replica(self, r: Replica,
                       reason: str = "autoscale") -> bool:
        """Drain and permanently remove one replica (scale-down):
        routing stops immediately, SIGTERM runs the api_server's
        graceful drain, and the slot is left in the terminal RETIRED
        state. Returns False WITHOUT touching the process when the
        replica is the last healthy one (a fleet of zero serves
        nothing) or is not in a retirable state."""
        healthy_others = [x for x in self.replicas
                          if x is not r and x.state == HEALTHY
                          and not x.planned_restart]
        if r.state != HEALTHY or r.planned_restart \
                or not healthy_others:
            self._count("autoscale_refused")
            self.flight.record(
                "retire_refused", replica=r.idx,
                reason=("last_healthy" if not healthy_others
                        else f"state:{r.state}"))
            return False
        r.planned_restart = True         # supervisor hands the proc over
        self._set_state(r, DRAINING)
        # scale-down is a planned disruption: move the mid-decode
        # sequences to surviving replicas first, then drain whatever
        # (if anything) refused to export
        mig = self._migrate_off(r, reason)
        try:
            if r.proc is not None and r.proc.poll() is None:
                r.proc.terminate()       # SIGTERM -> graceful drain
                try:
                    r.proc.wait(timeout=self.cfg.drain_exit_timeout_sec)
                except Exception:
                    try:
                        r.proc.kill()
                        r.proc.wait(timeout=5)
                    except Exception:
                        pass
        finally:
            self._set_state(r, RETIRED)
            r.planned_restart = False
        self._count("autoscale_retired")
        self.flight.record("replica_retired", replica=r.idx,
                           reason=reason,
                           migrated=mig.get("migrated", 0))
        return True

    def reassign_role(self, r: Replica, role: str) -> bool:
        """Flip one replica's fleet role via drain + respawn (the role
        is a process property, resolved from the spawn env — never
        mutated live). Refuses on the last healthy replica: the flip
        makes it unavailable for a spawn cycle."""
        if role not in ROLES:
            raise ValueError(f"unknown replica role {role!r}")
        healthy_others = [x for x in self.replicas
                          if x is not r and x.state == HEALTHY
                          and not x.planned_restart]
        if r.state != HEALTHY or r.planned_restart \
                or not healthy_others:
            self._count("autoscale_refused")
            self.flight.record("role_flip_refused", replica=r.idx,
                               role=role)
            return False
        prev = r.role
        r.planned_restart = True
        self._set_state(r, DRAINING)
        try:
            if r.proc is not None and r.proc.poll() is None:
                r.proc.terminate()
                try:
                    r.proc.wait(timeout=self.cfg.drain_exit_timeout_sec)
                except Exception:
                    try:
                        r.proc.kill()
                        r.proc.wait(timeout=5)
                    except Exception:
                        pass
            r.role = role
            self._respawn(r)
            ok = self._wait_healthy(r, self.cfg.spawn_timeout_sec)
        finally:
            r.planned_restart = False
        self._count("autoscale_role_flips")
        self.flight.record("replica_role_flip", replica=r.idx,
                           prev=prev, role=role, ok=ok)
        return ok

    # -- distributed-trace fan-out ------------------------------------------

    def trace_timeline(self, trace_id: str) -> dict:
        """The ``GET /v1/trace/{id}`` document: this router's own spans
        plus every replica's (``GET /v1/internal/spans?trace_id=``),
        stitched by ``merge_timeline`` with a per-replica clock-skew
        estimate (local midpoint of the fan-out RTT minus the replica's
        reported ``now``)."""
        groups: List[Tuple[float, List[dict]]] = [
            (0.0, self.spans.spans_for(trace_id))]
        for r in self.replicas:
            if not r.alive():
                continue
            try:
                t_req0 = time.time()
                status, body = self._http_get(
                    r.port, f"/v1/internal/spans?trace_id={trace_id}",
                    self.cfg.health_timeout_sec)
                t_req1 = time.time()
                if status != 200:
                    continue
                doc = json.loads(body)
                skew = ((t_req0 + t_req1) / 2.0
                        - float(doc.get("now", t_req1)))
                groups.append((skew, doc.get("spans") or []))
            except (OSError, ValueError):
                continue
        # a client-supplied parent span lives outside the fleet: spans
        # pointing at it are NOT orphans
        ext = [s["parent_id"] for s in self.spans.spans_for(trace_id)
               if s.get("name") == "router.request"
               and s.get("parent_id")]
        return merge_timeline(trace_id, groups, external_parents=ext)

    def trace_index(self, k: int = 16) -> List[dict]:
        """The ``GET /v1/traces`` list: recent slow traces (top-k by
        duration) merged across the router and every live replica."""
        best: Dict[str, dict] = {}

        def take(t: dict) -> None:
            tid = t.get("trace_id")
            cur = best.get(tid)
            if cur is None or t.get("duration_s", 0.0) \
                    > cur.get("duration_s", 0.0):
                best[tid] = t

        for t in self.spans.recent_traces(k):
            take(t)
        for r in self.replicas:
            if not r.alive():
                continue
            try:
                status, body = self._http_get(
                    r.port, "/v1/internal/spans",
                    self.cfg.health_timeout_sec)
                if status != 200:
                    continue
                for t in json.loads(body).get("traces") or []:
                    take(t)
            except (OSError, ValueError):
                continue
        out = sorted(best.values(),
                     key=lambda d: -d.get("duration_s", 0.0))
        return out[:max(k, 0)]

    # -- introspection ------------------------------------------------------

    def _tenant_aggregate(self) -> dict:
        """Fleet-wide per-tenant counters: the sum of every replica's
        probed overload.tenants block (admitted/shed/generated)."""
        agg: Dict[str, collections.Counter] = {}
        for r in self.replicas:
            for name, t in (r.tenants or {}).items():
                acc = agg.setdefault(str(name), collections.Counter())
                for k, v in t.items():
                    if isinstance(v, (int, float)):
                        acc[k] += v
        return {name: dict(c) for name, c in sorted(agg.items())}

    def _count(self, key: str, n: int = 1) -> None:
        """Bump a stats counter (thread-safe: supervisor + handlers)."""
        with self._lock:
            self.counts[key] += n

    def counts_snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {k: int(v) for k, v in sorted(self.counts.items())}

    def _perf_aggregate(self) -> dict:
        """Fleet roofline view from the per-replica /v1/stats perf
        blocks: per-replica utils plus fleet min/mean (the min is the
        alarm — one replica off the roof drags every hedged request)
        and the count of tripped sentinels."""
        per: Dict[str, dict] = {}
        utils: List[float] = []
        tripped = 0
        for r in self.replicas:
            if not r.perf:
                continue
            per[str(r.idx)] = dict(r.perf)
            u = r.perf.get("roofline_util_decode")
            if isinstance(u, (int, float)):
                utils.append(float(u))
            if r.perf.get("sentinel_tripped"):
                tripped += 1
        out: dict = {"replicas": per, "sentinels_tripped": tripped}
        if utils:
            out["decode_util_min"] = round(min(utils), 4)
            out["decode_util_mean"] = round(
                sum(utils) / len(utils), 4)
        return out

    def _quality_aggregate(self) -> dict:
        """Fleet quality view from the per-replica /v1/stats quality
        blocks: per-replica NLL/probe numbers plus the fleet's worst
        probe NLL (one silently-degraded replica is the alarm — it
        serves wrong-but-plausible tokens at full speed) and the count
        of tripped quality sentinels."""
        per: Dict[str, dict] = {}
        probe_nlls: List[float] = []
        tripped = 0
        for r in self.replicas:
            if not r.quality:
                continue
            per[str(r.idx)] = dict(r.quality)
            pn = r.quality.get("probe_nll")
            if isinstance(pn, (int, float)):
                probe_nlls.append(float(pn))
            if r.quality.get("sentinel_tripped"):
                tripped += 1
        out: dict = {"replicas": per, "sentinels_tripped": tripped}
        if probe_nlls:
            out["probe_nll_max"] = round(max(probe_nlls), 4)
            out["probe_nll_mean"] = round(
                sum(probe_nlls) / len(probe_nlls), 4)
        return out

    def _slo_aggregate(self) -> dict:
        """Fleet SLO view from the per-replica /v1/stats slo blocks:
        total active alerts and the worst burn rate anywhere (one
        replica burning its budget is the fleet's page), plus the
        canary prober's correctness state."""
        per: Dict[str, dict] = {}
        alerts_active = alerts_total = 0
        burn_max = 0.0
        for r in self.replicas:
            if not r.slo:
                continue
            per[str(r.idx)] = dict(r.slo)
            alerts_active += int(r.slo.get("alerts_active") or 0)
            alerts_total += int(r.slo.get("alerts_total") or 0)
            bm = r.slo.get("burn_rate_max")
            if isinstance(bm, (int, float)):
                burn_max = max(burn_max, float(bm))
        return {
            "replicas": per,
            "alerts_active": alerts_active,
            "alerts_total": alerts_total,
            "burn_rate_max": round(burn_max, 4),
            "canary": self.canary.snapshot(),
        }

    def _migration_aggregate(self) -> dict:
        """Fleet live-migration view: the sum of every replica's
        probed counters (per-generation deltas keep respawn resets
        from double-counting) plus live staging depth."""
        agg = collections.Counter()
        staged = pending = 0
        for r in self.replicas:
            for k, v in r.migration_counts.items():
                agg[k] += v
            mg = r.migration or {}
            staged += int(mg.get("staged", 0) or 0)
            pending += int(mg.get("pending_out", 0) or 0)
        return {**{k: int(v) for k, v in sorted(agg.items())},
                "staged": staged, "pending_out": pending}

    def stats_snapshot(self) -> dict:
        """JSON-ready router state for ``GET /v1/router/stats`` (and
        the bench JSON's ``router`` block)."""
        return {
            "replicas": [r.snapshot() for r in self.replicas],
            "journal_depth": self.journal.depth(),
            "journal": self.journal.snapshot(),
            "migration": self._migration_aggregate(),
            "spans": self.spans.snapshot(),
            "tenants": self._tenant_aggregate(),
            "counters": self.counts_snapshot(),
            "rolling_restart_in_progress": self._rolling,
            "perf": self._perf_aggregate(),
            "quality": self._quality_aggregate(),
            "slo": self._slo_aggregate(),
            "roles": {ro: sum(1 for r in self.replicas
                              if r.role == ro and r.state == HEALTHY)
                      for ro in ROLES},
            "autoscaler": (self.autoscaler.snapshot()
                           if self.autoscaler is not None else None),
            "config": {
                "replicas": self.cfg.replicas,
                "health_sec": self.cfg.health_sec,
                "hedge_ms": self.cfg.hedge_ms,
                "crash_budget": self.cfg.crash_budget,
                "canary_sec": self.cfg.canary_sec,
                "breaker_threshold": self.cfg.breaker_threshold,
                "max_replays": self.cfg.max_replays,
                "affinity_tokens": self.cfg.affinity_tokens,
                "handoff_fanout": self.cfg.handoff_fanout,
                "journal_path": self.cfg.journal_path,
            },
        }

    # -- http front ---------------------------------------------------------

    def make_handler(router):
        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):   # quiet
                pass

            def _json(self, code: int, obj, headers=()):
                body = obj if isinstance(obj, bytes) \
                    else json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                try:
                    self.wfile.write(body)
                except OSError:
                    pass

            def _proxy(self, method: str, body: Optional[bytes] = None):
                """Pass non-completion traffic (models, stats, memory,
                metrics-of-replica, profiler) to any routable replica."""
                try:
                    r = router._pick(0)
                except NoReplica:
                    return self._json(503, {"error": {
                        "message": "no healthy replica",
                        "type": "unavailable", "code": 503}})
                conn = http.client.HTTPConnection(
                    router.host, r.port,
                    timeout=router.cfg.forward_timeout_sec)
                try:
                    conn.request(method, self.path, body=body,
                                 headers={"Content-Type":
                                          "application/json"})
                    resp = conn.getresponse()
                    data = resp.read()
                    ctype = resp.getheader("Content-Type",
                                           "application/json")
                    self.send_response(resp.status)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                except (OSError, http.client.HTTPException) as e:
                    self._json(502, {"error": {
                        "message": f"replica proxy failed: {e}",
                        "type": "replica_lost", "code": 502}})
                finally:
                    conn.close()

            def do_GET(self):
                if self.path in ("/health", "/ping"):
                    n = sum(1 for r in router.replicas
                            if router._routable(r))
                    if n:
                        self._json(200, {"status": "ok",
                                         "routable_replicas": n})
                    else:
                        self._json(
                            503,
                            {"status": "no_healthy_replica",
                             "retry_after": router.retry_after_hint()},
                            headers=(("Retry-After",
                                      str(router.retry_after_hint())),))
                elif self.path == "/metrics":
                    body = router.registry.render().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/v1/router/stats":
                    self._json(200, router.stats_snapshot())
                elif self.path == "/v1/router/flight":
                    self._json(200, {"events":
                                     router.flight.snapshot()})
                elif self.path.startswith("/v1/trace/"):
                    tid = self.path[len("/v1/trace/"):].split("?")[0]
                    self._json(200, router.trace_timeline(tid))
                elif self.path == "/v1/traces" \
                        or self.path.startswith("/v1/traces?"):
                    from urllib.parse import parse_qs, urlparse

                    q = parse_qs(urlparse(self.path).query)
                    try:
                        k = int((q.get("k") or ["16"])[0])
                    except ValueError:
                        k = 16
                    self._json(200, {"traces": router.trace_index(k)})
                else:
                    self._proxy("GET")

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n) if n else b"{}"
                if self.path == "/v1/admin/rolling_restart":
                    try:
                        out = router.rolling_restart()
                    except RuntimeError as e:
                        return self._json(409, {"error": str(e)})
                    return self._json(200 if out.get("ok") else 500,
                                      out)
                if self.path == "/v1/admin/profiler":
                    try:
                        body = json.loads(raw or b"{}")
                    except json.JSONDecodeError:
                        return self._json(400, {"error": "bad json"})
                    try:
                        out = router.fleet_profiler(body)
                    except ValueError as e:
                        return self._json(400, {"error": str(e)})
                    except RuntimeError as e:
                        return self._json(409, {"error": str(e)})
                    return self._json(200 if out.get("ok") else 500,
                                      out)
                if self.path not in ("/v1/completions",
                                     "/v1/chat/completions"):
                    return self._proxy("POST", raw)
                try:
                    body = json.loads(raw or b"{}")
                except json.JSONDecodeError:
                    return self._json(400, {"error": "bad json"})
                # trace context: accept the client's traceparent or
                # mint a fresh trace id; the tail-sampling decision is
                # a pure function of the id, so every replica agrees
                client = parse_traceparent(
                    self.headers.get("traceparent"))
                tid, parent = client if client is not None \
                    else (new_trace_id(), None)
                trace = ((tid, parent, new_span_id())
                         if trace_sampled(tid, router.spans.sample)
                         else None)
                entry = JournalEntry(
                    rid=f"rtr-{uuid.uuid4().hex[:12]}",
                    path=self.path, body=raw,
                    stream=bool(body.get("stream")),
                    key=router._affinity_key(body),
                    tenant=router._tenant_of(self.headers),
                    trace=trace)
                router.journal.admit(entry)   # write-ahead
                t_req0 = time.time()
                status = None
                try:
                    if entry.stream:
                        self._stream(entry)
                    else:
                        status, data = router.route_buffered(entry)
                        headers = ()
                        if status in (429, 503):
                            headers = _retry_after_headers(data) or (
                                ("Retry-After",
                                 str(router.retry_after_hint())),)
                        if entry.trace is not None:
                            headers = tuple(headers) + (
                                ("X-Trace-Id", entry.trace[0]),)
                        self._json(status, data, headers=headers)
                finally:
                    router.journal.complete(entry.rid)
                    if entry.trace is not None:
                        router.spans.record(
                            "router.request", entry.trace[0],
                            span_id=entry.trace[2],
                            parent_id=entry.trace[1],
                            t_start=t_req0, t_end=time.time(),
                            request_id=entry.rid, path=self.path,
                            stream=entry.stream,
                            replays=entry.replays,
                            hedged=entry.hedged,
                            **({"status": status}
                               if status is not None else {}))

            def _stream(self, entry: JournalEntry):
                """Relay SSE from the replica. A replica lost BEFORE
                any byte reached the client re-routes invisibly; lost
                MID-STREAM, the client gets a structured error event
                plus [DONE] instead of a dropped socket (generation is
                not transparently resumable — the client resubmits
                after retry_after)."""
                exclude: Dict[int, int] = {}
                reroutes = 0
                pick_deadline = (time.monotonic()
                                 + router.cfg.no_replica_wait_sec)
                while True:
                    try:
                        r = router._pick_wait(entry.key, exclude,
                                              pick_deadline)
                    except NoReplica:
                        return self._json(503, {"error": {
                            "message": "no healthy replica",
                            "type": "unavailable", "code": 503,
                            "retry_after": router.retry_after_hint()}})
                    router.journal.assign(entry.rid, r.idx,
                                          r.generation)
                    r.inflight.add(entry.rid)
                    conn = http.client.HTTPConnection(
                        router.host, r.port,
                        timeout=router.cfg.connect_timeout_sec)
                    try:
                        try:
                            conn.request(
                                "POST", entry.path, body=entry.body,
                                headers=router._fwd_headers(entry, r))
                            conn.sock.settimeout(
                                router.cfg.forward_timeout_sec)
                            resp = conn.getresponse()
                        except (OSError,
                                http.client.HTTPException) as e:
                            # nothing streamed yet: invisible failover
                            router._breaker_failure(r)
                            exclude[r.idx] = r.generation
                            router._count("failovers")
                            router._c_failovers.inc()
                            router.flight.record(
                                "failover", rid=entry.rid,
                                replica=r.idx, error=str(e)[:200],
                                **({"trace_id": entry.trace[0]}
                                   if entry.trace is not None
                                   else {}))
                            if entry.trace is not None:
                                router.spans.annotate(
                                    entry.trace[0], "failover",
                                    parent_id=entry.trace[2],
                                    replica=r.idx,
                                    request_id=entry.rid)
                            if entry.replays < router.cfg.max_replays:
                                entry.replays += 1
                                router._count("replays")
                                router._c_replays.inc()
                                if entry.trace is not None:
                                    router.spans.annotate(
                                        entry.trace[0],
                                        "failover_replay",
                                        parent_id=entry.trace[2],
                                        attempt=entry.replays,
                                        request_id=entry.rid)
                                continue
                            return self._json(502, {"error": {
                                "message": "replica failed before the "
                                           "stream started",
                                "type": "replica_lost", "code": 502}})
                        if resp.status == 429:
                            # tenant rate limit: same budget on every
                            # replica — propagate, don't re-route
                            data = resp.read()
                            router._breaker_success(r)
                            router._count("shed_429")
                            return self._json(
                                429, data,
                                headers=_retry_after_headers(data))
                        if resp.status == 503 \
                                and reroutes <= len(router.replicas):
                            resp.read()
                            exclude[r.idx] = r.generation
                            reroutes += 1
                            router._count("rerouted_503")
                            continue
                        if resp.status != 200:
                            data = resp.read()
                            router._breaker_failure(r) \
                                if resp.status >= 500 \
                                else router._breaker_success(r)
                            return self._json(resp.status, data)
                        # 200: stream is live — relay line-wise
                        router._breaker_success(r)
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "text/event-stream")
                        self.send_header("Cache-Control", "no-cache")
                        self.end_headers()
                        self._relay(entry, r, resp)
                        return
                    finally:
                        r.inflight.discard(entry.rid)
                        conn.close()

            def _pump(self, entry: JournalEntry, resp):
                """Relay one replica's SSE to the client until EOF.
                Returns (saw_done, migrated_info_or_None,
                client_gone). The mid-decode migration marker (a
                ``data: {"migrated": ...}`` event the replica emits
                INSTEAD of [DONE]) is consumed here — it is
                router-internal routing state, never client bytes."""
                saw_done = False
                mig = None
                try:
                    while True:
                        line = resp.fp.readline()
                        if not line:
                            break
                        s = line.strip()
                        if s == b"data: [DONE]":
                            saw_done = True
                        elif s.startswith(b'data: {"migrated"'):
                            try:
                                doc = json.loads(s[len(b"data: "):])
                            except ValueError:
                                doc = {}
                            got = doc.get("migrated")
                            if isinstance(got, dict):
                                mig = got
                                continue
                        try:
                            self.wfile.write(line)
                            if line == b"\n":
                                self.wfile.flush()
                        except OSError:
                            # CLIENT left: closing the replica conn
                            # (caller's finally) trips the engine's
                            # SSE write failure -> abort + slot free
                            router.flight.record(
                                "stream_client_gone", rid=entry.rid)
                            return saw_done, None, True
                except (OSError, http.client.HTTPException):
                    pass                 # replica died mid-read
                return saw_done, mig, False

            def _relay(self, entry: JournalEntry, r: Replica, resp):
                """Relay the stream; when the replica hands the
                sequence off mid-decode, re-POST the journaled body to
                the migration target with X-Resume-Id and ride the
                continuation SSE on the SAME client socket — the
                client sees one uninterrupted stream whose bytes match
                an unmigrated run (the target's first delta carries
                the boundary separator; serving/api_server.py seeds
                the resumed decode state)."""
                hops = 0
                conn2 = None
                try:
                    while True:
                        saw_done, mig, gone = self._pump(entry, resp)
                        if saw_done or gone:
                            return
                        if mig is None:
                            break        # replica lost mid-stream
                        hops += 1
                        if hops > len(router.replicas) + 1:
                            break
                        resume_id = mig.get("resume_id")
                        target = str(mig.get("target") or "")
                        router._count("migration_continuations")
                        router.journal.record_migration(
                            entry.rid, resume_id, target)
                        router.flight.record(
                            "migration_continue", rid=entry.rid,
                            resume_id=resume_id, target=target,
                            stream=True)
                        if entry.trace is not None:
                            router.spans.annotate(
                                entry.trace[0], "migration_continue",
                                parent_id=entry.trace[2],
                                target=target, resume_id=resume_id,
                                request_id=entry.rid)
                        rep = router._replica_at(target)
                        if rep is None or not resume_id:
                            break
                        hdrs = router._fwd_headers(entry)
                        hdrs["X-Resume-Id"] = str(resume_id)
                        if conn2 is not None:
                            conn2.close()
                        conn2 = http.client.HTTPConnection(
                            router.host, rep.port,
                            timeout=router.cfg.connect_timeout_sec)
                        router.journal.assign(entry.rid, rep.idx,
                                              rep.generation)
                        try:
                            conn2.request("POST", entry.path,
                                          body=entry.body,
                                          headers=hdrs)
                            conn2.sock.settimeout(
                                router.cfg.forward_timeout_sec)
                            resp2 = conn2.getresponse()
                        except (OSError,
                                http.client.HTTPException):
                            break
                        if resp2.status != 200:
                            try:
                                resp2.read()
                            except (OSError,
                                    http.client.HTTPException):
                                pass
                            break
                        router._breaker_success(rep)
                        r = rep
                        resp = resp2
                    # REPLICA (or its migration continuation) lost
                    # mid-stream: structured error, not a dropped
                    # socket — generated bytes already with the client
                    # cannot be resumed transparently
                    router._count("failovers")
                    router._count("stream_errors")
                    router._c_failovers.inc()
                    router._breaker_failure(r)
                    retry = router.retry_after_hint()
                    router.flight.record("stream_replica_lost",
                                         rid=entry.rid,
                                         replica=r.idx)
                    event = {"error": {
                        "message": "replica failed mid-stream; "
                                   "resubmit the request",
                        "type": "replica_failover", "code": 503,
                        "retry_after": retry}}
                    try:
                        self.wfile.write(
                            b"data: " + json.dumps(event).encode()
                            + b"\n\ndata: [DONE]\n\n")
                        self.wfile.flush()
                    except OSError:
                        pass
                finally:
                    if conn2 is not None:
                        conn2.close()

        return Handler

    def serve(self, host: str = "127.0.0.1", port: int = 8080,
              background: bool = False) -> ThreadingHTTPServer:
        self._httpd = ThreadingHTTPServer((host, port),
                                          self.make_handler())
        if background:
            t = threading.Thread(target=self._httpd.serve_forever,
                                 daemon=True)
            t.start()
        else:
            self._httpd.serve_forever()
        return self._httpd


def main():
    """CLI: python -m bigdl_tpu.serving.router --model PATH
    --replicas N [--tiny-random] — spawns the replicas as
    ``api_server`` subprocesses and serves the routed OpenAI API."""
    import argparse
    import signal

    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default=None)
    ap.add_argument("--load-in-low-bit", default="sym_int4")
    ap.add_argument("--tiny-random", action="store_true")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--replicas", type=int, default=None,
                    help="default $BIGDL_TPU_ROUTER_REPLICAS (2)")
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--max-seq", type=int, default=2048)
    ap.add_argument("--health-sec", type=float, default=None,
                    help="default $BIGDL_TPU_ROUTER_HEALTH_SEC (1.0)")
    ap.add_argument("--hedge-ms", type=float, default=None,
                    help="default $BIGDL_TPU_ROUTER_HEDGE_MS (0 = off)")
    ap.add_argument("--crash-budget", type=int, default=None,
                    help="default $BIGDL_TPU_ROUTER_CRASH_BUDGET (3)")
    ap.add_argument("--roles", default=None,
                    help="comma-separated per-index fleet roles, e.g. "
                         "'prefill,decode' (rest default to mixed)")
    ap.add_argument("--journal", default=None,
                    help="durable JSONL request-journal path (default "
                         "$BIGDL_TPU_ROUTER_JOURNAL; unset = "
                         "in-memory only)")
    ap.add_argument("--autoscale", action="store_true",
                    help="run the load-signal autoscaler "
                         "(serving/autoscaler.py; bounds from "
                         "$BIGDL_TPU_AUTOSCALE_MIN/MAX, dwell from "
                         "$BIGDL_TPU_AUTOSCALE_DWELL_SEC)")
    args = ap.parse_args()

    if not args.model and not args.tiny_random:
        ap.error("--model is required (or pass --tiny-random)")
    roles = ([s.strip() for s in args.roles.split(",") if s.strip()]
             if args.roles else None)
    cmd = [sys.executable, "-m", "bigdl_tpu.serving.api_server",
           "--host", args.host, "--port", "{port}",
           "--max-batch", str(args.max_batch),
           "--max-seq", str(args.max_seq)]
    if args.tiny_random:
        cmd += ["--tiny-random"]
    else:
        cmd += ["--model", args.model,
                "--load-in-low-bit", args.load_in_low_bit]

    router = Router(
        replica_cmd=cmd,
        config=RouterConfig(replicas=args.replicas,
                            health_sec=args.health_sec,
                            hedge_ms=args.hedge_ms,
                            crash_budget=args.crash_budget,
                            roles=roles,
                            journal_path=args.journal),
        host=args.host)
    print(f"router: spawning {router.cfg.replicas} replicas on ports "
          f"{[r.port for r in router.replicas]}", file=sys.stderr)
    router.start()

    scaler = None
    if args.autoscale:
        from bigdl_tpu.serving.autoscaler import Autoscaler

        scaler = Autoscaler(router)
        scaler.start()
        print(f"autoscaler: bounds [{scaler.cfg.min_replicas}, "
              f"{scaler.cfg.max_replicas}], dwell "
              f"{scaler.cfg.dwell_sec}s", file=sys.stderr)

    def _term(signum, frame):
        if scaler is not None:
            scaler.stop()
        threading.Thread(target=router.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    print(f"routing on http://{args.host}:{args.port}/v1",
          file=sys.stderr)
    router.serve(args.host, args.port)


if __name__ == "__main__":
    main()
