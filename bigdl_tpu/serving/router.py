"""Multi-replica serving tier: a thin HTTP front over N supervised
engine replicas.

PR 5 made a single engine survive bad requests (quarantine, deadlines,
drain); this module moves one failure domain up and makes the SERVICE
survive a bad engine *process*. The reference's L6 serving layer (vLLM
behind FastChat workers) leaves replication and failover to an external
orchestrator; for a TPU-native stack serving heavy traffic we build
that tier in-tree, on the drain/health/flight-recorder substrate the
engine already provides:

- **Replica supervisor** — spawns N ``api_server`` subprocesses, probes
  ``/health`` every ``$BIGDL_TPU_ROUTER_HEALTH_SEC``, restarts crashed
  replicas with exponential backoff, SIGKILLs hung ones (a live process
  whose ``/health`` stops answering — or answers "wedged" off the
  engine's step-loop heartbeat), and quarantines a replica that flaps
  past ``$BIGDL_TPU_ROUTER_CRASH_BUDGET`` deaths inside the crash
  window (the replica-granularity mirror of PR 5's per-request blame).
- **Write-ahead request journal** — every admitted request is recorded
  (raw body: prompt + sampling params, plus the assigned replica)
  BEFORE the first byte is forwarded. When a replica dies mid-flight
  its non-streaming requests are transparently REPLAYED on a healthy
  replica (byte-identical for greedy sampling, since replicas share
  weights); streaming requests get a structured SSE error event with a
  ``retry_after`` hint instead of a dropped socket.
- **Per-replica circuit breakers** — consecutive transport failures
  trip the breaker (routing skips the replica), a cooldown later it
  half-opens (one trial request), success closes it. Plus one optional
  HEDGED retry (``$BIGDL_TPU_ROUTER_HEDGE_MS``): a non-streaming
  request with no response past the hedge latency fires one duplicate
  on a second replica and the first answer wins — the loser's
  connection close triggers the engine's client-disconnect abort, so
  the wasted work frees its slot immediately.
- **Rolling restart** — ``POST /v1/admin/rolling_restart`` drains
  replicas one at a time through PR 5's SIGTERM drain (in-flight work
  finishes, new work is re-routed; a request that races the drain gets
  the replica's 503 and is transparently re-routed), then respawns and
  waits healthy before moving on: a config/weight rollout drops zero
  requests and serves zero 5xx.
- **Prefix-affinity routing** — consistent hash over the prompt prefix
  so shared-system-prompt traffic lands where its prefix-cache entry
  already lives, falling back to least-loaded (live ``/v1/stats``
  occupancy) when the affinity target is down, tripped, or full.

Observability: ``bigdl_tpu_router_*`` metric families (per-replica
state gauge, failover/replay/hedge/breaker-trip/restart counters,
routed-request latency histogram), router events in a flight recorder,
and ``GET /v1/router/stats`` — the JSON snapshot bench embeds. Every
admitted completion gets a W3C-style ``traceparent`` (generated here or
accepted from the client; observability/disttrace.py) forwarded on each
replica hop; ``GET /v1/trace/{trace_id}`` returns the stitched
clock-skew-adjusted fleet timeline and ``GET /v1/traces`` lists recent
slow traces.

Run: ``python -m bigdl_tpu.serving.router --model PATH --replicas 2``
(or ``--tiny-random`` for the checkpoint-free chaos/bench mode).
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import http.client
import json
import os
import queue
import socket
import subprocess
import sys
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from bigdl_tpu.observability.disttrace import (SpanRecorder,
                                               make_traceparent,
                                               merge_timeline,
                                               new_span_id, new_trace_id,
                                               parse_traceparent,
                                               trace_sampled)
from bigdl_tpu.observability.flight import FlightRecorder
from bigdl_tpu.observability.metrics import MetricsRegistry

ROUTER_HEALTH_ENV = "BIGDL_TPU_ROUTER_HEALTH_SEC"
ROUTER_REPLICAS_ENV = "BIGDL_TPU_ROUTER_REPLICAS"
ROUTER_HEDGE_ENV = "BIGDL_TPU_ROUTER_HEDGE_MS"
ROUTER_CRASH_BUDGET_ENV = "BIGDL_TPU_ROUTER_CRASH_BUDGET"

# replica lifecycle states -> bigdl_tpu_router_replica_state gauge codes
STARTING = "starting"
HEALTHY = "healthy"
UNHEALTHY = "unhealthy"
DRAINING = "draining"
BACKOFF = "backoff"
QUARANTINED = "quarantined"
RETIRED = "retired"                    # scaled down; never respawned
STATE_CODES = {STARTING: 0, HEALTHY: 1, UNHEALTHY: 2, DRAINING: 3,
               BACKOFF: 4, QUARANTINED: 5, RETIRED: 6}

#: fleet roles (api_server.REPLICA_ROLES): decode replicas are reserved
#: for KV-handoff decode work and only take client traffic when nothing
#: else is routable
ROLES = ("mixed", "prefill", "decode")


def resolve_router_health_sec(value: Optional[str] = None) -> float:
    """Health-probe interval in seconds (default 1.0). Raises
    ``ValueError`` on a non-positive or non-numeric value — env_check
    surfaces it; the router falls back to the default."""
    raw = value if value is not None else os.environ.get(
        ROUTER_HEALTH_ENV, "")
    if not raw:
        return 1.0
    sec = float(raw)                   # ValueError propagates
    if sec <= 0:
        raise ValueError(
            f"{ROUTER_HEALTH_ENV} must be positive, got {raw!r}")
    return sec


def resolve_router_replicas(value: Optional[str] = None) -> int:
    """Replica count (default 2, must be >= 1)."""
    raw = value if value is not None else os.environ.get(
        ROUTER_REPLICAS_ENV, "")
    if not raw:
        return 2
    n = int(raw)                       # ValueError propagates
    if n < 1:
        raise ValueError(
            f"{ROUTER_REPLICAS_ENV} must be >= 1, got {raw!r}")
    return n


def resolve_router_hedge_ms(value: Optional[str] = None) -> float:
    """Hedged-retry latency threshold in ms (default 0 = hedging off)."""
    raw = value if value is not None else os.environ.get(
        ROUTER_HEDGE_ENV, "")
    if not raw:
        return 0.0
    ms = float(raw)                    # ValueError propagates
    if ms < 0:
        raise ValueError(
            f"{ROUTER_HEDGE_ENV} must be >= 0 (0 disables), got {raw!r}")
    return ms


def resolve_router_canary_sec(value: Optional[str] = None) -> float:
    """Canary-probe sweep interval (default 0 = canaries off);
    delegates to serving/canary.resolve_canary_sec."""
    from bigdl_tpu.serving.canary import resolve_canary_sec
    return resolve_canary_sec(value)


def resolve_router_crash_budget(value: Optional[str] = None) -> int:
    """Deaths inside the crash window before a replica is quarantined
    (default 3, must be >= 1)."""
    raw = value if value is not None else os.environ.get(
        ROUTER_CRASH_BUDGET_ENV, "")
    if not raw:
        return 3
    n = int(raw)                       # ValueError propagates
    if n < 1:
        raise ValueError(
            f"{ROUTER_CRASH_BUDGET_ENV} must be >= 1, got {raw!r}")
    return n


@dataclasses.dataclass
class RouterConfig:
    """Knobs for the serving tier. ``None`` fields defer to their env
    variables (resolver fallbacks apply on bad values via env_check)."""
    replicas: Optional[int] = None          # $BIGDL_TPU_ROUTER_REPLICAS
    health_sec: Optional[float] = None      # $BIGDL_TPU_ROUTER_HEALTH_SEC
    hedge_ms: Optional[float] = None        # $BIGDL_TPU_ROUTER_HEDGE_MS
    crash_budget: Optional[int] = None      # $BIGDL_TPU_ROUTER_CRASH_BUDGET
    canary_sec: Optional[float] = None      # $BIGDL_TPU_CANARY_SEC
    health_timeout_sec: float = 2.0    # per-probe HTTP timeout
    unhealthy_after: int = 3           # probe failures before hang-kill
    crash_window_sec: float = 60.0     # deaths inside count to the budget
    backoff_base_sec: float = 0.25     # restart backoff: base * 2^deaths
    backoff_max_sec: float = 30.0
    breaker_threshold: int = 3         # consecutive failures to trip
    breaker_cooldown_sec: float = 2.0  # open -> half-open delay
    affinity_tokens: int = 32          # prompt prefix hashed for affinity
    max_replays: int = 2               # failover replays per request
    connect_timeout_sec: float = 5.0
    forward_timeout_sec: float = 600.0  # backstop; deaths close the socket
    spawn_timeout_sec: float = 180.0   # replica boot -> healthy
    drain_exit_timeout_sec: float = 60.0  # SIGTERM -> exit before SIGKILL
    # how long a request WAITS for a routable replica before 503ing:
    # losing the last healthy replica usually means its replacement is
    # seconds away (backoff + respawn), and giving up instantly would
    # drop exactly the requests the replay journal exists to save
    no_replica_wait_sec: float = 30.0
    # per-index fleet roles ("prefill" / "decode" / "mixed"); shorter
    # than the replica count -> the rest default to "mixed". A prefill
    # replica gets X-Handoff-Targets on its non-streaming forwards and
    # ships KV to a decode replica (api_server /v1/internal/kv_handoff)
    roles: Optional[List[str]] = None
    # decode targets named per handoff (ordered least-loaded)
    handoff_fanout: int = 3

    def resolve(self) -> "RouterConfig":
        out = dataclasses.replace(self)
        if out.replicas is None:
            try:
                out.replicas = resolve_router_replicas()
            except ValueError:
                out.replicas = 2          # env_check reports it
        if out.health_sec is None:
            try:
                out.health_sec = resolve_router_health_sec()
            except ValueError:
                out.health_sec = 1.0
        if out.hedge_ms is None:
            try:
                out.hedge_ms = resolve_router_hedge_ms()
            except ValueError:
                out.hedge_ms = 0.0
        if out.crash_budget is None:
            try:
                out.crash_budget = resolve_router_crash_budget()
            except ValueError:
                out.crash_budget = 3
        if out.canary_sec is None:
            try:
                out.canary_sec = resolve_router_canary_sec()
            except ValueError:
                out.canary_sec = 0.0      # env_check reports it
        return out


class ReplicaLost(RuntimeError):
    """The replica's connection failed mid-request (death, hang-kill,
    connection refused). The failover/replay path catches this."""


class NoReplica(RuntimeError):
    """No routable replica (all down, draining, or breaker-open)."""


@dataclasses.dataclass
class JournalEntry:
    """One admitted request in the write-ahead journal: everything
    needed to replay it on another replica (the raw JSON body IS the
    prompt + SamplingParams), plus failover bookkeeping."""
    rid: str
    path: str
    body: bytes
    stream: bool
    key: int                           # affinity hash
    replica: Optional[int] = None      # currently assigned replica idx
    generation: int = 0                # that replica's spawn generation
    replays: int = 0
    hedged: bool = False
    admitted_at: float = dataclasses.field(default_factory=time.monotonic)
    tenant: Optional[str] = None       # X-Tenant-Id to forward
    # distributed-trace context (observability/disttrace.py):
    # (trace_id, client_parent_span_id or None, router_span_id) — None
    # when the trace was tail-sampled out, so no header is forwarded
    trace: Optional[Tuple[str, Optional[str], str]] = None


class RequestJournal:
    """In-memory write-ahead journal of in-flight requests. `admit`
    happens BEFORE the first forward; `complete` removes the entry once
    the client has its answer (or its structured error)."""

    def __init__(self):
        self._entries: Dict[str, JournalEntry] = {}
        self._lock = threading.Lock()

    def admit(self, entry: JournalEntry) -> None:
        with self._lock:
            self._entries[entry.rid] = entry

    def assign(self, rid: str, replica: int, generation: int) -> None:
        with self._lock:
            e = self._entries.get(rid)
            if e is not None:
                e.replica = replica
                e.generation = generation

    def complete(self, rid: str) -> None:
        with self._lock:
            self._entries.pop(rid, None)

    def depth(self) -> int:
        with self._lock:
            return len(self._entries)

    def inflight_on(self, replica: int) -> List[JournalEntry]:
        with self._lock:
            return [e for e in self._entries.values()
                    if e.replica == replica]


class Replica:
    """Supervisor-side view of one engine replica process."""

    def __init__(self, idx: int, port: int, role: str = "mixed"):
        self.idx = idx
        self.port = port
        self.role = role                 # mixed | prefill | decode
        self.proc: Any = None            # Popen-like handle
        self.state = STARTING
        self.generation = 0              # bumped per (re)spawn
        self.started_at = 0.0
        self.probe_failures = 0
        self.restarts = 0                # lifetime respawns
        self.deaths: collections.deque = collections.deque(maxlen=32)
        self.backoff_until = 0.0
        self.last_exit: Optional[str] = None
        self.planned_restart = False     # rolling restart owns the proc
        self.inflight: set = set()       # router-assigned request ids
        self.occupancy = 0.0             # active/total slots (probed)
        self.queue_depth = 0
        self.brownout = 0                # engine brownout level (probed)
        self.tenants: dict = {}          # per-tenant counters (probed)
        # autoscaler load signals (probed from /v1/stats)
        self.tpot_ewma_ms = 0.0          # decode-step latency EWMA
        self.headroom_frac: Optional[float] = None  # HBM ledger headroom
        # last probed handoff counter block + the spawn generation it
        # belongs to (a respawn resets the replica's counters to zero)
        self.handoff: dict = {}
        self.handoff_gen = -1
        # compact live-perf block (roofline util, sentinel state)
        # probed from /v1/stats; feeds the router perf aggregate
        self.perf: Optional[dict] = None
        # compact live-quality block (token NLL, probe NLL,
        # QualitySentinel state) probed from /v1/stats; feeds the
        # router's fleet quality aggregate
        self.quality: Optional[dict] = None
        # compact SLO block (active alerts, worst burn rate) probed
        # from /v1/stats; feeds the router's fleet SLO aggregate
        self.slo: Optional[dict] = None
        # circuit breaker
        self.breaker = "closed"          # closed | open | half_open
        self.breaker_failures = 0
        self.breaker_open_until = 0.0

    @property
    def pid(self) -> Optional[int]:
        return getattr(self.proc, "pid", None)

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def snapshot(self) -> dict:
        return {
            "idx": self.idx, "port": self.port, "pid": self.pid,
            "state": self.state, "role": self.role,
            "generation": self.generation,
            "restarts": self.restarts, "last_exit": self.last_exit,
            "probe_failures": self.probe_failures,
            "breaker": self.breaker,
            "breaker_failures": self.breaker_failures,
            "inflight": len(self.inflight),
            "occupancy": self.occupancy,
            "queue_depth": self.queue_depth,
            "brownout": self.brownout,
            "tpot_ewma_ms": self.tpot_ewma_ms,
            "headroom_frac": self.headroom_frac,
            "handoff": dict(self.handoff),
            "perf": dict(self.perf) if self.perf else None,
            "slo": dict(self.slo) if self.slo else None,
            "quality": dict(self.quality) if self.quality else None,
        }


def _retry_after_headers(data: bytes) -> tuple:
    """Rebuild the Retry-After header from a buffered shed/drain
    response body (the replica's header was consumed with the
    buffered read; its JSON error block carries the same value)."""
    try:
        ra = json.loads(data).get("error", {}).get("retry_after")
        if ra:
            return (("Retry-After", str(int(ra))),)
    except (ValueError, AttributeError, TypeError):
        pass
    return ()


def _free_port(host: str = "127.0.0.1") -> int:
    s = socket.socket()
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port


class Router:
    """Supervises N replicas and routes OpenAI-API traffic to them.

    ``replica_cmd`` is the subprocess argv with a ``{port}`` placeholder
    (default: ``api_server`` with the flags the CLI assembled); tests
    inject ``spawn(idx, port) -> Popen-like`` to control the processes
    entirely."""

    def __init__(self, replica_cmd: Optional[List[str]] = None,
                 spawn: Optional[Callable[[int, int], Any]] = None,
                 config: Optional[RouterConfig] = None,
                 ports: Optional[List[int]] = None,
                 host: str = "127.0.0.1",
                 registry: Optional[MetricsRegistry] = None,
                 flight: Optional[FlightRecorder] = None,
                 spawn_env: Optional[Dict[str, str]] = None):
        if replica_cmd is None and spawn is None:
            raise ValueError("pass replica_cmd (argv with a {port} "
                             "placeholder) or a spawn(idx, port) factory")
        self.cfg = (config or RouterConfig()).resolve()
        self.host = host
        self._replica_cmd = replica_cmd
        self._spawn_fn = spawn
        self._spawn_env = spawn_env
        ports = list(ports) if ports else [
            _free_port(host) for _ in range(self.cfg.replicas)]
        if len(ports) != self.cfg.replicas:
            raise ValueError(f"got {len(ports)} ports for "
                             f"{self.cfg.replicas} replicas")
        roles = list(self.cfg.roles or [])
        for ro in roles:
            if ro not in ROLES:
                raise ValueError(f"unknown replica role {ro!r} "
                                 f"(choices: {', '.join(ROLES)})")
        self.replicas = [
            Replica(i, p, role=(roles[i] if i < len(roles) else "mixed"))
            for i, p in enumerate(ports)]
        self.journal = RequestJournal()
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.flight = flight if flight is not None else FlightRecorder()
        # one traceparent per admitted request (generated here, or
        # accepted from the client) stitches router + replica spans
        # into the GET /v1/trace/{id} timeline
        self.spans = SpanRecorder(service="router")
        self._lock = threading.Lock()
        self._stop = False
        self._wake = threading.Event()
        self._supervisor: Optional[threading.Thread] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._admin_lock = threading.Lock()
        self._rolling = False
        # attached by Autoscaler(router); stats_snapshot embeds its
        # decision log when present
        self.autoscaler: Any = None

        # plain counters mirror the metric families so bench JSON and
        # stats_snapshot() embed them without a registry scrape.
        # Incremented from the supervisor thread AND HTTP handler
        # threads: Counter's += is a read-modify-write, so every
        # touch goes through _count()/counts_snapshot() under _lock
        self.counts = collections.Counter()
        self._g_state = self.registry.gauge(
            "bigdl_tpu_router_replica_state",
            "replica lifecycle state (0 starting, 1 healthy, 2 "
            "unhealthy, 3 draining, 4 backoff, 5 quarantined)",
            ["replica"])
        self._c_failovers = self.registry.counter(
            "bigdl_tpu_router_failovers_total",
            "in-flight requests whose replica died under them")
        self._c_replays = self.registry.counter(
            "bigdl_tpu_router_replays_total",
            "non-streaming requests replayed on another replica")
        self._c_hedges = self.registry.counter(
            "bigdl_tpu_router_hedges_total",
            "hedged duplicate requests fired past the latency threshold")
        self._c_trips = self.registry.counter(
            "bigdl_tpu_router_breaker_trips_total",
            "circuit-breaker open transitions", ["replica"])
        self._c_restarts = self.registry.counter(
            "bigdl_tpu_router_restarts_total",
            "replica respawns (crash recovery + rolling restarts)",
            ["replica"])
        self._c_requests = self.registry.counter(
            "bigdl_tpu_router_requests_total",
            "routed requests by replica and response code",
            ["replica", "code"])
        self._h_latency = self.registry.histogram(
            "bigdl_tpu_router_request_seconds",
            "end-to-end routed request latency")
        self._c_canary_probes = self.registry.counter(
            "bigdl_tpu_router_canary_probes_total",
            "golden-canary correctness probes sent to replicas")
        self._c_canary_fail = self.registry.counter(
            "bigdl_tpu_router_canary_failures_total",
            "canary byte mismatches (each quarantines its replica)",
            ["replica"])

        # golden-canary prober (serving/canary.py): periodic greedy
        # probes through each healthy replica; byte mismatch vs the
        # recorded golden quarantines the replica via canary_mismatch.
        # Off unless canary_sec > 0 ($BIGDL_TPU_CANARY_SEC).
        from bigdl_tpu.serving.canary import CanaryProber
        self.canary = CanaryProber(self, self.cfg.canary_sec or 0.0)

    # -- lifecycle ----------------------------------------------------------

    def start(self, wait_healthy: bool = True) -> None:
        for r in self.replicas:
            self._respawn(r, initial=True)
        self._supervisor = threading.Thread(target=self._supervise,
                                            daemon=True)
        self._supervisor.start()
        self.canary.start()
        if wait_healthy:
            deadline = time.monotonic() + self.cfg.spawn_timeout_sec
            while time.monotonic() < deadline:
                if any(r.state == HEALTHY for r in self.replicas):
                    return
                time.sleep(0.05)
            raise RuntimeError(
                "no replica became healthy within "
                f"{self.cfg.spawn_timeout_sec:.0f}s; last exits: "
                f"{[r.last_exit for r in self.replicas]}")

    def shutdown(self) -> None:
        self._stop = True
        self.canary.stop()
        self._wake.set()
        if self._httpd is not None:
            self._httpd.shutdown()
        if self._supervisor is not None:
            self._supervisor.join(timeout=5)
        for r in self.replicas:
            if r.proc is None:
                continue
            try:
                r.proc.terminate()
            except Exception:
                pass
        deadline = time.monotonic() + 5.0
        for r in self.replicas:
            if r.proc is None:
                continue
            while r.proc.poll() is None and time.monotonic() < deadline:
                time.sleep(0.05)
            if r.proc.poll() is None:
                try:
                    r.proc.kill()
                except Exception:
                    pass

    def _spawn(self, idx: int, port: int, role: str = "mixed"):
        if self._spawn_fn is not None:
            return self._spawn_fn(idx, port)
        cmd = [a.replace("{port}", str(port)) for a in self._replica_cmd]
        env = dict(os.environ)
        if self._spawn_env:
            env.update(self._spawn_env)
        # the replica process learns its fleet role from the env the
        # api_server CLI resolves ($BIGDL_TPU_REPLICA_ROLE) — role
        # flips go through a drain-respawn, never a live mutation
        env["BIGDL_TPU_REPLICA_ROLE"] = role
        return subprocess.Popen(cmd, env=env,
                                stdout=subprocess.DEVNULL)

    def _respawn(self, r: Replica, initial: bool = False) -> None:
        r.generation += 1
        r.proc = self._spawn(r.idx, r.port, r.role)
        r.started_at = time.monotonic()
        r.probe_failures = 0
        r.breaker = "closed"
        r.breaker_failures = 0
        self._set_state(r, STARTING)
        if not initial:
            r.restarts += 1
            self._count("restarts")
            # replica idx is bounded by fleet size — audited
            self._c_restarts.labels(str(r.idx)).inc()  # graftlint: disable=metric-label-cardinality
        self.flight.record("replica_spawn", replica=r.idx, port=r.port,
                           pid=r.pid, generation=r.generation)

    def _set_state(self, r: Replica, state: str) -> None:
        if r.state != state:
            self.flight.record("replica_state", replica=r.idx,
                               prev=r.state, state=state)
        r.state = state
        # replica idx is bounded by fleet size — audited
        self._g_state.labels(str(r.idx)).set(  # graftlint: disable=metric-label-cardinality
            STATE_CODES[state])

    # -- supervision --------------------------------------------------------

    def _supervise(self) -> None:
        while not self._stop:
            try:
                self._tick()
            except Exception:
                import traceback

                traceback.print_exc()   # the supervisor must survive
            self._wake.wait(timeout=self.cfg.health_sec)
            self._wake.clear()

    def _tick(self) -> None:
        now = time.monotonic()
        for r in list(self.replicas):    # add_replica appends live
            if r.state in (QUARANTINED, RETIRED) or r.planned_restart:
                continue
            if r.state == BACKOFF:
                if now >= r.backoff_until:
                    self._respawn(r)
                continue
            if r.proc is not None and r.proc.poll() is not None:
                self._handle_death(
                    r, f"exit code {r.proc.returncode}")
                continue
            self._probe(r, now)

    def _http_get(self, port: int, path: str,
                  timeout: float) -> Tuple[int, bytes]:
        conn = http.client.HTTPConnection(self.host, port,
                                          timeout=timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    def _http_post(self, port: int, path: str, doc: dict,
                   timeout: float) -> Tuple[int, bytes]:
        body = json.dumps(doc).encode()
        conn = http.client.HTTPConnection(self.host, port,
                                          timeout=timeout)
        try:
            conn.request("POST", path, body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    def _probe(self, r: Replica, now: float) -> None:
        try:
            status, body = self._http_get(r.port, "/health",
                                          self.cfg.health_timeout_sec)
        except OSError:
            status, body = -1, b""
        if status == 200:
            r.probe_failures = 0
            if r.state != HEALTHY:
                self._set_state(r, HEALTHY)
            self._poll_stats(r)
            return
        detail = ""
        if status == 503:
            try:
                detail = json.loads(body).get("status", "")
            except (ValueError, AttributeError):
                detail = ""
        if detail == "draining":
            # expected while the replica finishes in-flight work
            # (rolling restart, operator SIGTERM); not a failure
            self._set_state(r, DRAINING)
            return
        # refused / timed out / wedged: the process may be alive but
        # the service is not there
        r.probe_failures += 1
        if r.state == STARTING:
            if now - r.started_at > self.cfg.spawn_timeout_sec:
                self._kill_hung(r, "never became healthy")
            return
        if r.state == HEALTHY:
            self._set_state(r, UNHEALTHY)
        if r.probe_failures >= self.cfg.unhealthy_after:
            self._kill_hung(
                r, f"hung ({r.probe_failures} probe failures"
                   f"{', ' + detail if detail else ''})")

    def canary_probe(self) -> None:
        """One canary probe was sent (counter hook for CanaryProber)."""
        self._count("canary_probes")
        self._c_canary_probes.inc()

    def canary_mismatch(self, r: Replica, kind: str, prompt_idx: int,
                        expected: str, got: str) -> None:
        """A golden-canary byte mismatch on replica ``r`` — a
        CORRECTNESS failure: the replica answers fast and healthy but
        wrong, so it is quarantined through the same supervisor path a
        crash loop takes (no restarts — wrong weights respawn wrong)
        and its process is terminated so in-flight requests fail over
        to byte-correct neighbors instead of finishing wrong."""
        self._count("canary_failures")
        # replica idx is bounded by fleet size — audited
        self._c_canary_fail.labels(str(r.idx)).inc()  # graftlint: disable=metric-label-cardinality
        self.flight.record(
            "canary_mismatch", replica=r.idx, kind=kind,
            prompt_idx=prompt_idx, expected=expected[:200],
            got=got[:200])
        if r.state == QUARANTINED:
            return                     # already isolated this sweep
        self._count("quarantined")
        self._set_state(r, QUARANTINED)
        self.flight.record("replica_quarantined", replica=r.idx,
                           reason="canary_mismatch", kind=kind)
        try:
            if r.proc is not None:
                r.proc.terminate()
        except Exception:
            pass

    def _kill_hung(self, r: Replica, reason: str) -> None:
        """A live-but-unresponsive replica (replica_hang, wedged step
        loop) is killed so its sockets break and in-flight requests can
        fail over — then handled exactly like a crash."""
        self.flight.record("replica_hung", replica=r.idx, reason=reason)
        try:
            if r.proc is not None:
                r.proc.kill()
                r.proc.wait(timeout=5)
        except Exception:
            pass
        self._handle_death(r, reason)

    def _handle_death(self, r: Replica, reason: str) -> None:
        now = time.monotonic()
        r.last_exit = reason
        r.deaths.append(now)
        orphaned = self.journal.inflight_on(r.idx)
        self.flight.record("replica_death", replica=r.idx, reason=reason,
                           inflight=len(orphaned))
        recent = [t for t in r.deaths
                  if now - t <= self.cfg.crash_window_sec]
        if len(recent) >= self.cfg.crash_budget:
            # crash loop: stop feeding it restarts — the replica-level
            # mirror of the engine's per-request crash-budget quarantine
            self._count("quarantined")
            self._set_state(r, QUARANTINED)
            self.flight.record("replica_quarantined", replica=r.idx,
                               deaths_in_window=len(recent),
                               window_sec=self.cfg.crash_window_sec)
            return
        backoff = min(self.cfg.backoff_max_sec,
                      self.cfg.backoff_base_sec * (2 ** (len(recent) - 1)))
        r.backoff_until = now + backoff
        self._set_state(r, BACKOFF)
        self.flight.record("replica_backoff", replica=r.idx,
                           backoff_sec=round(backoff, 3))

    def _poll_stats(self, r: Replica) -> None:
        """Occupancy for least-loaded fallback routing, plus the
        autoscaler's load signals (brownout, queue depth, tpot EWMA,
        ledger headroom) and the replica's handoff counters (turned
        into fleet-level deltas); best-effort."""
        try:
            status, body = self._http_get(r.port, "/v1/stats",
                                          self.cfg.health_timeout_sec)
            if status != 200:
                return
            doc = json.loads(body)
            slots = doc.get("slots", {})
            total = max(int(slots.get("total", 1)), 1)
            r.occupancy = float(slots.get("active", 0)) / total
            r.queue_depth = int(doc.get("queue_depth", 0))
            ov = doc.get("overload") or {}
            r.brownout = int(ov.get("brownout_level", 0))
            r.tenants = ov.get("tenants") or {}
            r.tpot_ewma_ms = float(ov.get("tpot_ewma_ms", 0.0))
            hr = (doc.get("memory") or {}).get("headroom") or {}
            hb, lim = hr.get("headroom_bytes"), hr.get("bytes_limit")
            r.headroom_frac = (float(hb) / float(lim)
                               if isinstance(hb, (int, float))
                               and isinstance(lim, (int, float))
                               and lim else None)
            ho = doc.get("handoff") or {}
            ho = {k: int(v) for k, v in ho.items()
                  if isinstance(v, (int, float))}
            # per-generation deltas: a respawned replica restarts its
            # counters at zero, so only compare within one generation
            prev = r.handoff if r.handoff_gen == r.generation else {}
            for key in ("retries", "fallbacks"):
                d = ho.get(key, 0) - prev.get(key, 0)
                if d > 0:
                    self._count(f"handoff_{key}", d)
            r.handoff = ho
            r.handoff_gen = r.generation
            perf = doc.get("perf")
            r.perf = perf if isinstance(perf, dict) else None
            quality = doc.get("quality")
            r.quality = quality if isinstance(quality, dict) else None
            slo = doc.get("slo")
            if isinstance(slo, dict):
                # compact fleet view; the full per-replica document
                # stays one proxy hop away at GET /v1/slo
                r.slo = {
                    "alerts_active": int(slo.get("alerts_active") or 0),
                    "alerts_total": int(slo.get("alerts_total") or 0),
                    "burn_rate_max": float(
                        slo.get("burn_rate_max") or 0.0),
                }
        except (OSError, ValueError):
            pass

    # -- circuit breaker ----------------------------------------------------

    def _breaker_failure(self, r: Replica) -> None:
        r.breaker_failures += 1
        if r.breaker == "half_open" or (
                r.breaker == "closed"
                and r.breaker_failures >= self.cfg.breaker_threshold):
            r.breaker = "open"
            r.breaker_open_until = (time.monotonic()
                                    + self.cfg.breaker_cooldown_sec)
            self._count("breaker_trips")
            # replica idx is bounded by fleet size — audited
            self._c_trips.labels(str(r.idx)).inc()  # graftlint: disable=metric-label-cardinality
            self.flight.record("breaker_open", replica=r.idx,
                               failures=r.breaker_failures)

    def _breaker_success(self, r: Replica) -> None:
        r.breaker_failures = 0
        if r.breaker != "closed":
            self.flight.record("breaker_close", replica=r.idx,
                               was=r.breaker)
            r.breaker = "closed"

    def _routable(self, r: Replica) -> bool:
        if r.state != HEALTHY or r.planned_restart:
            return False
        if r.breaker == "open":
            if time.monotonic() < r.breaker_open_until:
                return False
            # cooldown elapsed: half-open, admit a trial request
            r.breaker = "half_open"
            self.flight.record("breaker_half_open", replica=r.idx)
        return True

    # -- routing ------------------------------------------------------------

    def _affinity_key(self, body: dict) -> int:
        prompt = body.get("prompt")
        if prompt is None:
            msgs = body.get("messages") or []
            prompt = "\x00".join(
                f"{m.get('role', '')}:{m.get('content', '')}"
                for m in msgs)
        if isinstance(prompt, list):
            prefix = prompt[:self.cfg.affinity_tokens]
        else:
            prefix = str(prompt)[:self.cfg.affinity_tokens * 4]
        digest = hashlib.sha1(repr(prefix).encode()).digest()
        return int.from_bytes(digest[:8], "big")

    def _pick(self, key: int, exclude=()) -> Replica:
        """Prefix-affinity first: the consistent-hash target takes the
        request when it is routable and has a free slot (its prefix
        cache already holds this prompt family's entry); otherwise the
        least-loaded routable replica. Decode-role replicas are
        reserved for handoff decode work — they take client traffic
        only when NO other replica is routable (degraded fleet beats a
        503)."""
        n = len(self.replicas)
        candidates = [r for r in self.replicas
                      if r.idx not in exclude and self._routable(r)]
        if not candidates:
            raise NoReplica()
        front = [r for r in candidates if r.role != "decode"]
        if front:
            candidates = front
        affinity = self.replicas[key % n]
        # a browned-out replica is degrading service to protect itself:
        # prefix affinity is not worth routing INTO the pressure, and
        # the least-loaded fallback prefers the lowest brownout level
        if affinity in candidates and affinity.occupancy < 1.0 \
                and affinity.brownout == 0:
            return affinity
        return min(candidates,
                   key=lambda r: (r.brownout, r.occupancy,
                                  r.queue_depth, len(r.inflight), r.idx))

    def _pick_wait(self, key: int, exclude: Dict[int, int],
                   deadline: float) -> Replica:
        """``_pick`` that RIDES OUT a replica gap: with every replica
        momentarily unroutable (the last healthy one just died and its
        replacement is mid-spawn), keep polling until ``deadline``
        instead of failing the request. ``exclude`` maps replica idx ->
        the GENERATION that failed us: a respawned process at the same
        index is a new generation and gets forgiven, while the dead
        instance stays excluded even during the window where the
        supervisor still believes it healthy (state is probe-delayed;
        generation only moves on respawn)."""
        while True:
            try:
                return self._pick(key, exclude)
            except NoReplica:
                stale = [i for i, gen in exclude.items()
                         if self.replicas[i].generation != gen]
                for i in stale:
                    del exclude[i]
                if stale:
                    continue
                if time.monotonic() >= deadline:
                    raise
                time.sleep(min(0.05, self.cfg.health_sec))

    def retry_after_hint(self) -> int:
        """Seconds until a fresh replica is plausibly routable."""
        return max(1, int(round(2 * self.cfg.health_sec)))

    @staticmethod
    def _tenant_of(headers) -> Optional[str]:
        """Same identity derivation as the replica api_server (explicit
        X-Tenant-Id, else a stable API-key hash) so router-fronted and
        direct traffic land in the same per-tenant buckets."""
        tid = headers.get("X-Tenant-Id")
        if tid:
            return str(tid)[:64]
        auth = headers.get("Authorization")
        if auth:
            return "key-" + hashlib.sha256(
                auth.encode("utf-8", "replace")).hexdigest()[:12]
        return None

    # -- forwarding ---------------------------------------------------------

    def _handoff_targets(self, prefill: Replica) -> List[str]:
        """host:port decode candidates for a prefill replica's KV
        handoff, ordered least-loaded. Decode-role replicas first;
        with none routable, mixed replicas stand in (the prefill
        replica itself is never a target)."""
        cands = [r for r in self.replicas
                 if r is not prefill and self._routable(r)]
        pool = [r for r in cands if r.role == "decode"] \
            or [r for r in cands if r.role == "mixed"]
        pool.sort(key=lambda r: (r.brownout, r.occupancy,
                                 r.queue_depth, len(r.inflight), r.idx))
        return [f"{self.host}:{r.port}"
                for r in pool[:max(1, self.cfg.handoff_fanout)]]

    def _fwd_headers(self, entry: JournalEntry,
                     r: Optional[Replica] = None) -> Dict[str, str]:
        """Headers for a replica forward: the client's tenant identity
        must survive the hop or every request lands in the replica's
        shared 'default' rate-limit bucket. A non-streaming forward to
        a prefill-role replica also names its decode candidates
        (X-Handoff-Targets) — the replica prefills, ships KV to the
        first target it can reach, and relays the decode's answer."""
        h = {"Content-Type": "application/json"}
        if entry.tenant:
            h["X-Tenant-Id"] = entry.tenant
        if entry.trace is not None:
            # the replica parents its engine spans under the ROUTER
            # span, not the client's — replays re-forward the same id,
            # so every attempt lands on one timeline
            h["traceparent"] = make_traceparent(entry.trace[0],
                                                entry.trace[2])
        if r is not None and r.role == "prefill" and not entry.stream:
            targets = self._handoff_targets(r)
            if targets:
                h["X-Handoff-Targets"] = ",".join(targets)
        return h

    def _forward_buffered(self, r: Replica, entry: JournalEntry
                          ) -> Tuple[int, bytes]:
        """POST the journaled body to one replica and buffer the full
        response. Raises ``ReplicaLost`` on any transport failure — a
        SIGKILLed process closes its sockets, so every death mode ends
        here rather than in a client-visible hang."""
        rid = entry.rid
        r.inflight.add(rid)
        conn = http.client.HTTPConnection(
            self.host, r.port, timeout=self.cfg.connect_timeout_sec)
        try:
            conn.request("POST", entry.path, body=entry.body,
                         headers=self._fwd_headers(entry, r))
            conn.sock.settimeout(self.cfg.forward_timeout_sec)
            resp = conn.getresponse()
            return resp.status, resp.read()
        except (OSError, http.client.HTTPException) as e:
            raise ReplicaLost(f"replica {r.idx}: "
                              f"{type(e).__name__}: {e}") from e
        finally:
            r.inflight.discard(rid)
            conn.close()

    def _forward_hedged(self, primary: Replica, entry: JournalEntry,
                        exclude: Dict[int, int]
                        ) -> Tuple[Replica, int, bytes]:
        """Primary forward, plus ONE duplicate on another replica when
        no response lands inside hedge_ms. First answer wins; the
        loser's closed connection triggers the replica engine's
        client-disconnect abort, freeing its slot."""
        hedge_ms = self.cfg.hedge_ms
        results: "queue.Queue" = queue.Queue()

        def run(rep: Replica):
            try:
                status, data = self._forward_buffered(rep, entry)
                results.put((rep, None, status, data))
            except ReplicaLost as e:
                results.put((rep, e, 0, b""))

        threading.Thread(target=run, args=(primary,), daemon=True).start()
        launched = 1
        if hedge_ms > 0 and not entry.stream:
            try:
                got = results.get(timeout=hedge_ms / 1000.0)
                results.put(got)       # not late: hand it back
            except queue.Empty:
                try:
                    second = self._pick(
                        entry.key, set(exclude) | {primary.idx})
                except NoReplica:
                    second = None
                if second is not None:
                    entry.hedged = True
                    self._count("hedges")
                    self._c_hedges.inc()
                    self.flight.record("hedge", rid=entry.rid,
                                       primary=primary.idx,
                                       hedge=second.idx)
                    if entry.trace is not None:
                        self.spans.annotate(
                            entry.trace[0], "hedge",
                            parent_id=entry.trace[2],
                            primary=primary.idx, hedge=second.idx,
                            request_id=entry.rid)
                    threading.Thread(target=run, args=(second,),
                                     daemon=True).start()
                    launched += 1
        err: Optional[ReplicaLost] = None
        err_rep = primary
        for _ in range(launched):
            rep, e, status, data = results.get()
            if e is None:
                return rep, status, data
            err, err_rep = e, rep
            self._breaker_failure(rep)
        raise ReplicaLost(str(err)) from err

    # -- request paths ------------------------------------------------------

    def route_buffered(self, entry: JournalEntry) -> Tuple[int, bytes]:
        """Non-streaming path: forward, and on replica loss REPLAY the
        journaled request on a healthy replica (up to max_replays).
        A replica's own 503 (drain race) re-routes without burning the
        replay budget — that is the rolling restart's zero-5xx leg."""
        t0 = time.monotonic()
        pick_deadline = t0 + self.cfg.no_replica_wait_sec
        exclude: Dict[int, int] = {}
        reroutes = 0
        while True:
            try:
                r = self._pick_wait(entry.key, exclude, pick_deadline)
            except NoReplica:
                return 503, json.dumps({"error": {
                    "message": "no healthy replica; retry shortly",
                    "type": "unavailable", "code": 503,
                    "retry_after": self.retry_after_hint()}}).encode()
            self.journal.assign(entry.rid, r.idx, r.generation)
            try:
                used, status, data = self._forward_hedged(
                    r, entry, exclude)
            except ReplicaLost as e:
                exclude[r.idx] = r.generation
                self._count("failovers")
                self._c_failovers.inc()
                self.flight.record(
                    "failover", rid=entry.rid, replica=r.idx,
                    error=str(e)[:200],
                    **({"trace_id": entry.trace[0]}
                       if entry.trace is not None else {}))
                if entry.trace is not None:
                    self.spans.annotate(
                        entry.trace[0], "failover",
                        parent_id=entry.trace[2], replica=r.idx,
                        request_id=entry.rid, error=str(e)[:120])
                if entry.replays < self.cfg.max_replays:
                    entry.replays += 1
                    self._count("replays")
                    self._c_replays.inc()
                    self.flight.record("replay", rid=entry.rid,
                                       attempt=entry.replays)
                    if entry.trace is not None:
                        self.spans.annotate(
                            entry.trace[0], "failover_replay",
                            parent_id=entry.trace[2],
                            attempt=entry.replays,
                            request_id=entry.rid)
                    continue
                return 502, json.dumps({"error": {
                    "message": "replica failed and replay budget is "
                               "spent", "type": "replica_lost",
                    "code": 502, "replays": entry.replays,
                    "retry_after": self.retry_after_hint()}}).encode()
            if status == 429:
                # per-tenant rate limit: every replica enforces the
                # same tenant budget, so re-routing would just evade
                # it — propagate verbatim (Retry-After preserved by
                # the handler), no replay burn, no breaker hit
                self._breaker_success(used)
                self._count("shed_429")
                self.flight.record("shed_429", rid=entry.rid,
                                   replica=used.idx,
                                   tenant=entry.tenant or "default")
                self._count("requests")
                # idx bounded by fleet size, status by HTTP codes
                self._c_requests.labels(
                    str(used.idx), str(status)).inc()  # graftlint: disable=metric-label-cardinality
                return status, data
            if status == 503:
                # the replica is shedding (drain race or overload):
                # someone else takes it; re-route burns no replay
                # budget — only when every replica shed does the 503
                # reach the client
                exclude[used.idx] = used.generation
                reroutes += 1
                self._count("rerouted_503")
                self.flight.record("reroute_503", rid=entry.rid,
                                   replica=used.idx)
                if reroutes <= len(self.replicas):
                    continue
                return 503, data
            if status >= 500:
                self._breaker_failure(used)
            else:
                self._breaker_success(used)
            self._count("requests")
            # idx bounded by fleet size, status by HTTP codes
            self._c_requests.labels(
                str(used.idx), str(status)).inc()  # graftlint: disable=metric-label-cardinality
            self._h_latency.observe(time.monotonic() - t0)
            return status, data

    # streaming handled in the HTTP handler (needs the client socket)

    # -- rolling restart ----------------------------------------------------

    def rolling_restart(self) -> dict:
        """Drain + respawn replicas ONE AT A TIME: stop routing to the
        replica, SIGTERM it (the api_server's drain finishes in-flight
        work, then the process exits), respawn, wait healthy, move on.
        Raises ``RuntimeError`` when already in progress."""
        if not self._admin_lock.acquire(blocking=False):
            raise RuntimeError("rolling restart already in progress")
        t0 = time.monotonic()
        results = []
        self._rolling = True
        self.flight.record("rolling_restart_begin",
                           replicas=len(self.replicas))
        try:
            for r in self.replicas:
                if r.state == QUARANTINED:
                    results.append({"replica": r.idx,
                                    "skipped": "quarantined"})
                    continue
                r.planned_restart = True   # the supervisor hands over
                self._set_state(r, DRAINING)
                step = {"replica": r.idx, "pid": r.pid}
                try:
                    if r.proc is not None and r.proc.poll() is None:
                        r.proc.terminate()     # SIGTERM -> drain
                        try:
                            r.proc.wait(
                                timeout=self.cfg.drain_exit_timeout_sec)
                        except Exception:
                            r.proc.kill()
                            r.proc.wait(timeout=5)
                            step["forced_kill"] = True
                    self._respawn(r)
                    if not self._wait_healthy(
                            r, self.cfg.spawn_timeout_sec):
                        step["error"] = ("replacement never became "
                                         "healthy")
                        results.append(step)
                        break
                    step["ok"] = True
                    results.append(step)
                finally:
                    r.planned_restart = False
            return {"rolling_restart": results,
                    "duration_s": round(time.monotonic() - t0, 3),
                    "ok": all(s.get("ok") or s.get("skipped")
                              for s in results)}
        finally:
            self._rolling = False
            self.flight.record("rolling_restart_end")
            self._admin_lock.release()

    def fleet_profiler(self, body: Optional[dict] = None) -> dict:
        """``POST /v1/admin/profiler``: fan a time-boxed jax.profiler
        capture out to every routable replica SIMULTANEOUSLY (the
        interesting regressions are fleet-synchronized: a noisy
        neighbor, a tunnel hiccup, a bad deploy hits every replica in
        the same second). Each replica captures into its own subdir of
        ``log_dir`` and auto-stops at ``duration_sec`` (clamped to
        ``BIGDL_TPU_PROFILER_MAX_SEC``) via the profiler watchdog — no
        stop fan-out needed. The whole capture is stitched to one fleet
        ``capture_id`` (a trace id), recorded as a router span so
        ``GET /v1/trace/{capture_id}`` shows who captured what.
        Raises ``RuntimeError`` when an admin operation is already in
        progress, ``ValueError`` on a bad duration."""
        body = body or {}
        duration = body.get("duration_sec")
        if duration is not None:
            try:
                duration = float(duration)
            except (TypeError, ValueError):
                raise ValueError(
                    f"duration_sec must be a positive number, got "
                    f"{body.get('duration_sec')!r}")
            if duration <= 0:
                raise ValueError(
                    f"duration_sec must be a positive number, got "
                    f"{duration}")
        log_dir = body.get("log_dir") or os.path.join(
            os.environ.get("BIGDL_TPU_POSTMORTEM_DIR") or "/tmp",
            "fleet_profiler")
        if not os.path.isabs(log_dir):
            raise ValueError(
                f"log_dir must be an absolute path, got {log_dir!r}")
        if not self._admin_lock.acquire(blocking=False):
            raise RuntimeError("an admin operation is already in "
                               "progress")
        try:
            capture_id = new_trace_id()
            t0 = time.time()
            targets = [r for r in self.replicas
                       if r.state == HEALTHY and r.alive()]
            self.flight.record("fleet_profiler_begin",
                               capture_id=capture_id,
                               replicas=[r.idx for r in targets],
                               log_dir=log_dir,
                               duration_sec=duration)
            # one thread per replica: the whole point is that every
            # replica's capture brackets the SAME wall-clock window
            # (profiler init can take seconds — serial fan-out would
            # stagger the windows by that much per replica)
            results = []
            for r in targets:
                sub = os.path.join(log_dir, capture_id,
                                   f"replica{r.idx}")
                results.append({"replica": r.idx, "port": r.port,
                                "log_dir": sub})

            def _start_one(r, row):
                doc = {"log_dir": row["log_dir"],
                       "capture_id": capture_id}
                if duration is not None:
                    doc["duration_sec"] = duration
                try:
                    status, raw = self._http_post(
                        r.port, "/v1/profiler/start", doc,
                        max(self.cfg.health_timeout_sec, 15.0))
                    row["status"] = status
                    try:
                        row["body"] = json.loads(raw)
                    except ValueError:
                        pass
                    row["ok"] = status == 200
                except OSError as e:
                    row["ok"] = False
                    row["error"] = str(e)

            threads = [threading.Thread(target=_start_one, args=tr,
                                        daemon=True)
                       for tr in zip(targets, results)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30.0)
            for r, row in zip(targets, results):
                row.setdefault("ok", False)
                self.spans.record(
                    "fleet_capture", capture_id,
                    t_start=t0, t_end=time.time(),
                    replica=r.idx, port=r.port,
                    log_dir=row["log_dir"], ok=row["ok"])
            started = sum(1 for row in results if row.get("ok"))
            self._count("fleet_profiler_captures", started)
            self.flight.record("fleet_profiler_end",
                               capture_id=capture_id, started=started,
                               replicas=len(results))
            return {"capture_id": capture_id, "log_dir": log_dir,
                    "duration_sec": duration, "replicas": results,
                    "started": started, "ok": started == len(results)
                    and bool(results)}
        finally:
            self._admin_lock.release()

    def _wait_healthy(self, r: Replica, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if r.proc is not None and r.proc.poll() is not None:
                return False
            try:
                status, _ = self._http_get(r.port, "/health",
                                           self.cfg.health_timeout_sec)
                if status == 200:
                    r.probe_failures = 0
                    self._set_state(r, HEALTHY)
                    return True
            except OSError:
                pass
            time.sleep(min(0.1, self.cfg.health_sec))
        return False

    # -- fleet mutation (autoscaler) ----------------------------------------
    #
    # All three mutators are called with self._admin_lock HELD by the
    # caller (the autoscaler tick) — the same lock rolling_restart
    # takes, so a scale decision can never race a rolling restart.
    # Replicas are NEVER removed from self.replicas (routing holds
    # positional idx lookups); a retired replica stays in the list in
    # the terminal RETIRED state, which the supervisor skips.

    def add_replica(self, role: str = "mixed") -> Replica:
        """Grow the fleet by one replica (scale-up). Returns the new
        Replica immediately (state STARTING); the supervisor's probe
        loop promotes it to HEALTHY once /health answers."""
        if role not in ROLES:
            raise ValueError(f"unknown replica role {role!r}")
        r = Replica(len(self.replicas), _free_port(self.host),
                    role=role)
        self._respawn(r, initial=True)
        self.replicas.append(r)
        self._count("autoscale_spawned")
        self.flight.record("replica_added", replica=r.idx,
                           port=r.port, role=role)
        return r

    def retire_replica(self, r: Replica,
                       reason: str = "autoscale") -> bool:
        """Drain and permanently remove one replica (scale-down):
        routing stops immediately, SIGTERM runs the api_server's
        graceful drain, and the slot is left in the terminal RETIRED
        state. Returns False WITHOUT touching the process when the
        replica is the last healthy one (a fleet of zero serves
        nothing) or is not in a retirable state."""
        healthy_others = [x for x in self.replicas
                          if x is not r and x.state == HEALTHY
                          and not x.planned_restart]
        if r.state != HEALTHY or r.planned_restart \
                or not healthy_others:
            self._count("autoscale_refused")
            self.flight.record(
                "retire_refused", replica=r.idx,
                reason=("last_healthy" if not healthy_others
                        else f"state:{r.state}"))
            return False
        r.planned_restart = True         # supervisor hands the proc over
        self._set_state(r, DRAINING)
        try:
            if r.proc is not None and r.proc.poll() is None:
                r.proc.terminate()       # SIGTERM -> graceful drain
                try:
                    r.proc.wait(timeout=self.cfg.drain_exit_timeout_sec)
                except Exception:
                    try:
                        r.proc.kill()
                        r.proc.wait(timeout=5)
                    except Exception:
                        pass
        finally:
            self._set_state(r, RETIRED)
            r.planned_restart = False
        self._count("autoscale_retired")
        self.flight.record("replica_retired", replica=r.idx,
                           reason=reason)
        return True

    def reassign_role(self, r: Replica, role: str) -> bool:
        """Flip one replica's fleet role via drain + respawn (the role
        is a process property, resolved from the spawn env — never
        mutated live). Refuses on the last healthy replica: the flip
        makes it unavailable for a spawn cycle."""
        if role not in ROLES:
            raise ValueError(f"unknown replica role {role!r}")
        healthy_others = [x for x in self.replicas
                          if x is not r and x.state == HEALTHY
                          and not x.planned_restart]
        if r.state != HEALTHY or r.planned_restart \
                or not healthy_others:
            self._count("autoscale_refused")
            self.flight.record("role_flip_refused", replica=r.idx,
                               role=role)
            return False
        prev = r.role
        r.planned_restart = True
        self._set_state(r, DRAINING)
        try:
            if r.proc is not None and r.proc.poll() is None:
                r.proc.terminate()
                try:
                    r.proc.wait(timeout=self.cfg.drain_exit_timeout_sec)
                except Exception:
                    try:
                        r.proc.kill()
                        r.proc.wait(timeout=5)
                    except Exception:
                        pass
            r.role = role
            self._respawn(r)
            ok = self._wait_healthy(r, self.cfg.spawn_timeout_sec)
        finally:
            r.planned_restart = False
        self._count("autoscale_role_flips")
        self.flight.record("replica_role_flip", replica=r.idx,
                           prev=prev, role=role, ok=ok)
        return ok

    # -- distributed-trace fan-out ------------------------------------------

    def trace_timeline(self, trace_id: str) -> dict:
        """The ``GET /v1/trace/{id}`` document: this router's own spans
        plus every replica's (``GET /v1/internal/spans?trace_id=``),
        stitched by ``merge_timeline`` with a per-replica clock-skew
        estimate (local midpoint of the fan-out RTT minus the replica's
        reported ``now``)."""
        groups: List[Tuple[float, List[dict]]] = [
            (0.0, self.spans.spans_for(trace_id))]
        for r in self.replicas:
            if not r.alive():
                continue
            try:
                t_req0 = time.time()
                status, body = self._http_get(
                    r.port, f"/v1/internal/spans?trace_id={trace_id}",
                    self.cfg.health_timeout_sec)
                t_req1 = time.time()
                if status != 200:
                    continue
                doc = json.loads(body)
                skew = ((t_req0 + t_req1) / 2.0
                        - float(doc.get("now", t_req1)))
                groups.append((skew, doc.get("spans") or []))
            except (OSError, ValueError):
                continue
        # a client-supplied parent span lives outside the fleet: spans
        # pointing at it are NOT orphans
        ext = [s["parent_id"] for s in self.spans.spans_for(trace_id)
               if s.get("name") == "router.request"
               and s.get("parent_id")]
        return merge_timeline(trace_id, groups, external_parents=ext)

    def trace_index(self, k: int = 16) -> List[dict]:
        """The ``GET /v1/traces`` list: recent slow traces (top-k by
        duration) merged across the router and every live replica."""
        best: Dict[str, dict] = {}

        def take(t: dict) -> None:
            tid = t.get("trace_id")
            cur = best.get(tid)
            if cur is None or t.get("duration_s", 0.0) \
                    > cur.get("duration_s", 0.0):
                best[tid] = t

        for t in self.spans.recent_traces(k):
            take(t)
        for r in self.replicas:
            if not r.alive():
                continue
            try:
                status, body = self._http_get(
                    r.port, "/v1/internal/spans",
                    self.cfg.health_timeout_sec)
                if status != 200:
                    continue
                for t in json.loads(body).get("traces") or []:
                    take(t)
            except (OSError, ValueError):
                continue
        out = sorted(best.values(),
                     key=lambda d: -d.get("duration_s", 0.0))
        return out[:max(k, 0)]

    # -- introspection ------------------------------------------------------

    def _tenant_aggregate(self) -> dict:
        """Fleet-wide per-tenant counters: the sum of every replica's
        probed overload.tenants block (admitted/shed/generated)."""
        agg: Dict[str, collections.Counter] = {}
        for r in self.replicas:
            for name, t in (r.tenants or {}).items():
                acc = agg.setdefault(str(name), collections.Counter())
                for k, v in t.items():
                    if isinstance(v, (int, float)):
                        acc[k] += v
        return {name: dict(c) for name, c in sorted(agg.items())}

    def _count(self, key: str, n: int = 1) -> None:
        """Bump a stats counter (thread-safe: supervisor + handlers)."""
        with self._lock:
            self.counts[key] += n

    def counts_snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {k: int(v) for k, v in sorted(self.counts.items())}

    def _perf_aggregate(self) -> dict:
        """Fleet roofline view from the per-replica /v1/stats perf
        blocks: per-replica utils plus fleet min/mean (the min is the
        alarm — one replica off the roof drags every hedged request)
        and the count of tripped sentinels."""
        per: Dict[str, dict] = {}
        utils: List[float] = []
        tripped = 0
        for r in self.replicas:
            if not r.perf:
                continue
            per[str(r.idx)] = dict(r.perf)
            u = r.perf.get("roofline_util_decode")
            if isinstance(u, (int, float)):
                utils.append(float(u))
            if r.perf.get("sentinel_tripped"):
                tripped += 1
        out: dict = {"replicas": per, "sentinels_tripped": tripped}
        if utils:
            out["decode_util_min"] = round(min(utils), 4)
            out["decode_util_mean"] = round(
                sum(utils) / len(utils), 4)
        return out

    def _quality_aggregate(self) -> dict:
        """Fleet quality view from the per-replica /v1/stats quality
        blocks: per-replica NLL/probe numbers plus the fleet's worst
        probe NLL (one silently-degraded replica is the alarm — it
        serves wrong-but-plausible tokens at full speed) and the count
        of tripped quality sentinels."""
        per: Dict[str, dict] = {}
        probe_nlls: List[float] = []
        tripped = 0
        for r in self.replicas:
            if not r.quality:
                continue
            per[str(r.idx)] = dict(r.quality)
            pn = r.quality.get("probe_nll")
            if isinstance(pn, (int, float)):
                probe_nlls.append(float(pn))
            if r.quality.get("sentinel_tripped"):
                tripped += 1
        out: dict = {"replicas": per, "sentinels_tripped": tripped}
        if probe_nlls:
            out["probe_nll_max"] = round(max(probe_nlls), 4)
            out["probe_nll_mean"] = round(
                sum(probe_nlls) / len(probe_nlls), 4)
        return out

    def _slo_aggregate(self) -> dict:
        """Fleet SLO view from the per-replica /v1/stats slo blocks:
        total active alerts and the worst burn rate anywhere (one
        replica burning its budget is the fleet's page), plus the
        canary prober's correctness state."""
        per: Dict[str, dict] = {}
        alerts_active = alerts_total = 0
        burn_max = 0.0
        for r in self.replicas:
            if not r.slo:
                continue
            per[str(r.idx)] = dict(r.slo)
            alerts_active += int(r.slo.get("alerts_active") or 0)
            alerts_total += int(r.slo.get("alerts_total") or 0)
            bm = r.slo.get("burn_rate_max")
            if isinstance(bm, (int, float)):
                burn_max = max(burn_max, float(bm))
        return {
            "replicas": per,
            "alerts_active": alerts_active,
            "alerts_total": alerts_total,
            "burn_rate_max": round(burn_max, 4),
            "canary": self.canary.snapshot(),
        }

    def stats_snapshot(self) -> dict:
        """JSON-ready router state for ``GET /v1/router/stats`` (and
        the bench JSON's ``router`` block)."""
        return {
            "replicas": [r.snapshot() for r in self.replicas],
            "journal_depth": self.journal.depth(),
            "spans": self.spans.snapshot(),
            "tenants": self._tenant_aggregate(),
            "counters": self.counts_snapshot(),
            "rolling_restart_in_progress": self._rolling,
            "perf": self._perf_aggregate(),
            "quality": self._quality_aggregate(),
            "slo": self._slo_aggregate(),
            "roles": {ro: sum(1 for r in self.replicas
                              if r.role == ro and r.state == HEALTHY)
                      for ro in ROLES},
            "autoscaler": (self.autoscaler.snapshot()
                           if self.autoscaler is not None else None),
            "config": {
                "replicas": self.cfg.replicas,
                "health_sec": self.cfg.health_sec,
                "hedge_ms": self.cfg.hedge_ms,
                "crash_budget": self.cfg.crash_budget,
                "canary_sec": self.cfg.canary_sec,
                "breaker_threshold": self.cfg.breaker_threshold,
                "max_replays": self.cfg.max_replays,
                "affinity_tokens": self.cfg.affinity_tokens,
                "handoff_fanout": self.cfg.handoff_fanout,
            },
        }

    # -- http front ---------------------------------------------------------

    def make_handler(router):
        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):   # quiet
                pass

            def _json(self, code: int, obj, headers=()):
                body = obj if isinstance(obj, bytes) \
                    else json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                try:
                    self.wfile.write(body)
                except OSError:
                    pass

            def _proxy(self, method: str, body: Optional[bytes] = None):
                """Pass non-completion traffic (models, stats, memory,
                metrics-of-replica, profiler) to any routable replica."""
                try:
                    r = router._pick(0)
                except NoReplica:
                    return self._json(503, {"error": {
                        "message": "no healthy replica",
                        "type": "unavailable", "code": 503}})
                conn = http.client.HTTPConnection(
                    router.host, r.port,
                    timeout=router.cfg.forward_timeout_sec)
                try:
                    conn.request(method, self.path, body=body,
                                 headers={"Content-Type":
                                          "application/json"})
                    resp = conn.getresponse()
                    data = resp.read()
                    ctype = resp.getheader("Content-Type",
                                           "application/json")
                    self.send_response(resp.status)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                except (OSError, http.client.HTTPException) as e:
                    self._json(502, {"error": {
                        "message": f"replica proxy failed: {e}",
                        "type": "replica_lost", "code": 502}})
                finally:
                    conn.close()

            def do_GET(self):
                if self.path in ("/health", "/ping"):
                    n = sum(1 for r in router.replicas
                            if router._routable(r))
                    if n:
                        self._json(200, {"status": "ok",
                                         "routable_replicas": n})
                    else:
                        self._json(
                            503,
                            {"status": "no_healthy_replica",
                             "retry_after": router.retry_after_hint()},
                            headers=(("Retry-After",
                                      str(router.retry_after_hint())),))
                elif self.path == "/metrics":
                    body = router.registry.render().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/v1/router/stats":
                    self._json(200, router.stats_snapshot())
                elif self.path == "/v1/router/flight":
                    self._json(200, {"events":
                                     router.flight.snapshot()})
                elif self.path.startswith("/v1/trace/"):
                    tid = self.path[len("/v1/trace/"):].split("?")[0]
                    self._json(200, router.trace_timeline(tid))
                elif self.path == "/v1/traces" \
                        or self.path.startswith("/v1/traces?"):
                    from urllib.parse import parse_qs, urlparse

                    q = parse_qs(urlparse(self.path).query)
                    try:
                        k = int((q.get("k") or ["16"])[0])
                    except ValueError:
                        k = 16
                    self._json(200, {"traces": router.trace_index(k)})
                else:
                    self._proxy("GET")

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n) if n else b"{}"
                if self.path == "/v1/admin/rolling_restart":
                    try:
                        out = router.rolling_restart()
                    except RuntimeError as e:
                        return self._json(409, {"error": str(e)})
                    return self._json(200 if out.get("ok") else 500,
                                      out)
                if self.path == "/v1/admin/profiler":
                    try:
                        body = json.loads(raw or b"{}")
                    except json.JSONDecodeError:
                        return self._json(400, {"error": "bad json"})
                    try:
                        out = router.fleet_profiler(body)
                    except ValueError as e:
                        return self._json(400, {"error": str(e)})
                    except RuntimeError as e:
                        return self._json(409, {"error": str(e)})
                    return self._json(200 if out.get("ok") else 500,
                                      out)
                if self.path not in ("/v1/completions",
                                     "/v1/chat/completions"):
                    return self._proxy("POST", raw)
                try:
                    body = json.loads(raw or b"{}")
                except json.JSONDecodeError:
                    return self._json(400, {"error": "bad json"})
                # trace context: accept the client's traceparent or
                # mint a fresh trace id; the tail-sampling decision is
                # a pure function of the id, so every replica agrees
                client = parse_traceparent(
                    self.headers.get("traceparent"))
                tid, parent = client if client is not None \
                    else (new_trace_id(), None)
                trace = ((tid, parent, new_span_id())
                         if trace_sampled(tid, router.spans.sample)
                         else None)
                entry = JournalEntry(
                    rid=f"rtr-{uuid.uuid4().hex[:12]}",
                    path=self.path, body=raw,
                    stream=bool(body.get("stream")),
                    key=router._affinity_key(body),
                    tenant=router._tenant_of(self.headers),
                    trace=trace)
                router.journal.admit(entry)   # write-ahead
                t_req0 = time.time()
                status = None
                try:
                    if entry.stream:
                        self._stream(entry)
                    else:
                        status, data = router.route_buffered(entry)
                        headers = ()
                        if status in (429, 503):
                            headers = _retry_after_headers(data) or (
                                ("Retry-After",
                                 str(router.retry_after_hint())),)
                        if entry.trace is not None:
                            headers = tuple(headers) + (
                                ("X-Trace-Id", entry.trace[0]),)
                        self._json(status, data, headers=headers)
                finally:
                    router.journal.complete(entry.rid)
                    if entry.trace is not None:
                        router.spans.record(
                            "router.request", entry.trace[0],
                            span_id=entry.trace[2],
                            parent_id=entry.trace[1],
                            t_start=t_req0, t_end=time.time(),
                            request_id=entry.rid, path=self.path,
                            stream=entry.stream,
                            replays=entry.replays,
                            hedged=entry.hedged,
                            **({"status": status}
                               if status is not None else {}))

            def _stream(self, entry: JournalEntry):
                """Relay SSE from the replica. A replica lost BEFORE
                any byte reached the client re-routes invisibly; lost
                MID-STREAM, the client gets a structured error event
                plus [DONE] instead of a dropped socket (generation is
                not transparently resumable — the client resubmits
                after retry_after)."""
                exclude: Dict[int, int] = {}
                reroutes = 0
                pick_deadline = (time.monotonic()
                                 + router.cfg.no_replica_wait_sec)
                while True:
                    try:
                        r = router._pick_wait(entry.key, exclude,
                                              pick_deadline)
                    except NoReplica:
                        return self._json(503, {"error": {
                            "message": "no healthy replica",
                            "type": "unavailable", "code": 503,
                            "retry_after": router.retry_after_hint()}})
                    router.journal.assign(entry.rid, r.idx,
                                          r.generation)
                    r.inflight.add(entry.rid)
                    conn = http.client.HTTPConnection(
                        router.host, r.port,
                        timeout=router.cfg.connect_timeout_sec)
                    try:
                        try:
                            conn.request(
                                "POST", entry.path, body=entry.body,
                                headers=router._fwd_headers(entry, r))
                            conn.sock.settimeout(
                                router.cfg.forward_timeout_sec)
                            resp = conn.getresponse()
                        except (OSError,
                                http.client.HTTPException) as e:
                            # nothing streamed yet: invisible failover
                            router._breaker_failure(r)
                            exclude[r.idx] = r.generation
                            router._count("failovers")
                            router._c_failovers.inc()
                            router.flight.record(
                                "failover", rid=entry.rid,
                                replica=r.idx, error=str(e)[:200],
                                **({"trace_id": entry.trace[0]}
                                   if entry.trace is not None
                                   else {}))
                            if entry.trace is not None:
                                router.spans.annotate(
                                    entry.trace[0], "failover",
                                    parent_id=entry.trace[2],
                                    replica=r.idx,
                                    request_id=entry.rid)
                            if entry.replays < router.cfg.max_replays:
                                entry.replays += 1
                                router._count("replays")
                                router._c_replays.inc()
                                if entry.trace is not None:
                                    router.spans.annotate(
                                        entry.trace[0],
                                        "failover_replay",
                                        parent_id=entry.trace[2],
                                        attempt=entry.replays,
                                        request_id=entry.rid)
                                continue
                            return self._json(502, {"error": {
                                "message": "replica failed before the "
                                           "stream started",
                                "type": "replica_lost", "code": 502}})
                        if resp.status == 429:
                            # tenant rate limit: same budget on every
                            # replica — propagate, don't re-route
                            data = resp.read()
                            router._breaker_success(r)
                            router._count("shed_429")
                            return self._json(
                                429, data,
                                headers=_retry_after_headers(data))
                        if resp.status == 503 \
                                and reroutes <= len(router.replicas):
                            resp.read()
                            exclude[r.idx] = r.generation
                            reroutes += 1
                            router._count("rerouted_503")
                            continue
                        if resp.status != 200:
                            data = resp.read()
                            router._breaker_failure(r) \
                                if resp.status >= 500 \
                                else router._breaker_success(r)
                            return self._json(resp.status, data)
                        # 200: stream is live — relay line-wise
                        router._breaker_success(r)
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "text/event-stream")
                        self.send_header("Cache-Control", "no-cache")
                        self.end_headers()
                        self._relay(entry, r, resp)
                        return
                    finally:
                        r.inflight.discard(entry.rid)
                        conn.close()

            def _relay(self, entry: JournalEntry, r: Replica, resp):
                saw_done = False
                try:
                    while True:
                        line = resp.fp.readline()
                        if not line:
                            break
                        if line.strip() == b"data: [DONE]":
                            saw_done = True
                        try:
                            self.wfile.write(line)
                            if line == b"\n":
                                self.wfile.flush()
                        except OSError:
                            # CLIENT left: closing the replica conn
                            # (finally below) trips the engine's SSE
                            # write failure -> abort + slot free
                            router.flight.record(
                                "stream_client_gone", rid=entry.rid)
                            return
                except (OSError, http.client.HTTPException):
                    pass                 # replica died mid-read
                if saw_done:
                    return
                # REPLICA lost mid-stream: structured error, not a
                # dropped socket
                router._count("failovers")
                router._count("stream_errors")
                router._c_failovers.inc()
                router._breaker_failure(r)
                retry = router.retry_after_hint()
                router.flight.record("stream_replica_lost",
                                     rid=entry.rid, replica=r.idx)
                event = {"error": {
                    "message": "replica failed mid-stream; resubmit "
                               "the request",
                    "type": "replica_failover", "code": 503,
                    "retry_after": retry}}
                try:
                    self.wfile.write(
                        b"data: " + json.dumps(event).encode()
                        + b"\n\ndata: [DONE]\n\n")
                    self.wfile.flush()
                except OSError:
                    pass

        return Handler

    def serve(self, host: str = "127.0.0.1", port: int = 8080,
              background: bool = False) -> ThreadingHTTPServer:
        self._httpd = ThreadingHTTPServer((host, port),
                                          self.make_handler())
        if background:
            t = threading.Thread(target=self._httpd.serve_forever,
                                 daemon=True)
            t.start()
        else:
            self._httpd.serve_forever()
        return self._httpd


def main():
    """CLI: python -m bigdl_tpu.serving.router --model PATH
    --replicas N [--tiny-random] — spawns the replicas as
    ``api_server`` subprocesses and serves the routed OpenAI API."""
    import argparse
    import signal

    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default=None)
    ap.add_argument("--load-in-low-bit", default="sym_int4")
    ap.add_argument("--tiny-random", action="store_true")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--replicas", type=int, default=None,
                    help="default $BIGDL_TPU_ROUTER_REPLICAS (2)")
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--max-seq", type=int, default=2048)
    ap.add_argument("--health-sec", type=float, default=None,
                    help="default $BIGDL_TPU_ROUTER_HEALTH_SEC (1.0)")
    ap.add_argument("--hedge-ms", type=float, default=None,
                    help="default $BIGDL_TPU_ROUTER_HEDGE_MS (0 = off)")
    ap.add_argument("--crash-budget", type=int, default=None,
                    help="default $BIGDL_TPU_ROUTER_CRASH_BUDGET (3)")
    ap.add_argument("--roles", default=None,
                    help="comma-separated per-index fleet roles, e.g. "
                         "'prefill,decode' (rest default to mixed)")
    ap.add_argument("--autoscale", action="store_true",
                    help="run the load-signal autoscaler "
                         "(serving/autoscaler.py; bounds from "
                         "$BIGDL_TPU_AUTOSCALE_MIN/MAX, dwell from "
                         "$BIGDL_TPU_AUTOSCALE_DWELL_SEC)")
    args = ap.parse_args()

    if not args.model and not args.tiny_random:
        ap.error("--model is required (or pass --tiny-random)")
    roles = ([s.strip() for s in args.roles.split(",") if s.strip()]
             if args.roles else None)
    cmd = [sys.executable, "-m", "bigdl_tpu.serving.api_server",
           "--host", args.host, "--port", "{port}",
           "--max-batch", str(args.max_batch),
           "--max-seq", str(args.max_seq)]
    if args.tiny_random:
        cmd += ["--tiny-random"]
    else:
        cmd += ["--model", args.model,
                "--load-in-low-bit", args.load_in_low_bit]

    router = Router(
        replica_cmd=cmd,
        config=RouterConfig(replicas=args.replicas,
                            health_sec=args.health_sec,
                            hedge_ms=args.hedge_ms,
                            crash_budget=args.crash_budget,
                            roles=roles),
        host=args.host)
    print(f"router: spawning {router.cfg.replicas} replicas on ports "
          f"{[r.port for r in router.replicas]}", file=sys.stderr)
    router.start()

    scaler = None
    if args.autoscale:
        from bigdl_tpu.serving.autoscaler import Autoscaler

        scaler = Autoscaler(router)
        scaler.start()
        print(f"autoscaler: bounds [{scaler.cfg.min_replicas}, "
              f"{scaler.cfg.max_replicas}], dwell "
              f"{scaler.cfg.dwell_sec}s", file=sys.stderr)

    def _term(signum, frame):
        if scaler is not None:
            scaler.stop()
        threading.Thread(target=router.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    print(f"routing on http://{args.host}:{args.port}/v1",
          file=sys.stderr)
    router.serve(args.host, args.port)


if __name__ == "__main__":
    main()
