"""FastChat model worker over the bigdl-tpu engine.

Equivalent of the reference's FastChat integration (reference
serving/fastchat/ipex_llm_worker.py:52 `BigDLLLMWorker`: registers with a
FastChat controller, serves generate_stream). fastchat is optional; the
streaming core (`WorkerCore`) is dependency-free and unit-tested, the HTTP
worker shell is created only when fastchat is importable.

Run: python -m bigdl_tpu.serving.fastchat_worker --model-path PATH \
         --controller-address http://... --worker-address http://...
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Any, Dict, Iterator, Optional

from bigdl_tpu.serving.engine import EngineConfig, LLMEngine, SamplingParams


class WorkerCore:
    """Model + engine + tokenizer; yields FastChat-wire-format chunks."""

    def __init__(self, model_path: str, low_bit: str = "sym_int4",
                 max_batch: int = 4, max_seq: int = 2048,
                 embedder_path: Optional[str] = None):
        from bigdl_tpu.transformers.model import AutoModelForCausalLM

        self.model = AutoModelForCausalLM.from_pretrained(
            model_path, load_in_low_bit=low_bit, max_seq=max_seq)
        self.tokenizer = None
        try:
            from transformers import AutoTokenizer

            self.tokenizer = AutoTokenizer.from_pretrained(model_path)
        except Exception:
            pass
        self.engine = LLMEngine(self.model, EngineConfig(
            max_batch=max_batch, max_seq=max_seq))
        self.context_len = max_seq
        # embeddings endpoint: a BERT-family encoder served next to the
        # LLM (the reference worker has no embeddings either; ours wires
        # transformers/embedder.py when a checkpoint is configured)
        self.embedder = None
        self.embedder_tokenizer = None
        if embedder_path is not None:
            from transformers import AutoTokenizer

            from bigdl_tpu.transformers.embedder import BertEmbedder

            self.embedder = BertEmbedder.from_pretrained(
                embedder_path, load_in_low_bit=low_bit)
            self.embedder_tokenizer = AutoTokenizer.from_pretrained(
                embedder_path)

    def generate_stream(self, params: Dict[str, Any]) -> Iterator[Dict]:
        """FastChat generate_stream protocol: yields dicts with
        {text, error_code, usage} as tokens arrive."""
        prompt = params["prompt"]
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ValueError("string prompt needs a tokenizer")
            ids = self.tokenizer(prompt)["input_ids"]
        else:
            ids = list(prompt)
        sp = SamplingParams(
            max_tokens=int(params.get("max_new_tokens", 256)),
            temperature=float(params.get("temperature", 0.0)),
            top_k=int(params.get("top_k", 0)),
            top_p=float(params.get("top_p", 1.0)),
        )
        rid = f"fc-{uuid.uuid4().hex[:12]}"
        self.engine.add_request(rid, ids, sp)
        out_ids = []
        finished = False
        while not finished:
            if not self.engine.step():
                time.sleep(0.002)
            for o in self.engine.get_outputs(rid):
                out_ids.extend(o.new_token_ids)
                finished |= o.finished
                text = (self.tokenizer.decode(out_ids,
                                              skip_special_tokens=True)
                        if self.tokenizer else json.dumps(out_ids))
                yield {
                    "text": text,
                    "error_code": 0,
                    "usage": {"prompt_tokens": len(ids),
                              "completion_tokens": len(out_ids),
                              "total_tokens": len(ids) + len(out_ids)},
                    "finish_reason": o.finish_reason if o.finished else None,
                }

    def get_embeddings(self, params: Dict[str, Any]) -> Dict[str, Any]:
        """FastChat embeddings protocol: {"input": [texts]} ->
        {"embedding": [[f32]], "token_num": N}. Tokenizes ONCE (with
        truncation), so token_num counts exactly what was embedded."""
        if self.embedder is None:
            raise ValueError(
                "no embedder configured; start the worker with "
                "--embedder-path pointing at a BERT-family checkpoint")
        texts = params["input"]
        if isinstance(texts, str):
            texts = [texts]
        if not texts:
            return {"embedding": [], "token_num": 0}
        vecs, token_num = self.embedder.embed_texts(
            texts, self.embedder_tokenizer, with_counts=True)
        return {"embedding": [list(map(float, v)) for v in vecs],
                "token_num": token_num}


def _make_fastchat_worker():
    import asyncio

    from fastchat.serve.base_model_worker import BaseModelWorker, app

    class BigdlTpuWorker(BaseModelWorker):
        """The reference's BigDLLLMWorker equivalent."""

        def __init__(self, controller_addr, worker_addr, worker_id,
                     model_path, model_names, limit_worker_concurrency,
                     conv_template=None, **core_kwargs):
            super().__init__(controller_addr, worker_addr, worker_id,
                             model_path, model_names,
                             limit_worker_concurrency,
                             conv_template=conv_template)
            self.core = WorkerCore(model_path, **core_kwargs)
            self.context_len = self.core.context_len
            self.init_heart_beat()

        def generate_stream_gate(self, params):
            try:
                for chunk in self.core.generate_stream(params):
                    yield json.dumps(chunk).encode() + b"\0"
            except Exception as e:
                yield json.dumps({"text": str(e), "error_code": 1}).encode() \
                    + b"\0"

        async def generate_gate(self, params):
            out = None
            for chunk in self.core.generate_stream(params):
                out = chunk
            return out

        def get_embeddings(self, params):
            # never raise through the route: fastchat acquires the worker
            # semaphore before calling and only releases after — an
            # exception here would leak a permit per failed call
            try:
                return self.core.get_embeddings(params)
            except Exception as e:
                return {"embedding": [], "token_num": 0,
                        "error_code": 1, "text": str(e)}

    return BigdlTpuWorker, app


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--model-path", required=True)
    ap.add_argument("--low-bit", default="sym_int4")
    ap.add_argument("--controller-address", default="http://localhost:21001")
    ap.add_argument("--worker-address", default="http://localhost:21002")
    ap.add_argument("--host", default="localhost")
    ap.add_argument("--port", type=int, default=21002)
    ap.add_argument("--model-names", default=None)
    ap.add_argument("--embedder-path", default=None,
                    help="BERT-family checkpoint for /worker_get_embeddings")
    args = ap.parse_args()

    try:
        BigdlTpuWorker, app = _make_fastchat_worker()
    except ImportError as e:
        raise SystemExit(
            f"fastchat is not installed ({e}); the WorkerCore API is still "
            "usable programmatically") from e
    import uvicorn

    worker = BigdlTpuWorker(
        args.controller_address, args.worker_address,
        str(uuid.uuid4())[:8], args.model_path,
        (args.model_names or args.model_path).split(","), 5,
        low_bit=args.low_bit, embedder_path=args.embedder_path)
    uvicorn.run(app, host=args.host, port=args.port, log_level="info")


if __name__ == "__main__":
    main()
