"""Checksummed, versioned framing for fleet-internal wire payloads.

PR 9's KV-handoff payload was a bare JSON document: a bit-flipped
base64 body (a bad NIC, a proxy truncation, a version-skewed peer)
deserializes into *garbage KV* silently and the decode replica serves
wrong-but-plausible tokens at full speed. Every internal transfer —
``/v1/internal/kv_handoff`` and the live-migration
``/v1/internal/migrate_in`` — now travels inside one self-describing
frame::

    offset  size  field
    0       4     magic  b"BTW1"
    4       2     version (big-endian u16; this writer emits 1)
    6       4     CRC32 of the body (big-endian u32, zlib.crc32)
    10      8     body length (big-endian u64)
    18      n     body: UTF-8 JSON document

The receiver rejects a frame whose magic, version, length, or CRC
does not check out with a typed :class:`WireError` — the HTTP layer
turns that into a structured 400 counted in
``bigdl_tpu_handoff_rejects_total{reason}`` and the sender falls back
(local decode for handoff, local resume / journal replay for
migration). A legacy *unframed* JSON body is still accepted by the
servers for one version of mixed-fleet compatibility: frames start
with ``BTW``, JSON starts with ``{``, so the two are unambiguous.
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Any

MAGIC = b"BTW1"
WIRE_VERSION = 1
_HEADER = struct.Struct(">4sHIQ")      # magic, version, crc32, body len

#: reject reasons the metrics pre-label (render-before-first-reject)
REJECT_REASONS = ("magic", "version", "length", "crc", "json",
                  "too_large")


class WireError(ValueError):
    """A frame failed validation. ``reason`` is one of
    :data:`REJECT_REASONS` — it becomes the structured-400 body and
    the ``reason`` label on ``bigdl_tpu_handoff_rejects_total``."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"bad wire frame ({reason})"
                         + (f": {detail}" if detail else ""))
        self.reason = reason


def frame_payload(obj: Any) -> bytes:
    """Serialize ``obj`` (a JSON-able document) into one checksummed
    frame."""
    body = json.dumps(obj).encode("utf-8")
    return _HEADER.pack(MAGIC, WIRE_VERSION,
                        zlib.crc32(body) & 0xFFFFFFFF,
                        len(body)) + body


def is_framed(data: bytes) -> bool:
    """True when ``data`` starts like a frame (vs a legacy bare-JSON
    payload, which starts with ``{``)."""
    return data[:len(MAGIC)] == MAGIC


def unframe_payload(data: bytes) -> Any:
    """Validate one frame and return the decoded JSON document.
    Raises :class:`WireError` on any mismatch."""
    if len(data) < _HEADER.size:
        raise WireError("length",
                        f"{len(data)} bytes < {_HEADER.size}-byte header")
    magic, version, crc, blen = _HEADER.unpack_from(data)
    if magic != MAGIC:
        raise WireError("magic", repr(magic))
    if version != WIRE_VERSION:
        raise WireError("version",
                        f"got v{version}, this build speaks "
                        f"v{WIRE_VERSION}")
    body = data[_HEADER.size:]
    if len(body) != blen:
        raise WireError("length",
                        f"header says {blen} body bytes, got "
                        f"{len(body)}")
    if zlib.crc32(body) & 0xFFFFFFFF != crc:
        raise WireError("crc", "checksum mismatch")
    try:
        return json.loads(body)
    except ValueError as e:
        raise WireError("json", str(e)[:120]) from e


def corrupt_frame(data: bytes) -> bytes:
    """Deterministically flip one bit in the frame BODY (fault
    injection: ``migration_corrupt``). The receiver's CRC check must
    catch it; flipping a body bit rather than a header bit exercises
    the checksum, not the cheap structural validation."""
    if len(data) <= _HEADER.size:
        return data[:-1] + bytes([data[-1] ^ 0x01]) if data else data
    i = _HEADER.size + (len(data) - _HEADER.size) // 2
    return data[:i] + bytes([data[i] ^ 0x01]) + data[i + 1:]
